"""Pure state-machine transition functions for jobs and instances.

These mirror the reference's transactional Datomic db-fns
(reference: schema.clj :instance/update-state :1242-1308 and
:job/update-state :1202-1239) as pure functions over entity values.  The
store applies them inside a transaction so the "txn aborts if state moved"
discipline is preserved (SURVEY.md section 5, race handling #4).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from .schema import (
    Instance,
    InstanceStatus,
    Job,
    JobState,
    Reasons,
)

# Legal instance transitions (reference: schema.clj:1242-1308). A transition
# request to the current state is a no-op; anything not listed is rejected.
_INSTANCE_TRANSITIONS = {
    InstanceStatus.UNKNOWN: {InstanceStatus.RUNNING, InstanceStatus.SUCCESS, InstanceStatus.FAILED},
    InstanceStatus.RUNNING: {InstanceStatus.SUCCESS, InstanceStatus.FAILED},
    InstanceStatus.SUCCESS: set(),
    InstanceStatus.FAILED: set(),
}


def instance_transition_allowed(cur: InstanceStatus, new: InstanceStatus) -> bool:
    return new is cur or new in _INSTANCE_TRANSITIONS[cur]


def next_job_state(
    job: Job,
    instances: Dict[str, Instance],
) -> Tuple[JobState, Optional[str]]:
    """Recompute job state from its instances.

    Returns (state, reason) where reason explains a COMPLETED verdict.
    Mirrors :job/update-state (schema.clj:1202-1239):
      - any live (unknown/running) instance  -> RUNNING
      - a successful instance                -> COMPLETED
      - all attempts consumed                -> COMPLETED
      - user killed the job                  -> COMPLETED
      - otherwise                            -> WAITING (retry)
    """
    if job.user_killed:
        return JobState.COMPLETED, "user-killed"
    success = False
    live = False
    for tid in job.instances:
        inst = instances.get(tid)
        if inst is None:
            continue
        if inst.status is InstanceStatus.SUCCESS:
            success = True
        elif inst.status in (InstanceStatus.UNKNOWN, InstanceStatus.RUNNING):
            live = True
    if success:
        return JobState.COMPLETED, "success"
    if live:
        return JobState.RUNNING, None
    if job.attempts_used(instances) >= job.max_retries:
        return JobState.COMPLETED, "attempts-consumed"
    return JobState.WAITING, None


def allowed_to_start(job: Job, instances: Dict[str, Instance]) -> Optional[str]:
    """Launch guard (reference: :job/allowed-to-start? schema.clj:1311-1325).

    Returns None when the job may start a new instance, else a rejection
    reason string.  Applied inside the launch transaction so a concurrent
    kill/complete aborts the launch (scheduler.clj:987-1009 invariant).
    """
    if job.state is not JobState.WAITING:
        return f"job-state-{job.state.value}"
    if not job.committed:
        return "uncommitted"
    for tid in job.instances:
        inst = instances.get(tid)
        if inst is not None and inst.status in (InstanceStatus.UNKNOWN, InstanceStatus.RUNNING):
            return "has-live-instance"
    return None


def classify_failure(reason_code: Optional[int]) -> Tuple[bool, Optional[int]]:
    """Return (mea_culpa?, failure_limit) for a failure reason code."""
    reason = Reasons.by_code(reason_code if reason_code is not None else Reasons.UNKNOWN.code)
    return reason.mea_culpa, reason.failure_limit


def gang_failure_action(group, reason_code: Optional[int],
                        failed_member_state: JobState,
                        live_members: Optional[int] = None) -> str:
    """What the gang policy does when one member's instance fails
    (docs/GANG.md).  Pure so the scheduler's tx-event handler stays a
    thin dispatcher.

    Returns one of:

    - ``"none"`` — not a gang, or the failure IS a gang-policy kill
      (``gang-member-lost``) or an elastic resize shrink
      (``gang-resized``): reacting to our own kills would cascade;
      also chosen for an ELASTIC gang that still holds ``gang_min``
      live members after the failure (``live_members``, counted by the
      caller post-transition) — the gang absorbs the loss as a shrink
      instead of tearing down work that is legal at its current size;
    - ``"requeue"`` — kill the gang's other live instances mea-culpa
      (``gang-member-lost``) so the whole gang returns to WAITING and
      relaunches atomically (the default policy);
    - ``"kill"`` — kill the whole gang's jobs outright.  Chosen when the
      group's policy says so, and FORCED when the failed member's job
      went terminal (retries exhausted, user kill): its siblings could
      otherwise wait forever on a gang that can never be whole again
      (elastic gangs still above ``gang_min`` excepted — they run on
      legally without the terminal member).
    """
    from .schema import GANG_POLICY_KILL, gang_bounds, gang_is_elastic
    if group is None or not getattr(group, "gang", False):
        return "none"
    if reason_code in (Reasons.GANG_MEMBER_LOST.code,
                       Reasons.GANG_RESIZED.code):
        return "none"
    if gang_is_elastic(group) and live_members is not None \
            and live_members >= gang_bounds(group)[0]:
        return "none"
    if failed_member_state is JobState.COMPLETED:
        return "kill"
    if getattr(group, "gang_policy", "") == GANG_POLICY_KILL:
        return "kill"
    return "requeue"


def gang_status(store, group,
                cache: Optional[Dict[str, Dict]] = None) -> Dict:
    """Gang placement status computed from the store (docs/GANG.md):
    members placed (live instance) / running, and the barrier state —
    ``None`` until any member launches, ``"pending"`` while members are
    coming up, ``"released"`` once every member has STARTED: currently
    RUNNING, or completed after a run (a short member can exit SUCCESS
    before the last member comes up — requiring everyone simultaneously
    RUNNING would misreport such gangs as forever "pending"; this also
    makes a gang whose members all ran and finished stay "released").
    Derived on demand so it survives leader handoffs.  ``cache`` (group
    uuid -> status) lets batch queries compute each gang once instead
    of once per member job."""
    if cache is not None and group.uuid in cache:
        return cache[group.uuid]
    placed = running = started = 0
    for member_uuid in group.jobs:
        member = store.job(member_uuid)
        if member is None:
            continue
        insts = [i for t in member.instances
                 if (i := store.instance(t)) is not None]
        if any(i.status in (InstanceStatus.UNKNOWN,
                            InstanceStatus.RUNNING) for i in insts):
            placed += 1
        if any(i.status is InstanceStatus.RUNNING for i in insts):
            running += 1
            started += 1
        elif member.state is JobState.COMPLETED and any(
                # the member DID run at some point: SUCCESS, or a
                # terminal instance that reached RUNNING (start stamp)
                i.status is InstanceStatus.SUCCESS
                or i.mesos_start_time_ms for i in insts):
            started += 1
    from .schema import gang_bounds, gang_is_elastic
    size = group.gang_size or len(group.jobs)
    # elastic gangs make the barrier at gang_min STARTED members — the
    # gang is legally whole at any count in [min, max] (docs/GANG.md
    # elasticity); rigid gangs read lo == size, unchanged
    lo, hi = gang_bounds(group)
    barrier = None
    if started >= (lo or size):
        barrier = "released"
    elif placed:
        barrier = "pending"
    out = {"size": size,
           "topology": group.gang_topology,
           "policy": group.gang_policy,
           "members_placed": placed,
           "members_running": running,
           "barrier": barrier}
    if gang_is_elastic(group):
        out["min"] = lo
        out["max"] = hi
    if cache is not None:
        cache[group.uuid] = out
    return out
