"""WAL v2 integrity envelope: CRC32C-sealed, length-framed journal lines.

Every durability argument before this module rested on the torn-*tail*
excision discipline: a crash can only truncate the journal, so replay
stops at the first unparseable line and excises it.  ALICE (OSDI'14)
showed that crash-consistency protocols break at byte boundaries nobody
tested, and a mid-file bit-flip, a short write that still parses, or a
lying fsync ("Can Applications Recover from fsync Failures?", ATC'20)
all *pass* the torn-tail check while silently discarding every record
after the damage.  This module closes that hole:

- :func:`seal_record` wraps one journal record in a **v2 frame**::

      v2 <payload-bytes> <crc32c-hex> <json-payload>\\n

  The length field makes a short write detectable even when the
  truncated JSON happens to parse; the CRC32C (Castagnoli, the iSCSI /
  ext4 / Btrfs polynomial) catches bit rot.  ``json.dumps`` never emits
  raw newlines, so the one-line-per-record journal shape (and every
  newline-based offset scan, e.g. replication's
  ``_trimmed_journal_bytes``) is unchanged.

- :func:`scan_journal` replays a journal distinguishing **torn tail**
  (an incomplete final frame — excise, exactly as before) from
  **mid-file corruption** (a complete-but-invalid frame, or garbage
  with valid records after it — refuse and report, never silently
  truncate committed records).  Legacy v1 plain-JSON lines still parse,
  so journals and mirrors written before this module replay unchanged.

- :func:`write_manifest` / :func:`verify_snapshot` give checkpoints a
  checksummed manifest (``snapshot.manifest.json``) verified at load;
  a mismatch falls back to the previous checkpoint + its rotated
  journal (``Store.checkpoint`` keeps ``snapshot.prev.json`` /
  ``journal.prev.jsonl`` for exactly this).

- :func:`hygiene_sweep` unlinks crash-orphaned ``.tmp.`` atomic-write
  leftovers and stale poison markers at ``Store.open`` — a SIGKILL
  mid-publish used to leave them forever.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from ..utils.metrics import registry

#: v2 frame marker.  A v1 record is a bare JSON object line, so the
#: first byte of every legacy record is ``{`` — the ``v2 `` prefix can
#: never collide with one.
V2_PREFIX = b"v2 "

#: minimum age before the boot-time hygiene sweep unlinks an orphaned
#: temp/marker: a LIVE writer's in-flight ``.tmp.`` must survive a
#: concurrent open of a shared dir (config.StorageConfig overrides).
HYGIENE_MIN_AGE_S = 60.0


def _make_crc32c_table() -> List[int]:
    # reflected Castagnoli polynomial (0x1EDC6F41 bit-reversed)
    poly = 0x82F63B78
    table = []
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        table.append(c)
    return table


_CRC32C_TABLE = _make_crc32c_table()


def _crc32c_py(data: bytes, crc: int = 0) -> int:
    table = _CRC32C_TABLE
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


try:  # native Castagnoli when the wheel is present (~800x the pure-
    # Python table loop — the journal append and scrub paths CRC every
    # payload byte, so this is worth a soft dependency)
    from google_crc32c import extend as _crc32c_extend

    def crc32c(data: bytes, crc: int = 0) -> int:
        """CRC-32C (Castagnoli) of ``data``, optionally continuing a
        running checksum ``crc``."""
        return _crc32c_extend(crc, bytes(data))
except ImportError:  # pragma: no cover — exercised via _crc32c_py tests
    crc32c = _crc32c_py


def seal_record(rec: Dict[str, Any]) -> str:
    """Serialize one journal record into its checksummed v2 frame (the
    ONE blessed appender — the ``cs lint`` journal-raw-write pass
    rejects journal writes that bypass it)."""
    payload = json.dumps(rec)
    data = payload.encode("utf-8")
    return f"v2 {len(data)} {crc32c(data):08x} {payload}\n"


class FrameError(ValueError):
    """One journal line failed to parse.  ``complete`` distinguishes the
    two causes replay must treat differently: an INCOMPLETE frame (short
    payload, truncated header — the shape a torn write produces) may be
    excised when it is the file's final line; a COMPLETE frame whose CRC
    or length check fails can only be corruption (torn writes produce
    prefixes, and a prefix never carries the full declared payload), so
    it is corruption even at the tail."""

    def __init__(self, reason: str, complete: bool):
        super().__init__(reason)
        self.complete = complete


def parse_journal_line(text: bytes) -> Dict[str, Any]:
    """Parse one stripped journal line (v2 sealed frame or legacy v1
    bare JSON) into its record dict.  Raises :class:`FrameError`."""
    if text.startswith(V2_PREFIX):
        parts = text.split(b" ", 3)
        if len(parts) < 4:
            raise FrameError("v2 frame header truncated", complete=False)
        _, length_b, crc_b, payload = parts
        try:
            length = int(length_b)
            crc = int(crc_b, 16)
        except ValueError:
            raise FrameError("v2 frame header unparseable",
                             complete=False) from None
        if len(payload) < length:
            raise FrameError(
                f"v2 frame short: {len(payload)} < declared {length}",
                complete=False)
        if len(payload) > length:
            raise FrameError(
                f"v2 frame long: {len(payload)} > declared {length}",
                complete=True)
        actual = crc32c(payload)
        if actual != crc:
            raise FrameError(
                f"v2 frame crc mismatch: {actual:08x} != {crc:08x}",
                complete=True)
        try:
            return json.loads(payload)
        except json.JSONDecodeError as e:
            # crc passed but json failed: the frame was SEALED that way,
            # i.e. a writer bug, not disk damage — still refuse loudly
            raise FrameError(f"v2 payload unparseable: {e}",
                             complete=True) from None
    try:
        return json.loads(text)
    except json.JSONDecodeError as e:
        # a v1 line carries no frame, so parse failure cannot tell torn
        # from flipped — mid-file position (the caller's call) is the
        # only disambiguator
        raise FrameError(f"v1 record unparseable: {e}",
                         complete=False) from None


class JournalCorruptionError(RuntimeError):
    """Mid-file (or complete-frame) journal damage: replay refuses to
    silently truncate committed records after the damage point.  The
    repair path (state/repair.py) pulls the range from a synced peer;
    docs/DEPLOY.md carries the operator runbook."""

    def __init__(self, path: str, offset: int, reason: str):
        super().__init__(
            f"journal corruption in {path} at byte {offset}: {reason}")
        self.path = path
        self.offset = offset
        self.reason = reason


class ScanResult:
    """:func:`scan_journal`'s outcome.  Iterable as the legacy
    ``(records, good, size)`` triple so existing unpack sites and tests
    keep working; ``corrupt_offset``/``reason`` carry the new verdict."""

    __slots__ = ("records", "good", "size", "corrupt_offset", "reason")

    def __init__(self, records: List[Dict[str, Any]], good: int,
                 size: int, corrupt_offset: Optional[int] = None,
                 reason: str = ""):
        self.records = records
        self.good = good
        self.size = size
        self.corrupt_offset = corrupt_offset
        self.reason = reason

    @property
    def corrupt(self) -> bool:
        return self.corrupt_offset is not None

    def __iter__(self):
        yield self.records
        yield self.good
        yield self.size


def scan_journal(path: str) -> ScanResult:
    """Parse a journal file (v1 and v2 records interleaved) into
    records.  ``good`` marks the byte offset after the last valid
    record.  Verdicts:

    - a final line that is an INCOMPLETE frame (no trailing newline, or
      a v2 frame shorter than its declared length, or unparseable v1
      JSON) is a **torn tail**: records stop there, ``corrupt`` is
      False — the caller excises it exactly as before this module;
    - an invalid line with MORE lines after it, or a COMPLETE v2 frame
      whose CRC/length check fails (even at the tail — torn writes only
      produce prefixes), is **corruption**: ``corrupt_offset`` marks
      the damage and the caller must refuse-and-repair, never silently
      truncate the committed records beyond it."""
    if not os.path.exists(path):
        return ScanResult([], 0, 0)
    with open(path, "rb") as f:
        data = f.read()
    records: List[Dict[str, Any]] = []
    good = 0
    lines = data.splitlines(keepends=True)
    for i, line in enumerate(lines):
        if not line.endswith(b"\n"):
            break  # torn tail: a crash mid-append
        text = line.strip()
        if text:
            try:
                records.append(parse_journal_line(text))
            except FrameError as e:
                if e.complete or i < len(lines) - 1:
                    return ScanResult(records, good, len(data),
                                      corrupt_offset=good, reason=str(e))
                break  # incomplete final frame: torn tail
        good += len(line)
    return ScanResult(records, good, len(data))


def verify_window(path: str, offset: int, max_bytes: int
                  ) -> ScanResult:
    """Incremental frame verification for the background scrub: check
    the journal window ``[offset, offset+max_bytes)`` line by line
    without materializing records.  Returns a :class:`ScanResult` whose
    ``records`` list is empty, ``good`` is the verified offset (never
    past an incomplete tail frame — the live appender finishes it), and
    ``corrupt_offset`` marks damage exactly as :func:`scan_journal`.
    ``size`` is the file size at read time."""
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            f.seek(offset)
            data = f.read(max_bytes)
    except OSError:
        return ScanResult([], offset, 0)
    good = offset
    lines = data.splitlines(keepends=True)
    for i, line in enumerate(lines):
        if not line.endswith(b"\n"):
            break  # window or file ends mid-frame: verify next pass
        text = line.strip()
        if text:
            try:
                parse_journal_line(text)
            except FrameError as e:
                at_eof = good + len(line) >= size
                if e.complete or i < len(lines) - 1 or not at_eof:
                    return ScanResult([], good, size,
                                      corrupt_offset=good, reason=str(e))
                break  # incomplete tail frame mid-append
        good += len(line)
    return ScanResult([], good, size)


# --------------------------------------------------------------- manifest
def manifest_path(snap_path: str) -> str:
    base = snap_path[:-len(".json")] if snap_path.endswith(".json") \
        else snap_path
    return base + ".manifest.json"


def write_manifest(snap_path: str, text: str) -> None:
    """Record the checkpoint snapshot's size + CRC32C beside it
    (``snapshot.manifest.json``), atomically.  The manifest is written
    AFTER the snapshot: a crash between the two leaves a manifest that
    describes the previous snapshot, which fails verification and falls
    back to the previous-checkpoint chain — a correct (idempotent
    re-replay) state, never a silently wrong one."""
    from ..utils.fsatomic import write_atomic_text
    data = text.encode("utf-8")
    write_atomic_text(manifest_path(snap_path), json.dumps(
        {"size": len(data), "crc32c": f"{crc32c(data):08x}"}))


def verify_snapshot(snap_path: str) -> Optional[bool]:
    """Check ``snap_path`` against its manifest.  True = verified,
    False = mismatch (fall back), None = no manifest (a legacy dir or a
    replication mirror — the manifest is node-local — loads unverified,
    exactly as before this module)."""
    mpath = manifest_path(snap_path)
    try:
        with open(mpath, encoding="utf-8") as f:
            man = json.loads(f.read())
    except (OSError, json.JSONDecodeError):
        return None
    try:
        with open(snap_path, "rb") as f:
            data = f.read()
    except OSError:
        return False
    return (len(data) == int(man.get("size", -1))
            and f"{crc32c(data):08x}" == str(man.get("crc32c")))


# ---------------------------------------------------------------- hygiene
#: poison/staleness markers the sweep may clear once they are old: a
#: mirror's corruption marker survives the repair that obsoleted it
#: only until the next store/view open.
_SWEEPABLE_MARKERS = ("mirror_poisoned",)


def hygiene_sweep(directory: str,
                  min_age_s: Optional[float] = None) -> int:
    """Unlink crash-orphaned atomic-write temps (dot-prefixed,
    ``.tmp.``-infixed — utils/fsatomic.py's writer-unique naming) and
    stale poison markers in ``directory``.  Only entries older than
    ``min_age_s`` go: a live writer's in-flight temp in a shared dir
    must survive.  Returns the count, also published as
    ``cook_storage_hygiene_removed_total``."""
    if min_age_s is None:
        min_age_s = HYGIENE_MIN_AGE_S
    removed = 0
    try:
        names = os.listdir(directory)
    except OSError:
        return 0
    now = time.time()
    for name in names:
        orphan_tmp = name.startswith(".") and ".tmp." in name
        if not (orphan_tmp or name in _SWEEPABLE_MARKERS):
            continue
        p = os.path.join(directory, name)
        try:
            if now - os.stat(p).st_mtime < min_age_s:
                continue
            os.unlink(p)
            removed += 1
        except OSError:
            continue
    if removed:
        registry.counter_inc("cook_storage_hygiene_removed",
                             value=float(removed))
    return removed
