"""Follower read fleet: a LIVE read-only store over a replication mirror.

PR 3's :class:`~cook_tpu.state.replication.ReplicationFollower` mirrors
the leader's journal BYTES into a local directory — byte-identical, but
inert: the standby could promote, yet served nothing.  This module
promotes the mirror to a live store (the ZooKeeper observer / non-voting
read replica shape, Hunt et al., USENIX ATC'10): a
:class:`FollowerReadView` tails the mirrored ``journal.jsonl`` and feeds
each record through the store's own replay path
(:meth:`Store._apply_journal_record`, with the same epoch-fence skipping
as :meth:`Store._replay_records`) into a local read-only :class:`Store`
the follower's REST layer serves GETs from.

The staleness contract (docs/DEPLOY.md):

- every follower-served response carries ``X-Cook-Replication-Offset``
  (applied journal bytes) and ``X-Cook-Replication-Age-Ms`` (an upper
  bound on how long the view has been behind its mirror);
- writes keep 307-redirecting to the leader, whose write responses carry
  ``X-Cook-Commit-Offset``;
- read-your-writes: a client threads its last commit offset back as
  ``X-Cook-Min-Offset``; a behind follower waits briefly
  (:meth:`wait_offset`), then redirects the read to the leader.

The mirror can be RE-BASED underneath the view (leader checkpoint →
full resync: new snapshot + fresh journal, new ``repl_token``): the view
detects the base change and rebuilds its store wholesale, swapping it
atomically and notifying ``on_swap`` subscribers (the REST layer points
``api.store`` at the fresh object).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..utils.locks import named_lock
from ..utils.metrics import registry
from .integrity import FrameError, parse_journal_line
from .store import Store, _scan_journal


def _read_text(path: str) -> str:
    try:
        with open(path, encoding="utf-8") as f:
            return f.read()
    except OSError:
        return ""


class FollowerReadView:
    """Tail a mirror directory into a live read-only :class:`Store`.

    Thread-safe for readers: queries go through the store's own lock,
    and the apply loop installs record batches under that same lock.
    ``store`` is replaced wholesale only on a mirror re-base; consumers
    that cache the reference subscribe via ``on_swap``."""

    def __init__(self, directory: str, interval_s: float = 0.02,
                 on_swap: Optional[Callable[[Store], None]] = None,
                 start: bool = True,
                 partition_id: Optional[int] = None):
        self.directory = str(directory)
        self.interval_s = max(float(interval_s), 0.001)
        #: partition this view mirrors in a partitioned write plane
        #: (state/partition.py): the replica store carries the id (lock
        #: family, metric labels) and the token wait-gate satisfies only
        #: entries QUALIFIED with this partition — an offset from a
        #: sibling partition's journal proves nothing here.  None = the
        #: classic single-journal plane.
        self.partition_id = partition_id
        self._on_swap: List[Callable[[Store], None]] = []
        if on_swap is not None:
            self._on_swap.append(on_swap)
        self._journal = os.path.join(self.directory, "journal.jsonl")
        self._stop = threading.Event()
        # ranks BELOW "store" (utils/locks.py): _rebuild holds _mu while
        # replaying into the fresh store under that store's own lock
        self._mu = named_lock("read_replica")
        # staleness bookkeeping
        self.applied_records = 0
        self.rebuilds = 0
        self._caught_up_ts = time.time()
        self._offset_cv = threading.Condition()
        self.store: Store = Store(partition=partition_id)
        self._offset = 0
        self._max_ep = 0
        self._base_sig: Any = None
        #: non-None when the mirror bytes failed frame verification
        #: (``{"offset", "reason"}``): the view STOPS advancing and
        #: serves only the verified prefix until repair_from_peer (or a
        #: clean re-base) heals the mirror — poisoned state is never
        #: served as fresh
        self.corrupt: Optional[Dict[str, Any]] = None
        self._rebuild()
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(
                target=self._apply_loop, daemon=True,
                name="cook-follower-apply")
            self._thread.start()

    # ---------------------------------------------------------------- state
    @property
    def offset(self) -> int:
        """Applied journal bytes (whole records only) — the follower's
        serving position, returned as X-Cook-Replication-Offset."""
        return self._offset

    def mirror_offset(self) -> int:
        """Raw mirrored journal bytes on disk (the native follower's
        write position) — the local apply target."""
        try:
            return os.path.getsize(self._journal)
        except OSError:
            return 0

    def lag_bytes(self) -> int:
        """Mirrored-but-unapplied bytes.  The mirror itself is pushed by
        the leader's stream, so this approximates 'behind the leader by N
        bytes' up to one network round."""
        return max(0, self.mirror_offset() - self._offset)

    def age_ms(self) -> float:
        """Upper bound on staleness: ~0 while the view keeps catching
        its mirror's head every tick, else time since it last did."""
        return max(0.0, (time.time() - self._caught_up_ts) * 1000.0)

    def stats(self) -> Dict[str, Any]:
        return {"offset": self._offset,
                "mirror_offset": self.mirror_offset(),
                "lag_bytes": self.lag_bytes(),
                "age_ms": round(self.age_ms(), 1),
                "applied_records": self.applied_records,
                "rebuilds": self.rebuilds,
                **({"corrupt": self.corrupt} if self.corrupt else {}),
                **({"partition": f"p{self.partition_id}"}
                   if self.partition_id is not None else {})}

    def on_swap(self, fn: Callable[[Store], None]) -> None:
        self._on_swap.append(fn)
        fn(self.store)

    @property
    def applied_epoch(self) -> int:
        """Highest election epoch applied from the mirror — qualifies
        the offset space a read-your-writes token compares against."""
        return self._max_ep

    def _satisfies(self, epoch: Optional[int], offset: int) -> bool:
        """Does the view's position cover a ``<epoch>:<offset>`` token?
        A HIGHER applied epoch covers any lower-epoch token outright
        (every determinate commit survives into later epochs' journals
        by the no-loss guarantee); the same epoch compares offsets; a
        lower applied epoch means this mirror is still in a previous
        leadership's offset space — its numerically-larger byte count
        proves nothing about the token's commit."""
        if epoch is None:
            return self._offset >= offset
        if self._max_ep != epoch:
            return self._max_ep > epoch
        return self._offset >= offset

    def wait_token(self, epoch: Optional[int], offset: int,
                   timeout_s: float = 1.0) -> bool:
        """Read-your-writes gate: block until the token's position is
        APPLIED (not merely mirrored).  False on timeout — the caller
        redirects the read to the leader."""
        deadline = time.time() + max(timeout_s, 0.0)
        with self._offset_cv:
            while not self._satisfies(epoch, offset):
                remaining = deadline - time.time()
                if remaining <= 0:
                    return self._satisfies(epoch, offset)
                self._offset_cv.wait(min(remaining, 0.05))
        return True

    def wait_offset(self, offset: int, timeout_s: float = 1.0) -> bool:
        """Offset-only form of :meth:`wait_token`."""
        return self.wait_token(None, offset, timeout_s=timeout_s)

    def wait_commit_token(self, token: str, timeout_s: float = 1.0
                          ) -> bool:
        """Vector-aware read-your-writes gate (the partitioned plane's
        X-Cook-Min-Offset form, state/partition.py):

        - an entry qualified with THIS view's partition waits like
          :meth:`wait_token`;
        - an entry for a SIBLING partition with bytes committed cannot
          be verified against this mirror (its offsets live in another
          journal's space) — False, the caller redirects to the leader;
          a zero-offset sibling entry is vacuously satisfied;
        - a partitionless (legacy) entry is satisfiable only by a
          partitionless view, and vice versa — an unqualified offset
          does not name which journal it measures.

        Raises ValueError on garbage (callers surface 400)."""
        from .partition import parse_token_vector
        entries = parse_token_vector(token)
        deadline = time.time() + max(timeout_s, 0.0)
        for part, ep, off in entries:
            if part is None:
                if self.partition_id is not None:
                    return False
            elif self.partition_id is None:
                return False
            elif part != self.partition_id:
                if off > 0:
                    return False
                continue
            remaining = max(deadline - time.time(), 0.0)
            if not self.wait_token(ep, off, timeout_s=remaining):
                return False
        return True

    # ---------------------------------------------------------------- apply
    def _base_signature(self) -> Any:
        """Identity of the mirror BASE: the follower's resync token plus
        the snapshot's stat — either changing means the journal byte
        space re-based (full resync after a leader checkpoint / a new
        leader's mirror) and incremental offsets are meaningless."""
        token = _read_text(os.path.join(self.directory, "repl_token"))
        try:
            st = os.stat(os.path.join(self.directory, "snapshot.json"))
            snap_sig = (st.st_mtime_ns, st.st_size)
        except OSError:
            snap_sig = None
        return (token, snap_sig)

    def _mark_corrupt(self, offset: int, reason: str) -> None:
        """First sighting of mirror damage: remember it (the view stops
        advancing and keeps serving the verified prefix), count it, and
        drop a ``mirror_poisoned`` marker so the daemon's health surface
        and the boot hygiene sweep can see it across restarts."""
        if self.corrupt is not None:
            return
        self.corrupt = {"offset": offset, "reason": reason}
        registry.counter_inc("cook_journal_corruption",
                             labels={"source": "mirror"})
        try:
            with open(os.path.join(self.directory, "mirror_poisoned"),
                      "w", encoding="utf-8") as f:
                f.write(f"{offset} {reason}\n")
        except OSError:
            pass

    def _clear_corrupt(self) -> None:
        if self.corrupt is None:
            return
        self.corrupt = None
        try:
            os.unlink(os.path.join(self.directory, "mirror_poisoned"))
        except OSError:
            pass

    def _rebuild(self) -> None:
        """Full rebuild from snapshot + journal (the Store.replay_only
        shape, with the epoch high-water mark kept for later incremental
        applies).  A mirror whose journal fails frame verification
        rebuilds to the verified PREFIX and marks itself corrupt — the
        re-base path is also how a repaired mirror (new repl_token)
        comes back clean."""
        with self._mu:
            self._base_sig = self._base_signature()
            snap = os.path.join(self.directory, "snapshot.json")
            store = (Store.restore(_read_text(snap),
                                   partition=self.partition_id)
                     if os.path.exists(snap)
                     else Store(partition=self.partition_id))
            scan = _scan_journal(self._journal)
            records, good = scan.records, scan.good
            max_ep = store._replay_records(records)
            swapped = store is not self.store
            self.store = store
            self._max_ep = max_ep
            with self._offset_cv:
                self._offset = good
                self._offset_cv.notify_all()
            self.rebuilds += 1
            self._caught_up_ts = time.time()
            if scan.corrupt:
                self._mark_corrupt(scan.corrupt_offset or good,
                                   scan.reason)
            else:
                self._clear_corrupt()
        if swapped:
            for fn in self._on_swap:
                fn(store)

    def poll(self) -> int:
        """One apply tick (also the test hook): detect re-base, else
        parse and apply the mirrored records beyond the applied offset.
        Returns the number of records applied (rebuilds count as 0)."""
        sig = self._base_signature()
        size = self.mirror_offset()
        if sig != self._base_sig or size < self._offset:
            self._rebuild()
            return 0
        if self.corrupt is not None:
            # poisoned mirror: hold the verified prefix and wait for a
            # re-base (repair_from_peer writes a new repl_token, which
            # the sig check above turns into a clean rebuild) — applying
            # past the damage would serve records whose provenance the
            # CRC just disproved
            return 0
        if size <= self._offset:
            self._caught_up_ts = time.time()
            return 0
        try:
            with open(self._journal, "rb") as f:
                f.seek(self._offset)
                data = f.read(size - self._offset)
        except OSError:
            return 0
        applied = 0
        good = self._offset
        recs: List[Dict[str, Any]] = []
        for line in data.splitlines(keepends=True):
            if not line.endswith(b"\n"):
                break  # torn tail: the mirror is mid-record
            text = line.strip()
            if text:
                try:
                    recs.append(parse_journal_line(text))
                except FrameError as e:
                    # a COMPLETE line that fails frame verification is
                    # mirror corruption, not a mid-append race: the
                    # native follower only splits lines mid-frame
                    # (before the newline), and those park on the
                    # endswith check above
                    self._mark_corrupt(good, str(e))
                    break
            good += len(line)
        store = self.store
        if recs:
            # the store's own replay owns the epoch-fence skip rule;
            # applied under the store lock so concurrent REST readers
            # see whole records
            with store._lock:
                self._max_ep = store._replay_records(recs, self._max_ep)
            applied = len(recs)
        self.applied_records += applied
        with self._offset_cv:
            self._offset = good
            self._offset_cv.notify_all()
        if good >= size:
            # caught the head AS OF this tick's start: staleness is
            # bounded by one poll interval.  Comparing against the LIVE
            # mirror head instead would never reset under a sustained
            # write stream (the mirror always advances during the
            # apply), ratcheting the reported age far above the real
            # one-tick lag.
            self._caught_up_ts = time.time()
        return applied

    def repair_from_peer(self, host: str, port: int,
                         timeout_s: float = 30.0) -> bool:
        """Heal a corrupt mirror by pulling a fresh full resync from a
        synced peer over the PR 3 framed-TCP catch-up carrier
        (:func:`cook_tpu.state.replication.catch_up_from_peer`).  The
        damaged journal is quarantined as ``journal.jsonl.corrupt``
        (forensics; docs/DEPLOY.md runbook) and the resync markers are
        cleared so the transfer starts from the peer's snapshot.  The
        resync mints a NEW ``repl_token`` — the next poll sees the base
        change and rebuilds the view from the healed bytes, clearing the
        poisoned state.  The caller must ensure the native follower that
        normally feeds this mirror is detached for the duration: two
        writers on one mirror directory is never safe."""
        from .replication import catch_up_from_peer
        d = self.directory
        try:
            os.replace(os.path.join(d, "journal.jsonl"),
                       os.path.join(d, "journal.jsonl.corrupt"))
        except OSError:
            pass
        for marker in ("repl_token", "repl_synced", "repl_following"):
            try:
                os.unlink(os.path.join(d, marker))
            except OSError:
                pass
        ok = catch_up_from_peer(host, int(port), d, 0,
                                timeout_s=timeout_s)
        if ok:
            registry.counter_inc("cook_storage_repair",
                                 labels={"kind": "peer"})
            self._rebuild()
        return ok

    def _apply_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll()
            except Exception:
                # the view must never die silently — a transient read
                # race with the native mirror writer resolves next tick
                pass
            self._stop.wait(self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
