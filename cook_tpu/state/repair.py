"""Repair a damaged persistence directory from a synced replication peer.

:func:`Store.open <cook_tpu.state.store.Store.open>` REFUSES a journal
with mid-file corruption (a complete frame whose CRC32C fails, or
garbage with valid records after it) instead of silently truncating the
committed records beyond the damage — see state/integrity.py.  This
module is the other half of that contract: the records the local disk
lost are still byte-identical on every synced mirror (PR 3's framed-TCP
replication fsyncs whole frames), so healing is a pull, not a guess.

The flow (docs/DEPLOY.md corrupted-journal runbook):

1. quarantine the damaged files (``journal.jsonl.corrupt`` /
   ``snapshot.json.corrupt`` — kept for forensics, out of replay's way);
2. full-resync from the most-advanced synced peer over the existing
   catch-up carrier (:func:`~cook_tpu.state.replication.
   catch_up_from_peer` — Viewstamped Replication's view-change state
   transfer);
3. reopen: the pulled snapshot + journal replay verifies clean.

Mirror-side healing lives on the view itself
(:meth:`~cook_tpu.state.read_replica.FollowerReadView.repair_from_peer`),
because the view must also re-base off the poisoned store.
"""

from __future__ import annotations

import os
from typing import Any, Iterable, Optional, Tuple

from ..utils.metrics import registry
from .integrity import JournalCorruptionError
from .store import Store

#: quarantine suffix for damaged persistence files — never parsed by
#: any replay path, swept only by operators
CORRUPT_SUFFIX = ".corrupt"


def quarantine(directory: str) -> None:
    """Move the damaged generation out of replay's way (journal,
    snapshot + manifest, prev chain, resync markers), keeping the bytes
    under ``*.corrupt`` names for forensics.  After this the directory
    is a blank slate a peer resync can safely fill."""
    for name in ("journal.jsonl", "journal.prev.jsonl",
                 "snapshot.json", "snapshot.manifest.json",
                 "snapshot.prev.json", "snapshot.prev.manifest.json"):
        src = os.path.join(directory, name)
        try:
            if os.path.exists(src):
                os.replace(src, src + CORRUPT_SUFFIX)
        except OSError:
            pass
    # a stale resync identity would make the follower transfer resume
    # instead of full-resyncing onto the blank slate
    for marker in ("repl_token", "repl_synced", "repl_following",
                   "mirror_poisoned"):
        try:
            os.unlink(os.path.join(directory, marker))
        except OSError:
            pass


def repair_from_peers(directory: str,
                      peers: Iterable[Tuple[str, int]],
                      timeout_s: float = 30.0) -> bool:
    """Quarantine ``directory`` and pull a full resync from the first
    reachable peer (callers order ``peers`` most-advanced first — the
    election medium's candidate positions under
    :func:`~cook_tpu.state.replication.rank_key` give that order).
    True once a peer's transfer reached its head (the synced marker)."""
    quarantine(directory)
    for host, port in peers:
        try:
            from .replication import catch_up_from_peer
            if catch_up_from_peer(host, int(port), directory, 0,
                                  timeout_s=timeout_s):
                registry.counter_inc("cook_storage_repair",
                                     labels={"kind": "peer"})
                return True
        except Exception:
            continue  # dead peer: the next-ranked one may still serve
    return False


def open_with_repair(directory: str,
                     peers: Iterable[Tuple[str, int]] = (),
                     fsync: bool = False,
                     epoch: Optional[Any] = None,
                     shared: bool = True,
                     partition: Optional[int] = None,
                     timeout_s: float = 30.0) -> Store:
    """:meth:`Store.open` with the repair path armed: a
    :class:`JournalCorruptionError` at replay triggers one
    quarantine-and-pull round from ``peers`` before reopening.  With no
    peers (or none reachable) the corruption error propagates — silent
    truncation is exactly what this subsystem exists to forbid."""
    try:
        return Store.open(directory, fsync=fsync, epoch=epoch,
                          shared=shared, partition=partition)
    except JournalCorruptionError:
        peers = list(peers)
        if not peers or not repair_from_peers(directory, peers,
                                              timeout_s=timeout_s):
            raise
        return Store.open(directory, fsync=fsync, epoch=epoch,
                          shared=shared, partition=partition)
