"""Socket journal replication: ctypes surface over ``native/repl.cpp``.

The reference framework's durable state is an out-of-process NETWORKED
store (Datomic — ``/root/reference/scheduler/src/cook/datomic.clj:79``), so
its leader failover works from any host: the new leader just re-reads
(``/root/reference/scheduler/src/cook/mesos.clj:153-328``).  cook_tpu's
:class:`~cook_tpu.state.store.Store` journals to a local directory; this
module streams that journal (and its compaction snapshots) to follower
processes over framed TCP so a follower holds a byte-identical mirror in
its OWN directory — no shared filesystem — and can promote with zero lost
committed transactions.

Roles:

- :class:`ReplicationServer` — runs in the leader next to an open store;
  tails ``<dir>/journal.jsonl``.  ``wait_acked(offset)`` blocks until every
  connected follower has fsynced through ``offset`` (sync replication: the
  store calls it per commit via ``Store.attach_replication``).
- :class:`ReplicationFollower` — runs in a standby; mirrors the leader's
  snapshot + journal bytes into a separate local directory.  Promotion is
  ``Store.open(local_dir, epoch=...)`` on that mirror; the journal records
  carry their election epochs, so the store's existing stale-epoch replay
  skipping applies unchanged.
"""

from __future__ import annotations

import ctypes
import threading
import time
from pathlib import Path
from typing import Optional

_NATIVE = Path(__file__).resolve().parent.parent.parent / "native"
_SRC = _NATIVE / "repl.cpp"
_LIB = _NATIVE / "build" / "libcookrepl.so"

_lib_handle = None
_lib_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib_handle, _lib_tried
    if _lib_tried:
        return _lib_handle
    _lib_tried = True
    from ..native.build import build_if_stale
    if build_if_stale([_SRC, _NATIVE / "framing.h"], _LIB,
                      ["-shared", "-fPIC"]) is None:
        return None
    lib = ctypes.CDLL(str(_LIB))
    lib.crp_serve.restype = ctypes.c_void_p
    lib.crp_serve.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.crp_port.argtypes = [ctypes.c_void_p]
    lib.crp_follower_count.argtypes = [ctypes.c_void_p]
    lib.crp_synced_count.argtypes = [ctypes.c_void_p]
    lib.crp_poke.argtypes = [ctypes.c_void_p]
    lib.crp_wait_acked.argtypes = [ctypes.c_void_p, ctypes.c_longlong,
                                   ctypes.c_int]
    lib.crp_min_acked.restype = ctypes.c_longlong
    lib.crp_min_acked.argtypes = [ctypes.c_void_p]
    lib.crp_stop.argtypes = [ctypes.c_void_p]
    lib.crf_follow.restype = ctypes.c_void_p
    lib.crf_follow.argtypes = [ctypes.c_char_p, ctypes.c_int,
                               ctypes.c_char_p]
    lib.crf_connected.argtypes = [ctypes.c_void_p]
    lib.crf_offset.restype = ctypes.c_longlong
    lib.crf_offset.argtypes = [ctypes.c_void_p]
    lib.crf_stop.argtypes = [ctypes.c_void_p]
    _lib_handle = lib
    return lib


def replication_available() -> bool:
    return _load() is not None


def assert_promotable(directory: str) -> None:
    """Refuse to promote a mirror that BEGAN following (``repl_token``)
    but never reached the leader's head (no ``repl_synced`` marker —
    fresh catch-up or mid-resync): opening it as the new authority would
    discard commits the dead leader confirmed on its synced peers' acks.
    A never-followed directory (no token) is cluster genesis and allowed.

    Residual (documented in DEPLOY.md): a mirror that synced ONCE and
    then lagged offline keeps its marker — ordering two once-synced
    candidates by log position needs quorum election (Raft's vote
    comparison), which the file elector cannot express.  Operators
    needing strict no-loss run ``min_sync_followers >= 1``."""
    d = Path(directory)
    began_following = (d / "repl_token").exists() \
        or (d / "repl_following").exists()
    if began_following and not (d / "repl_synced").exists():
        raise RuntimeError(
            "refusing promotion: this node's mirror never reached the "
            "previous leader's head (mid-catch-up); a synced peer must "
            "take over")


class ReplicationServer:
    """Leader side: serve ``directory``'s journal to followers.

    Every native call holds ``_mu``: ``stop()`` frees the C++ object, and
    freeing it while another thread sits inside ``crp_wait_acked`` (a
    committer blocked up to the ack timeout) would destroy the mutex and
    condvar under a waiter — the lock makes stop() wait them out."""

    def __init__(self, directory: str, port: int = 0):
        lib = _load()
        if lib is None:
            raise RuntimeError("native replication library unavailable "
                               "(g++ missing or build failed — see "
                               "stderr)")
        self._lib = lib
        self._mu = threading.Lock()
        self._handle = lib.crp_serve(str(directory).encode(), int(port))
        if not self._handle:
            raise RuntimeError(f"could not serve replication on port "
                               f"{port}")
        self.directory = str(directory)
        self.port = lib.crp_port(self._handle)

    @property
    def follower_count(self) -> int:
        with self._mu:
            return self._lib.crp_follower_count(self._handle) \
                if self._handle else 0

    @property
    def synced_follower_count(self) -> int:
        """Followers whose mirror has reached the journal head at least
        once — the set that participates in sync-commit acks.  The
        no-loss guarantee covers commits made after this is ≥ 1."""
        with self._mu:
            return self._lib.crp_synced_count(self._handle) \
                if self._handle else 0

    def poke(self) -> None:
        """Wake follower streams after a journal append."""
        with self._mu:
            if self._handle:
                self._lib.crp_poke(self._handle)

    def wait_acked(self, offset: int, timeout_s: float = 5.0) -> bool:
        """True once every synced follower fsynced through ``offset``
        (vacuously true with none), False on timeout."""
        with self._mu:
            if not self._handle:  # stopped server: nothing to wait for
                return True
            return bool(self._lib.crp_wait_acked(
                self._handle, int(offset), int(timeout_s * 1000)))

    def min_acked(self) -> int:
        """Lowest synced-follower ack offset, -1 when none."""
        with self._mu:
            return int(self._lib.crp_min_acked(self._handle)) \
                if self._handle else -1

    def stop(self) -> None:
        with self._mu:
            if self._handle:
                self._lib.crp_stop(self._handle)
                self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


class ReplicationFollower:
    """Standby side: mirror a leader's journal into ``directory``."""

    def __init__(self, host: str, port: int, directory: str):
        lib = _load()
        if lib is None:
            raise RuntimeError("native replication library unavailable "
                               "(g++ missing or build failed — see "
                               "stderr)")
        self._lib = lib
        self._mu = threading.Lock()
        self._handle = lib.crf_follow(host.encode(), int(port),
                                      str(directory).encode())
        self.directory = str(directory)

    @property
    def connected(self) -> bool:
        with self._mu:
            return bool(self._handle
                        and self._lib.crf_connected(self._handle))

    @property
    def offset(self) -> int:
        with self._mu:
            return int(self._lib.crf_offset(self._handle)) \
                if self._handle else -1

    def wait_offset(self, offset: int, timeout_s: float = 10.0) -> bool:
        """Wait until the local mirror reaches ``offset`` journal bytes."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if self.offset >= offset:
                return True
            time.sleep(0.002)
        return self.offset >= offset

    def stop(self) -> None:
        with self._mu:
            if self._handle:
                self._lib.crf_stop(self._handle)
                self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
