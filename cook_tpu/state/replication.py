"""Socket journal replication: ctypes surface over ``native/repl.cpp``.

The reference framework's durable state is an out-of-process NETWORKED
store (Datomic — ``/root/reference/scheduler/src/cook/datomic.clj:79``), so
its leader failover works from any host: the new leader just re-reads
(``/root/reference/scheduler/src/cook/mesos.clj:153-328``).  cook_tpu's
:class:`~cook_tpu.state.store.Store` journals to a local directory; this
module streams that journal (and its compaction snapshots) to follower
processes over framed TCP so a follower holds a byte-identical mirror in
its OWN directory — no shared filesystem — and can promote with zero lost
committed transactions.

Roles:

- :class:`ReplicationServer` — runs in the leader next to an open store;
  tails ``<dir>/journal.jsonl``.  ``wait_acked(offset)`` blocks until every
  connected follower has fsynced through ``offset`` (sync replication: the
  store calls it per commit via ``Store.attach_replication``).
- :class:`ReplicationFollower` — runs in a standby; mirrors the leader's
  snapshot + journal bytes into a separate local directory.  Promotion is
  ``Store.open(local_dir, epoch=...)`` on that mirror; the journal records
  carry their election epochs, so the store's existing stale-epoch replay
  skipping applies unchanged.
"""

from __future__ import annotations

import ctypes
import os
import threading
import time
from pathlib import Path
from typing import Dict, Optional, Tuple

from ..utils.locks import named_lock

_NATIVE = Path(__file__).resolve().parent.parent.parent / "native"
_SRC = _NATIVE / "repl.cpp"
_LIB = _NATIVE / "build" / "libcookrepl.so"

_lib_handle = None
_lib_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib_handle, _lib_tried
    if _lib_tried:
        return _lib_handle
    _lib_tried = True
    from ..native.build import build_if_stale
    if build_if_stale([_SRC, _NATIVE / "framing.h"], _LIB,
                      ["-shared", "-fPIC"]) is None:
        return None
    lib = ctypes.CDLL(str(_LIB))
    lib.crp_serve.restype = ctypes.c_void_p
    lib.crp_serve.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.crp_port.argtypes = [ctypes.c_void_p]
    lib.crp_follower_count.argtypes = [ctypes.c_void_p]
    lib.crp_synced_count.argtypes = [ctypes.c_void_p]
    lib.crp_poke.argtypes = [ctypes.c_void_p]
    lib.crp_wait_acked.argtypes = [ctypes.c_void_p, ctypes.c_longlong,
                                   ctypes.c_int]
    lib.crp_min_acked.restype = ctypes.c_longlong
    lib.crp_min_acked.argtypes = [ctypes.c_void_p]
    lib.crp_status_json.restype = ctypes.c_int
    lib.crp_status_json.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_int]
    lib.crp_stop.argtypes = [ctypes.c_void_p]
    lib.crf_follow.restype = ctypes.c_void_p
    lib.crf_follow.argtypes = [ctypes.c_char_p, ctypes.c_int,
                               ctypes.c_char_p]
    lib.crf_connected.argtypes = [ctypes.c_void_p]
    lib.crf_offset.restype = ctypes.c_longlong
    lib.crf_offset.argtypes = [ctypes.c_void_p]
    lib.crf_stop.argtypes = [ctypes.c_void_p]
    _lib_handle = lib
    return lib


def replication_available() -> bool:
    return _load() is not None


#: sidecar in a mirror directory recording the election epoch of the
#: leader this mirror last followed — the first component of the
#: candidate-ranking key (Raft compares (term, log index); here
#: (followed epoch, mirrored offset), Ongaro & Ousterhout §5.4.1)
REPL_EPOCH_FILE = "repl_epoch"


def record_followed_epoch(directory: str, epoch: int) -> None:
    """Durably note which election epoch this mirror is following —
    written by the standby wiring whenever it (re)points its follower at
    a published leader address."""
    from ..utils.fsatomic import write_atomic_int
    os.makedirs(directory, exist_ok=True)
    write_atomic_int(os.path.join(directory, REPL_EPOCH_FILE), int(epoch))


def _trimmed_journal_bytes(path: str) -> int:
    """Journal bytes up to the last record boundary (the follower only
    ever acks whole lines; a torn tail from a crash doesn't count)."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return 0
    if size == 0:
        return 0
    with open(path, "rb") as f:
        # scan back for the last newline in bounded chunks
        at = size
        while at > 0:
            frm = max(0, at - (1 << 16))
            f.seek(frm)
            chunk = f.read(at - frm)
            nl = chunk.rfind(b"\n")
            if nl >= 0:
                return frm + nl + 1
            at = frm
    return 0


def candidate_position(directory: str) -> Dict:
    """This mirror's replication position, as published into the
    election medium for candidate ranking: ``epoch`` (election epoch of
    the leader last followed), ``offset`` (mirrored journal bytes at a
    record boundary), ``synced`` (reached that leader's head at least
    once), ``began`` (ever was a mirror at all — False = genesis)."""
    d = Path(directory)
    from ..utils.fsatomic import read_int_file
    return {
        "epoch": read_int_file(str(d / REPL_EPOCH_FILE), 0) or 0,
        "offset": _trimmed_journal_bytes(str(d / "journal.jsonl")),
        "synced": (d / "repl_synced").exists(),
        "began": (d / "repl_token").exists()
        or (d / "repl_following").exists(),
    }


def rank_key(pos: Dict) -> Tuple[int, int, int]:
    """Total order over candidate positions: synced beats unsynced, then
    higher followed epoch (a mirror of a LATER leadership saw commits the
    earlier one cannot have), then more mirrored bytes.  The Raft
    vote-comparison rule (§5.4.1) expressed over (epoch, offset)."""
    return (1 if pos.get("synced") else 0,
            int(pos.get("epoch") or 0), int(pos.get("offset") or 0))


def choose_successor(my_pos: Dict, peers: Dict[str, Dict],
                     now: Optional[float] = None,
                     stale_s: float = 10.0) -> Optional[Tuple[str, Dict]]:
    """Given this node's position and the candidate positions collected
    from the election medium, return ``(peer_id, peer_position)`` of the
    best-synced peer STRICTLY ahead of us — the node to pull the missing
    delta from before opening our store as the new authority — or None
    when we already hold the best position.  Ghost entries (older than
    ``stale_s``) are dead nodes' leftovers and never win."""
    now = time.time() if now is None else now
    best: Optional[Tuple[str, Dict]] = None
    for peer_id, pos in peers.items():
        ts = pos.get("ts")
        if ts is not None and now - float(ts) > stale_s:
            continue
        if not pos.get("synced"):
            continue  # an unsynced mirror holds nothing we must preserve
        if rank_key(pos) <= rank_key(my_pos):
            continue
        if best is None or rank_key(pos) > rank_key(best[1]):
            best = (peer_id, pos)
    return best


def catch_up_from_peer(host: str, port: int, directory: str,
                       target_offset: int,
                       timeout_s: float = 30.0) -> bool:
    """Standby→standby catch-up over the existing framed-TCP carrier
    (Viewstamped Replication's view-change state transfer, Liskov &
    Cowling §4.2): mirror the better-synced peer's journal into
    ``directory`` until at least ``target_offset`` bytes AND the synced
    marker (HEAD) landed, then stop.  The peer's snapshot token differs
    from ours (tokens are per-directory), so this is a full resync —
    always correct, and the delta case costs one snapshot copy."""
    with ReplicationFollower(host, int(port), directory) as f:
        if not f.wait_offset(int(target_offset), timeout_s=timeout_s):
            return False
        # the HEAD marker re-arms the promotion gate; it follows the
        # last JDATA ack immediately
        deadline = time.time() + max(2.0, timeout_s / 4)
        marker = os.path.join(directory, "repl_synced")
        while time.time() < deadline:
            if os.path.exists(marker):
                return True
            time.sleep(0.005)
    return os.path.exists(marker)


def assert_promotable(directory: str) -> None:
    """Refuse to promote a mirror that BEGAN following (``repl_token``)
    but never reached the leader's head (no ``repl_synced`` marker —
    fresh catch-up or mid-resync): opening it as the new authority would
    discard commits the dead leader confirmed on its synced peers' acks.
    A never-followed directory (no token) is cluster genesis and allowed.

    A mirror that synced ONCE and then lagged keeps its marker and
    passes this gate; ordering such candidates is the job of the
    candidate-ranking layer (:func:`choose_successor` over positions
    published into the election medium) — the winner pulls the missing
    delta from the best-synced peer (:func:`catch_up_from_peer`) before
    opening its store, closing the once-synced-lag hole this gate alone
    could not express."""
    d = Path(directory)
    began_following = (d / "repl_token").exists() \
        or (d / "repl_following").exists()
    if began_following and not (d / "repl_synced").exists():
        raise RuntimeError(
            "refusing promotion: this node's mirror never reached the "
            "previous leader's head (mid-catch-up); a synced peer must "
            "take over")


def known_members(elector, self_id: Optional[str] = None,
                  self_url: Optional[str] = None, leader: bool = False,
                  extra: Optional[list] = None) -> Dict[str, Dict]:
    """The fleet-topology view every observability layer shares
    (sched/fleet.py; docs/OBSERVABILITY.md "Debugging the fleet"):
    ``{instance: {url, role, ts}}`` assembled from the election
    candidate registry (standbys publish their position + REST url each
    ``position_interval_seconds``, daemon._follow_leader_loop), this
    node itself, and any config-declared static ``extra`` members
    (FleetConfig.members — agents or processes that never campaign).

    Entries without a url are skipped (nothing to scrape); a STALE
    candidate entry is kept — the federation layer surfaces an
    unreachable member as ``up=0`` data, it never silently narrows the
    fleet.  A registry read failure degrades to the self + static view
    rather than raising into the monitor sweep."""
    out: Dict[str, Dict] = {}
    if self_id:
        out[str(self_id)] = {
            "url": self_url,
            "role": "leader" if leader else "follower",
            "ts": time.time(), "self": True}
    try:
        candidates = elector.read_candidates() if elector is not None \
            else {}
    except Exception:
        candidates = {}
    for nid, pos in candidates.items():
        nid = str(nid)
        if nid in out:
            continue
        url = (pos or {}).get("url")
        if not url:
            continue
        out[nid] = {"url": str(url), "role": "follower",
                    "ts": (pos or {}).get("ts")}
    for m in extra or []:
        inst = str(m.get("instance") or m.get("url"))
        if inst in out or not m.get("url"):
            continue
        out[inst] = {"url": str(m["url"]),
                     "role": str(m.get("role") or "member"),
                     "ts": None}
    return out


class ReplicationServer:
    """Leader side: serve ``directory``'s journal to followers.

    Every native call holds ``_mu``: ``stop()`` frees the C++ object, and
    freeing it while another thread sits inside ``crp_wait_acked`` (a
    committer blocked up to the ack timeout) would destroy the mutex and
    condvar under a waiter — the lock makes stop() wait them out."""

    def __init__(self, directory: str, port: int = 0):
        lib = _load()
        if lib is None:
            raise RuntimeError("native replication library unavailable "
                               "(g++ missing or build failed — see "
                               "stderr)")
        self._lib = lib
        # ranks ABOVE "store" (utils/locks.py): journal appends poke and
        # await this server while holding the store lock
        self._mu = named_lock("repl.server")
        self._handle = lib.crp_serve(str(directory).encode(), int(port))
        if not self._handle:
            raise RuntimeError(f"could not serve replication on port "
                               f"{port}")
        self.directory = str(directory)
        self.port = lib.crp_port(self._handle)
        #: election epoch this server serves for (set by the daemon at
        #: promotion); a superseding epoch fences the server
        self.epoch: Optional[int] = None
        #: partition this server replicates in a partitioned write
        #: plane (state/partition.py) — each partition owns its OWN
        #: topology: server, synced-standby set, lease.  Labels the
        #: replication-lag metrics; None = the classic single topology.
        self.partition: Optional[int] = None
        self.fenced = False

    def status(self) -> list:
        """Per-follower replication status: ``[{"id", "acked",
        "synced"}, ...]`` — the GET /debug/replication surface."""
        import json as _json
        with self._mu:
            if not self._handle:
                return []
            buf = ctypes.create_string_buffer(1 << 16)
            n = self._lib.crp_status_json(self._handle, buf, len(buf))
            if n < 0:
                return []
            return _json.loads(buf.value.decode())

    def fence(self) -> None:
        """A higher election epoch superseded this leader: refuse to
        serve the stale journal to followers (they must re-point at the
        new leader's published address) and fail every later ack wait so
        a racing commit cannot report determinate success."""
        self.fenced = True
        self.stop()

    @property
    def follower_count(self) -> int:
        with self._mu:
            return self._lib.crp_follower_count(self._handle) \
                if self._handle else 0

    @property
    def synced_follower_count(self) -> int:
        """Followers whose mirror has reached the journal head at least
        once — the set that participates in sync-commit acks.  The
        no-loss guarantee covers commits made after this is ≥ 1."""
        with self._mu:
            return self._lib.crp_synced_count(self._handle) \
                if self._handle else 0

    def poke(self) -> None:
        """Wake follower streams after a journal append."""
        with self._mu:
            if self._handle:
                self._lib.crp_poke(self._handle)

    def wait_acked(self, offset: int, timeout_s: float = 5.0) -> bool:
        """True once every synced follower fsynced through ``offset``
        (vacuously true with none), False on timeout."""
        with self._mu:
            if not self._handle:
                # stopped server: nothing to wait for — UNLESS it was
                # stopped by a fence, where a vacuous True would report
                # determinate success on a deposed leader (the fenced
                # flag is re-checked under _mu: fence() can race the
                # pre-lock window of a committing thread)
                return not self.fenced
            acked = bool(self._lib.crp_wait_acked(
                self._handle, int(offset), int(timeout_s * 1000)))
            # a fence that landed during the wait demotes the outcome to
            # indeterminate: the acking mirrors will resync to the
            # successor, whose replay skips this record's stale epoch
            return acked and not self.fenced

    def min_acked(self) -> int:
        """Lowest synced-follower ack offset, -1 when none."""
        with self._mu:
            return int(self._lib.crp_min_acked(self._handle)) \
                if self._handle else -1

    def stop(self) -> None:
        with self._mu:
            if self._handle:
                self._lib.crp_stop(self._handle)
                self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


class ReplicationFollower:
    """Standby side: mirror a leader's journal into ``directory``."""

    def __init__(self, host: str, port: int, directory: str):
        lib = _load()
        if lib is None:
            raise RuntimeError("native replication library unavailable "
                               "(g++ missing or build failed — see "
                               "stderr)")
        self._lib = lib
        self._mu = named_lock("repl.follower")
        self._handle = lib.crf_follow(host.encode(), int(port),
                                      str(directory).encode())
        self.directory = str(directory)

    @property
    def connected(self) -> bool:
        with self._mu:
            return bool(self._handle
                        and self._lib.crf_connected(self._handle))

    @property
    def offset(self) -> int:
        with self._mu:
            return int(self._lib.crf_offset(self._handle)) \
                if self._handle else -1

    def wait_offset(self, offset: int, timeout_s: float = 10.0) -> bool:
        """Wait until the local mirror reaches ``offset`` journal bytes."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if self.offset >= offset:
                return True
            time.sleep(0.002)
        return self.offset >= offset

    def stop(self) -> None:
        with self._mu:
            if self._handle:
                self._lib.crf_stop(self._handle)
                self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
