"""Columnar rank-path index: the store's query/cache layer, TPU-first.

The reference keeps Guava caches of entity attributes so the rank cycle
doesn't re-read Datomic per job (reference: caches.clj, cached_queries.clj,
tools.clj:876-973).  Here the same role is filled by an incrementally
maintained *columnar* projection — numpy columns of exactly the fields the
DRU rank kernel packs — so a cycle at the 1M-task design point never
materializes Python entities at all (VERDICT r1 weak #4): membership is
updated O(delta) off the store's tx-event feed, and building the kernel
inputs is pure vectorized numpy over the live rows.

Layout
------
jobs table (append-only static columns + a mutable pending flag):
  res f32[N,4] (cpus, mem, gpus, 1.0) | prio i32 | submit i64 |
  uuid U36 | user U64 | pool U32 | pending bool
live-instances table (swap-remove):
  job_row i64 | start i64 | task_id -> slot map

``rank_arrays(pool)`` produces the unpadded RankInputs columns in exactly
the order the entity path (sched/ranker.build_user_tasks +
ops/host_prep.pack_rank_inputs) produces them: users sorted by name, tasks
within a user by the feature key (-priority, start, submit, uuid)
(reference: tools.clj task->feature-vector :614-632, dru.clj:123).
"""

from __future__ import annotations

import itertools
import re
import threading
from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from ..utils.locks import named_lock
from .schema import (
    DISK_TYPE_LABEL,
    GPU_MODEL_LABEL,
    InstanceStatus,
    JobState,
)

F32 = np.float32
# pending tasks sort after every running task (reference: pending tasks get
# Long/MAX_VALUE start in the feature vector)
PENDING_START = np.int64(2**62)

_LIVE = (InstanceStatus.UNKNOWN, InstanceStatus.RUNNING)

# composite sort key for the per-pool incremental order cache, packed as
# fixed-width big-endian byte strings so every comparison is one memcmp
# (numpy structured-dtype comparisons cost 3-4x more in the searchsorted
# merge).  Field order IS the comparison order and must equal the lexsort
# key order below: (uid, -prio, start, submit, uuid-hi, uuid-lo), each
# field sign-biased into unsigned big-endian bytes so byte order equals
# numeric order.  At fixed width the S-dtype's trailing-NUL-stripping
# compare is exactly memcmp: two keys differing only in trailing zeros
# cannot exist (both are the full 40 bytes), and at the first differing
# byte both stripped forms still disagree there.
_KEY_NBYTES = 40
_KEY_DT = np.dtype(f"S{_KEY_NBYTES}")

# canonical lowercase uuid: ONLY this form sorts identically as a string
# and as a 128-bit integer (int(h, 16) would also accept uppercase/'0x'/
# signed forms whose string order differs — those force the string sort)
_CANON_UUID = re.compile(
    r"^[0-9a-f]{8}-[0-9a-f]{4}-[0-9a-f]{4}-[0-9a-f]{4}-[0-9a-f]{12}$")


class FusedSnapshot(NamedTuple):
    """One pool's fused-cycle pack snapshot, taken under a single index
    lock hold (every field is mutually consistent).  Base arrays are
    views of the live buffers: row values never mutate, and growth/
    compaction REPLACE buffers rather than moving rows in place, so the
    views stay valid; ``compactions`` keys device-side mirrors of the
    res/disk base columns (unchanged counter = row indices stable)."""

    arrays: Dict[str, np.ndarray]   # pending/valid/is_first (+ first_idx/
    #                                 user_rank/usage unless compact)
    rows_s: np.ndarray              # i64[T] sorted absolute base rows
    uuid_base: np.ndarray           # U36[n] by row
    user_base: np.ndarray           # U64[n] by row
    res_base: np.ndarray            # f32[n, 4] (cpus, mem, gpus, 1) by row
    disk_base: np.ndarray           # f32[n] by row
    users: List[str]                # distinct users in segment order
    job_res: Optional[np.ndarray]   # f32[T, 4] demand; None when compact
    complex_s: np.ndarray           # bool[T] entity-constraint rows
    owner_rows: Dict[str, int]      # reservation owner uuid -> base row
    compactions: int                # index compaction epoch at snapshot


class PackDelta(NamedTuple):
    """One consumer's drained per-pool delta batch (see
    :meth:`ColumnarIndex.pack_delta`): the tx-event feed compacted into
    the row set a device-resident pack consumer must reconcile, plus an
    explicit compaction-epoch fence.  ``rows``/``tombstones`` are base
    row ids valid ONLY within ``epoch``; a ``fence`` means row ids were
    remapped (compaction), the user-id space shifted, or sorted mode
    flipped — the consumer must full-repack, never scatter."""

    epoch: int              # index compaction epoch the row ids live in
    fence: bool             # True -> full repack required
    rows: np.ndarray        # i64[k] rows touched since the last drain
    tombstones: np.ndarray  # i64[m] rows that LEFT the pack (pending off
    #                         or live instance removed); subset semantics:
    #                         also present in ``rows``
    version: int            # the pool's pack version at drain time


def _is_complex(job) -> bool:
    """True when the job needs entity-level treatment in the fused cycle's
    constraint build: user constraints, group placement, checkpoint
    locality, estimated-completion, novel-host (any prior instance), or the
    gpu-model / disk-type affinity labels (state/schema.py
    GPU_MODEL_LABEL / DISK_TYPE_LABEL).  Plain jobs — the vast majority at
    the 1M design point — get a fully vectorized mask instead."""
    return bool(job.constraints or job.group is not None
                or job.checkpoint is not None
                or job.expected_runtime_ms
                or job.instances
                or GPU_MODEL_LABEL in job.labels
                or DISK_TYPE_LABEL in job.labels)


def _grow(arr: np.ndarray, n: int) -> np.ndarray:
    if n <= len(arr):
        return arr
    new = np.zeros((max(n, 2 * len(arr), 1024),) + arr.shape[1:],
                   dtype=arr.dtype)
    new[:len(arr)] = arr
    return new


def _fit_str(arr: np.ndarray, value: str) -> np.ndarray:
    """Widen a fixed-width string column when a value wouldn't fit —
    numpy silently truncates on assignment, and a truncated pool/user name
    would make its rows invisible to equality scans."""
    if len(value) <= arr.dtype.itemsize // 4:  # U-dtype: 4 bytes per char
        return arr
    return arr.astype(f"<U{max(len(value), 2 * (arr.dtype.itemsize // 4))}")


class ColumnarIndex:
    """Attach with ``ColumnarIndex(store)``; reads ``store`` internals once
    under its lock for the initial scan, then stays fresh off the tx feed."""

    def __init__(self, store):
        self.store = store
        # named for the lock-order sanitizer (utils/locks.py contract)
        self._lock = named_lock("index")
        self._n = 0
        # bumped ONLY by _maybe_compact (row remap); consumers holding a
        # (compactions, rows_s) snapshot know base rows < their snapshot's
        # n are content-stable while the counter is unchanged
        self.compactions = 0
        self._row: Dict[str, int] = {}
        self._res = np.zeros((1024, 4), dtype=F32)
        self._disk = np.zeros(1024, dtype=F32)
        self._complex = np.zeros(1024, dtype=bool)
        self._prio = np.zeros(1024, dtype=np.int32)
        # integer sort keys: string lexsort over (uuid, user) costs ~2.3x
        # the all-int sort at 100k+ rows.  _uid is an order-preserving user
        # id (rank of the user name among all known users; new names shift
        # later ids — rare, one vectorized pass); _uhi/_ulo are the uuid's
        # two 64-bit halves (canonical hex uuids sort identically as
        # strings and as 128-bit ints).  _sortable goes False if any uuid
        # is non-canonical, falling back to the string sort.
        self._uid = np.zeros(1024, dtype=np.int32)
        self._uhi = np.zeros(1024, dtype=np.uint64)
        self._ulo = np.zeros(1024, dtype=np.uint64)
        self._user_names: List[str] = []  # sorted; position = user id
        self._sortable = True
        self._submit = np.zeros(1024, dtype=np.int64)
        self._uuid = np.zeros(1024, dtype="<U36")
        self._user = np.zeros(1024, dtype="<U64")
        self._pool = np.zeros(1024, dtype="<U32")
        self._pending = np.zeros(1024, dtype=bool)
        self._done = np.zeros(1024, dtype=bool)  # job reached COMPLETED
        self._dead = 0  # count of done rows (compaction trigger)
        # live instances (swap-remove keeps the arrays dense)
        self._inst_slot: Dict[str, int] = {}
        self._inst_task: List[str] = []
        self._inst_job_row = np.zeros(1024, dtype=np.int64)
        self._inst_start = np.zeros(1024, dtype=np.int64)
        self._ninst = 0
        # per-pool incremental sorted order: pool -> {"kb": sorted _KEY_DT
        # byte-key array, "st": i64 start per entry, "uid": i32 user id
        # per entry, "rows": row index per entry, "log": ordered
        # (+1/-1, row, start) delta journal}.  The full lexsort is ~40 ms
        # at the 100k design point and re-ran every cycle; scheduling churn
        # only touches O(launched) rows, so the order is repaired by
        # searchsorted merge instead.
        self._ord: Dict[str, Dict] = {}
        # ---- delta feed (device-resident pack consumers) ----
        # consumer id -> {"pools": {pool: {"rows": set, "tombs": set}},
        #                 "fence_seen": {pool: fence_version}}
        self._consumers: Dict[int, Dict] = {}
        self._consumer_ids = itertools.count(1)
        # bumped on EVERY event that touches a pool's pack (membership,
        # pending flips, instance churn); cheap equality token for "has
        # anything about this pool changed since my last pack"
        self._pool_version: Dict[str, int] = {}
        # bumped on global order invalidations: compaction (row remap),
        # user-id shift (cached keys embed ids), sorted-mode flip
        self._fence_version = 0
        self._attach()

    # ------------------------------------------------------------ lifecycle
    def _attach(self) -> None:
        with self.store._lock:
            # the index lock is uncontended at construction, but the
            # row-sync helpers run lock-held BY CONTRACT (`caller holds
            # self._lock`) — hold it so the contract is call-site-true
            # here too, not just on the tx-feed path (store -> index is
            # the declared rank order, utils/locks.py)
            with self._lock:
                self._bulk_attach_jobs(list(self.store._jobs.values()))
                for inst in self.store._instances.values():
                    if inst.status in _LIVE:
                        self._add_instance_raw(inst)
            self.store.subscribe(self._on_events)

    def _bulk_attach_jobs(self, jobs) -> None:
        """Vectorized initial scan: one array build per COLUMN instead of
        one `_sync_job_raw` call per row (the per-row path stays for the
        incremental tx feed, where it is the right shape).  At the 1M-job
        design point (BASELINE config 5) this is the difference between
        ~18 s and a few seconds of index attach.  Caller holds
        self._lock (the attach path takes it; the helpers this calls
        are lock-held by the same contract)."""
        if not jobs or self._n:
            for job in jobs:  # non-empty index: incremental semantics
                self._sync_job_raw(job)
            return
        n = len(jobs)
        # 25% headroom: sizing to exactly n would guarantee a full
        # 13-column reallocation (hundreds of MB at 1M rows) on the very
        # first job submitted after attach
        cap = max(1024, n + n // 4)
        self._row = {j.uuid: i for i, j in enumerate(jobs)}
        self._n = n
        res = np.zeros((cap, 4), dtype=F32)
        res[:n, 0] = [j.resources.cpus for j in jobs]
        res[:n, 1] = [j.resources.mem for j in jobs]
        res[:n, 2] = [j.resources.gpus for j in jobs]
        res[:n, 3] = 1.0
        self._res = res
        self._disk = np.zeros(cap, dtype=F32)
        self._disk[:n] = [j.resources.disk for j in jobs]
        self._prio = np.zeros(cap, dtype=np.int32)
        self._prio[:n] = [j.priority for j in jobs]
        self._submit = np.zeros(cap, dtype=np.int64)
        self._submit[:n] = [j.submit_time_ms for j in jobs]
        uuids = [j.uuid for j in jobs]
        self._uuid = np.zeros(cap, dtype="<U36")
        self._uuid[:n] = uuids
        users = [j.user for j in jobs]
        # dtype fitted up front (the per-row path uses _fit_str): a name
        # longer than the column width would silently truncate
        ulen = max(64, max((len(u) for u in users), default=1))
        self._user = np.zeros(cap, dtype=f"<U{ulen}")
        self._user[:n] = users
        pools = [j.pool for j in jobs]
        plen = max(32, max((len(p) for p in pools), default=1))
        self._pool = np.zeros(cap, dtype=f"<U{plen}")
        self._pool[:n] = pools
        self._pending = np.zeros(cap, dtype=bool)
        self._pending[:n] = [j.committed and j.state is JobState.WAITING
                             for j in jobs]
        self._done = np.zeros(cap, dtype=bool)
        self._done[:n] = [j.state is JobState.COMPLETED for j in jobs]
        self._dead = int(self._done[:n].sum())
        self._complex = np.zeros(cap, dtype=bool)
        self._complex[:n] = [_is_complex(j) for j in jobs]
        # order-preserving user ids in ONE pass (vs per-row bisect+shift)
        self._user_names = sorted(set(users))
        name_pos = {u: i for i, u in enumerate(self._user_names)}
        self._uid = np.zeros(cap, dtype=np.int32)
        self._uid[:n] = [name_pos[u] for u in users]
        # canonical-uuid sort keys, per row exactly as _sync_job_raw: a
        # canonical row gets its key even when a non-canonical neighbor
        # disables sorted mode (consumers gate on _sortable)
        self._uhi = np.zeros(cap, dtype=np.uint64)
        self._ulo = np.zeros(cap, dtype=np.uint64)
        hi, lo = self._uhi, self._ulo
        for i, u in enumerate(uuids):
            if _CANON_UUID.match(u):
                h = u.replace("-", "")
                hi[i] = int(h[:16], 16)
                lo[i] = int(h[16:], 16)
            else:
                self._sortable = False

    def _sync_job_raw(self, job) -> None:
        """Insert-or-update one job row (caller holds self._lock or is the
        single-threaded attach scan)."""
        row = self._row.get(job.uuid)
        if row is None:
            row = self._n
            self._n += 1
            self._res = _grow(self._res, self._n)
            self._disk = _grow(self._disk, self._n)
            self._complex = _grow(self._complex, self._n)
            self._prio = _grow(self._prio, self._n)
            self._submit = _grow(self._submit, self._n)
            self._uuid = _grow(self._uuid, self._n)
            self._user = _grow(self._user, self._n)
            self._pool = _grow(self._pool, self._n)
            self._pending = _grow(self._pending, self._n)
            self._done = _grow(self._done, self._n)
            self._uid = _grow(self._uid, self._n)
            self._uhi = _grow(self._uhi, self._n)
            self._ulo = _grow(self._ulo, self._n)
            self._row[job.uuid] = row
            r = job.resources
            self._res[row] = (r.cpus, r.mem, r.gpus, 1.0)
            self._disk[row] = r.disk
            self._prio[row] = job.priority
            self._uid[row] = self._user_id(job.user, new_row=row)
            if _CANON_UUID.match(job.uuid):
                h = job.uuid.replace("-", "")
                self._uhi[row] = np.uint64(int(h[:16], 16))
                self._ulo[row] = np.uint64(int(h[16:], 16))
            elif self._sortable:
                # sorted-mode flip: cached byte keys and resident row
                # orders are built on the int-key order — fence them
                self._sortable = False
                self._fence_all()
            self._submit[row] = job.submit_time_ms
            self._uuid[row] = job.uuid
            self._user = _fit_str(self._user, job.user)
            self._user[row] = job.user
            self._pool = _fit_str(self._pool, job.pool)
            self._pool[row] = job.pool
        was_pending = bool(self._pending[row])
        now_pending = job.committed and job.state is JobState.WAITING
        if now_pending != was_pending:
            pool = str(self._pool[row])
            e = self._ord.get(pool)
            if e is not None:
                e["log"].append((1 if now_pending else -1, int(row),
                                 int(PENDING_START)))
        self._pending[row] = now_pending
        self._complex[row] = _is_complex(job)
        done = job.state is JobState.COMPLETED
        if done != self._done[row]:
            self._dead += 1 if done else -1  # retry paths resurrect rows
            self._done[row] = done
        # delta feed: every synced row is a touch; leaving the pending
        # set is a tombstone (the resident pack row becomes a running or
        # dead row, never a stale pending scatter)
        self._touch_row(str(self._pool[row]), row,
                        tomb=was_pending and not now_pending)

    def _user_id(self, user: str, new_row: Optional[int] = None) -> int:
        """Order-preserving user id (caller holds self._lock).  A new name
        inserts into the sorted list and shifts every later id up — one
        vectorized pass, and only when a never-seen user first submits.
        ``new_row`` is the not-yet-assigned row this id is FOR: its slot
        still holds uid 0 and must not count as a shifted existing key
        (it would fence/clear on every first-in-sort-order user)."""
        import bisect
        pos = bisect.bisect_left(self._user_names, user)
        if pos < len(self._user_names) and self._user_names[pos] == user:
            return pos
        self._user_names.insert(pos, user)
        shift = self._uid[:self._n] >= pos
        if new_row is not None and new_row < self._n:
            shift[new_row] = False
        if shift.any():
            self._uid[:self._n][shift] += 1
            self._ord.clear()  # cached keys embed the shifted ids
            self._fence_all()  # so do resident consumers' sorted orders
        return pos

    def _add_instance_raw(self, inst) -> None:
        row = self._row.get(inst.job_uuid)
        if row is None or inst.task_id in self._inst_slot:
            return
        slot = self._ninst
        self._ninst += 1
        self._inst_job_row = _grow(self._inst_job_row, self._ninst)
        self._inst_start = _grow(self._inst_start, self._ninst)
        if slot < len(self._inst_task):
            self._inst_task[slot] = inst.task_id
        else:
            self._inst_task.append(inst.task_id)
        self._inst_job_row[slot] = row
        self._inst_start[slot] = inst.start_time_ms
        self._inst_slot[inst.task_id] = slot
        pool = str(self._pool[row])
        e = self._ord.get(pool)
        if e is not None:
            e["log"].append((1, int(row), int(inst.start_time_ms)))
        self._touch_row(pool, int(row))

    def _remove_instance_raw(self, task_id: str) -> None:
        slot = self._inst_slot.pop(task_id, None)
        if slot is None:
            return
        row = self._inst_job_row[slot]
        pool = str(self._pool[row])
        e = self._ord.get(pool)
        if e is not None:
            e["log"].append((-1, int(row), int(self._inst_start[slot])))
        self._touch_row(pool, int(row), tomb=True)
        last = self._ninst - 1
        if slot != last:
            self._inst_job_row[slot] = self._inst_job_row[last]
            self._inst_start[slot] = self._inst_start[last]
            moved = self._inst_task[last]
            self._inst_task[slot] = moved
            self._inst_slot[moved] = slot
        self._ninst = last

    # ------------------------------------------------------------ delta feed
    def attach_pack_consumer(self) -> int:
        """Register a device-resident pack consumer: from now on every tx
        event that touches a pool's pack is journaled for this consumer
        (row ids + tombstones + fences) until :meth:`pack_delta` drains
        it.  Consumers attach cold (their first pack is a full build), so
        the journal starts empty."""
        with self._lock:
            cid = next(self._consumer_ids)
            self._consumers[cid] = {"pools": {}, "fence_seen": {}}
            return cid

    def detach_pack_consumer(self, cid: int) -> None:
        with self._lock:
            self._consumers.pop(cid, None)

    def pack_delta(self, cid: int, pool: str) -> PackDelta:
        """Drain one pool's journaled delta batch for a consumer: the
        compact per-cycle change feed of the incremental-view-maintenance
        path (ISSUE 7; McSherry-style deltas, not rebuilds).  A ``fence``
        (compaction row remap, user-id shift, sorted-mode flip) means the
        consumer's resident row ids are invalid — full repack."""
        with self._lock:
            c = self._consumers.get(cid)
            if c is None:  # detached/unknown: behave as a permanent fence
                return PackDelta(self.compactions, True,
                                 np.zeros(0, dtype=np.int64),
                                 np.zeros(0, dtype=np.int64), -1)
            fence = self._fence_version > c["fence_seen"].get(pool, 0)
            c["fence_seen"][pool] = self._fence_version
            d = c["pools"].pop(pool, None)
            rows = np.fromiter(d["rows"], dtype=np.int64,
                               count=len(d["rows"])) if d else \
                np.zeros(0, dtype=np.int64)
            tombs = np.fromiter(d["tombs"], dtype=np.int64,
                                count=len(d["tombs"])) if d else \
                np.zeros(0, dtype=np.int64)
            return PackDelta(self.compactions, fence, rows, tombs,
                             self._pool_version.get(pool, 0))

    def _touch_row(self, pool: str, row: int, tomb: bool = False) -> None:
        """Journal one row touch for every attached consumer (caller
        holds self._lock)."""
        self._pool_version[pool] = self._pool_version.get(pool, 0) + 1
        for c in self._consumers.values():
            d = c["pools"].get(pool)
            if d is None:
                d = c["pools"][pool] = {"rows": set(), "tombs": set()}
            d["rows"].add(int(row))
            if tomb:
                d["tombs"].add(int(row))

    def _fence_all(self) -> None:
        """Global order invalidation (caller holds self._lock): every
        consumer must full-repack every pool before trusting row ids or
        cached keys again."""
        self._fence_version += 1

    # ------------------------------------------------------------ tx events
    def _on_events(self, tx_id: int, events) -> None:
        # borrowed (no-deepcopy) reads: this handler runs for every event of
        # every transaction, and only copies scalar fields into columns
        with self._lock:
            for e in events:
                kind = e.kind
                if kind in ("job-created", "job-committed", "job-state"):
                    job = self.store.job_ref(e.data.get("uuid"))
                    if job is not None:
                        self._sync_job_raw(job)
                elif kind == "instance-created":
                    inst = self.store.instance_ref(e.data.get("task_id"))
                    if inst is not None and inst.status in _LIVE:
                        self._add_instance_raw(inst)
                    if inst is not None:
                        # the job now has a prior instance: novel-host (and
                        # checkpoint locality on restart) may apply
                        row = self._row.get(inst.job_uuid)
                        if row is not None:
                            self._complex[row] = True
                elif kind == "instance-status":
                    tid = e.data.get("task_id")
                    inst = self.store.instance_ref(tid)
                    if inst is None or inst.status not in _LIVE:
                        self._remove_instance_raw(tid)
                    elif inst.status in _LIVE:
                        # replays / resurrect paths: make sure it's tracked
                        self._add_instance_raw(inst)

    # ------------------------------------------------------------- queries
    def rank_arrays(self, pool: str,
                    ) -> Optional[Tuple[Dict[str, np.ndarray], np.ndarray,
                                        np.ndarray, List[str]]]:
        """Unpadded RankInputs columns for one pool, plus the sorted-order
        uuid and user arrays (kernel order positions -> job uuid/user) and
        the pool's distinct users in segment order.  None when the pool has
        no pending jobs (matching the entity path's early-out)."""
        with self._lock:
            got = self._rank_rows_locked(pool)
            if got is None:
                return None
            arrays, rows_s, user_s, seg_start = got
            if user_s is None:  # order-cache path skips the full gather
                user_s = self._user[rows_s]
            return (arrays, self._uuid[rows_s], user_s,
                    list(user_s[seg_start]))

    def _key_fields(self, rows: np.ndarray, start: np.ndarray
                    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(byte keys, start, uid) for (row, start) task entries (caller
        holds _lock).  Keys are fixed-width big-endian byte strings —
        each field sign-biased so that one memcmp equals the lexsort
        field comparison order below."""
        n = len(rows)
        kb = np.empty((n, _KEY_NBYTES), dtype=np.uint8)
        uid = self._uid[rows].astype(np.int32, copy=True)
        st = np.ascontiguousarray(start, dtype=np.int64)

        def be32(x, off):  # i64-safe signed -> biased big-endian u32
            kb[:, off:off + 4] = (x.astype(np.int64) + 2**31) \
                .astype(">u4").view(np.uint8).reshape(n, 4)

        def be64(x, off):  # u64 (sign bit pre-flipped for signed) -> BE
            kb[:, off:off + 8] = x.astype(">u8").view(np.uint8) \
                .reshape(n, 8)

        be32(uid, 0)
        be32(-self._prio[rows], 4)  # int32 negation, as in the lexsort
        be64(st.astype(np.uint64) ^ np.uint64(1 << 63), 8)
        be64(self._submit[rows].astype(np.uint64) ^ np.uint64(1 << 63), 16)
        be64(self._uhi[rows], 24)
        be64(self._ulo[rows], 32)
        return kb.reshape(-1).view(_KEY_DT), st, uid

    def _repair_order(self, e: Dict) -> None:
        """Apply the journaled (row, start) add/del deltas to one pool's
        cached sorted order by searchsorted merge — O(churn log n + n
        memcpy) instead of the full O(n log n) lexsort.  The memcpy tail
        runs in native/pack.cpp when the toolchain built it (one merge
        pass over the four parallel arrays) and falls back to
        np.delete/np.insert otherwise.

        The journal is order-preserving: an entry added and removed between
        two ranks (launch then completion inside one cycle) must cancel,
        not apply as a del-miss followed by a stale insert."""
        adds: Dict[Tuple[int, int], int] = {}
        dels: List[Tuple[int, int]] = []
        for op, row, start in e["log"]:
            k = (row, start)
            if op > 0:
                adds[k] = adds.get(k, 0) + 1
            elif adds.get(k, 0) > 0:
                adds[k] -= 1  # cancels a not-yet-applied add
            else:
                dels.append(k)
        e["log"] = []
        if not dels and not adds:
            return
        kb, st, uid, rows = e["kb"], e["st"], e["uid"], e["rows"]
        del_pos = np.zeros(0, dtype=np.int64)
        if dels:
            drows = np.array([r for r, _ in dels], dtype=np.int64)
            dstart = np.array([s for _, s in dels], dtype=np.int64)
            dkb, _dst, _duid = self._key_fields(drows, dstart)
            dkb = dkb[np.argsort(dkb, kind="stable")]
            pos = np.searchsorted(kb, dkb, side="left")
            # identical keys (same job, same start) form a run: the k-th
            # duplicate delete takes the k-th entry of the run
            for i in range(1, len(pos)):
                if pos[i] <= pos[i - 1] and dkb[i] == dkb[i - 1]:
                    pos[i] = pos[i - 1] + 1
            # a miss means the entry predates the cache; `pos` is already
            # sorted (nondecreasing from sorted needles, strictly advanced
            # within equal-key runs)
            ok = pos < len(kb)
            if ok.any():
                ok[ok] = kb[pos[ok]] == dkb[ok]
            del_pos = pos[ok].astype(np.int64)
        add_list = [k for k, c in adds.items() for _ in range(c)]
        if add_list:
            arows = np.array([r for r, _ in add_list], dtype=np.int64)
            astart = np.array([s for _, s in add_list], dtype=np.int64)
            akb, ast, auid = self._key_fields(arows, astart)
            aorder = np.argsort(akb, kind="stable")
            akb, ast, auid, arows = \
                akb[aorder], ast[aorder], auid[aorder], arows[aorder]
            # insertion points in the POST-delete array, computed without
            # materializing it: entries before a side="left" boundary are
            # strictly smaller, so deletions below the boundary shift it
            # down one-for-one
            ins = np.searchsorted(kb, akb, side="left")
            if len(del_pos):
                ins = ins - np.searchsorted(del_pos, ins, side="left")
        else:
            akb = ast = auid = arows = None
            ins = np.zeros(0, dtype=np.int64)
        from ..native.pack import order_merge
        e["kb"], e["st"], e["uid"], e["rows"] = order_merge(
            kb, st, uid, rows, del_pos, ins, akb, ast, auid, arows)

    def _rank_rows_locked(self, pool: str, skip_usage: bool = False):
        """Shared body of rank_arrays/fused_arrays (caller holds _lock):
        returns (arrays, sorted row indices, sorted users, segment starts)."""
        if self._maybe_compact():
            self._ord.clear()  # row indices were remapped
        n = self._n
        if self._sortable:
            e = self._ord.get(pool)
            if e is not None:
                self._repair_order(e)
                rows_s = e["rows"]
                pending = e["st"] == PENDING_START
                if not pending.any():
                    return None  # no pending jobs (entity-path early-out)
                return self._rank_arrays_tail(rows_s, pending,
                                              uid_s=e["uid"],
                                              skip_usage=skip_usage)
        pool_match = self._pool[:n] == pool
        prow = np.flatnonzero(pool_match & self._pending[:n])
        if prow.size == 0:
            return None
        ijr = self._inst_job_row[:self._ninst]
        ilive = np.flatnonzero(pool_match[ijr]) if self._ninst else \
            np.zeros(0, dtype=np.int64)
        irow = ijr[ilive]
        rows = np.concatenate([prow, irow])
        start = np.concatenate([
            np.full(prow.size, PENDING_START, dtype=np.int64),
            self._inst_start[:self._ninst][ilive]])
        pending = np.zeros(rows.size, dtype=bool)
        pending[:prow.size] = True

        if self._sortable:
            # all-integer sort keys (uuid halves + user id): ~2.3x faster
            # than the string lexsort at the 100k+ design point, identical
            # order (canonical uuids sort the same as their 128-bit value,
            # user ids are name-rank)
            order = np.lexsort((self._ulo[rows], self._uhi[rows],
                                self._submit[rows], start,
                                -self._prio[rows], self._uid[rows]))
        else:
            order = np.lexsort((self._uuid[rows], self._submit[rows], start,
                                -self._prio[rows], self._user[rows]))
        rows_s = rows[order]
        if self._sortable:
            # seed the incremental order cache for the next cycles
            kb, st_s, uid_s = self._key_fields(rows_s, start[order])
            self._ord[pool] = {"kb": kb, "st": st_s, "uid": uid_s,
                               "rows": rows_s.copy(), "log": []}
        user_s = self._user[rows_s]
        return self._rank_arrays_tail(rows_s, pending[order], user_s=user_s,
                                      skip_usage=skip_usage)

    def _rank_arrays_tail(self, rows_s: np.ndarray, pending_s: np.ndarray,
                          user_s: Optional[np.ndarray] = None,
                          uid_s: Optional[np.ndarray] = None,
                          skip_usage: bool = False):
        """Segment bookkeeping + column gathers for already-sorted rows
        (``pending_s`` in sorted order); shared by the lexsort path and the
        incremental order-cache path.  Segment boundaries come from
        ``uid_s`` (int compare) when given — an order-preserving id change
        is exactly a user change — else from the user strings.

        The full sorted user-string column is NOT materialized here: a
        U64 gather is ~25 MB of unicode copying at the 100k design point
        and segment boundaries only need the int ids.  Callers that want
        user strings gather the slice they need from ``self._user``."""
        if user_s is None and uid_s is None:
            user_s = self._user[rows_s]
        first = np.ones(rows_s.size, dtype=bool)
        if uid_s is not None:
            first[1:] = uid_s[1:] != uid_s[:-1]
        else:
            first[1:] = user_s[1:] != user_s[:-1]
        seg_start = np.flatnonzero(first)
        arrays = {
            "pending": pending_s,
            "valid": np.ones(rows_s.size, dtype=bool),
            "is_first": first,
        }
        if not skip_usage:
            # the compact device path re-derives first_idx/user_rank ON
            # DEVICE from the is_first flag bit (parallel/sharded
            # expand_compact) and gathers res via the base mirror; only
            # the legacy/rank paths pay these [T]-sized builds
            seg_id = np.cumsum(first) - 1
            arrays["first_idx"] = seg_start.astype(np.int32)[seg_id]
            arrays["user_rank"] = seg_id.astype(np.int32)
            arrays["usage"] = self._res[rows_s]
        return (arrays, rows_s, user_s, seg_start)

    def fused_arrays(self, pool: str, owner_uuids=None,
                     compact: bool = False):
        """rank_arrays plus the fused cycle's extra columns, all in the same
        sorted row order: ``job_res`` f32[n,4] = (cpus, mem, gpus, disk) —
        the match kernel's per-row resource demand — and ``complex`` bool[n]
        marking rows whose job needs entity-level constraint handling
        (see _is_complex).  None when the pool has no pending jobs.

        uuid/user columns are returned as BASE-array snapshots plus
        ``rows_s`` instead of materialized sorted gathers: unicode gathers
        cost ~40 MB of copying per cycle at 100k rows, while the cycle
        reads ~1k prefix uuids.  The snapshots stay valid forever: row
        values for uuid/user/res never mutate, and growth/compaction
        REPLACE the buffers (``_grow``, ``_maybe_compact``) rather than
        moving rows in place.

        ``owner_uuids`` (reservation owners) are resolved to base rows
        UNDER THE SAME LOCK HOLD as the snapshot: a later ``rows_for``
        call could race a compaction and compare remapped row ids against
        the pre-compaction ``rows_s``.

        With ``compact=True`` (the production device path) the [T]-sized
        usage/job_res gathers are SKIPPED entirely: the driver mirrors the
        immutable res/disk base columns on device (keyed on
        ``compactions``) and gathers by ``rows_s`` there, so the host
        never builds per-task resource columns at all."""
        with self._lock:
            got = self._rank_rows_locked(pool, skip_usage=compact)
            if got is None:
                return None
            arrays, rows_s, _user_s, seg_start = got
            if compact:
                job_res = None
            else:
                # reuse the usage gather (same _res rows) instead of a
                # second full-column fancy-index
                job_res = np.concatenate(
                    [arrays["usage"][:, :3], self._disk[rows_s][:, None]],
                    axis=1).astype(F32)
            owner_rows = {u: r for u in (owner_uuids or ())
                          if (r := self._row.get(u)) is not None}
            return FusedSnapshot(
                arrays=arrays, rows_s=rows_s,
                uuid_base=self._uuid[:self._n],
                user_base=self._user[:self._n],
                res_base=self._res[:self._n],
                disk_base=self._disk[:self._n],
                users=list(self._user[rows_s[seg_start]]),
                job_res=job_res, complex_s=self._complex[rows_s],
                owner_rows=owner_rows, compactions=self.compactions)

    def rows_for(self, uuids) -> np.ndarray:
        """Base-row indices for the given job uuids (unknown uuids are
        skipped).  Lets hot-path membership tests run on int64 rows instead
        of gathering string columns (e.g. reservation owners in the fused
        pack)."""
        with self._lock:
            return np.array([r for u in uuids
                             if (r := self._row.get(u)) is not None],
                            dtype=np.int64)

    def pool_usage_base(self, pool: str) -> np.ndarray:
        """Summed (cpus, mem, gpus, count) of the pool's live instances —
        the running-usage base of filter-based-on-quota
        (scheduler.clj:2134) without entity materialization."""
        with self._lock:
            if self._ninst == 0:
                return np.zeros(4, dtype=F32)
            ijr = self._inst_job_row[:self._ninst]
            mask = self._pool[:self._n][ijr] == pool
            return self._res[ijr[mask]].sum(axis=0).astype(F32) \
                if mask.any() else np.zeros(4, dtype=F32)

    def _maybe_compact(self) -> bool:
        """Drop rows of completed jobs with no live instances once they are
        the majority — bounds memory on a long-lived leader (caller holds
        self._lock).  Returns True when a compaction ran (row indices were
        remapped, so cached orders are stale)."""
        if self._dead < 4096 or self._dead * 2 < self._n:
            return False
        n = self._n
        # keep live rows plus anything a live instance still references; a
        # dropped job that ever transitions again is re-inserted by its
        # job-state event (the handler refetches the entity)
        keep = ~self._done[:n]
        keep[self._inst_job_row[:self._ninst]] = True
        new_rows = np.flatnonzero(keep)
        remap = np.full(n, -1, dtype=np.int64)
        remap[new_rows] = np.arange(new_rows.size)
        for arr_name in ("_res", "_disk", "_complex", "_prio", "_submit",
                         "_uuid", "_user", "_pool", "_pending", "_done",
                         "_uid", "_uhi", "_ulo"):
            arr = getattr(self, arr_name)
            setattr(self, arr_name, arr[:n][new_rows].copy())
        self._row = {u: int(remap[r]) for u, r in self._row.items()
                     if remap[r] >= 0}
        self._inst_job_row[:self._ninst] = remap[
            self._inst_job_row[:self._ninst]]
        self._n = new_rows.size
        self._dead = int(self._done[:self._n].sum())
        # row indices were remapped: device-resident base mirrors keyed on
        # this counter must fully resync (growth, by contrast, preserves
        # row indices and never bumps it), and every delta consumer's
        # resident rows are invalid — fence, never scatter stale rows
        self.compactions += 1
        self._fence_all()
        return True
