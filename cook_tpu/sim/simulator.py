"""Faster-than-real-time trace-replay simulator.

The port of the reference's simulator (reference:
scheduler/test/cook/test/zz_simulator.clj:355-718 + docs/simulator.md and the
mesos_mock offer fabricator): replay a JSON job trace against the *real*
scheduler wired to the fake cluster on a virtual clock.  Time advances only
between events, so runs compare *decisions*, not wall time; the wall-clock
cost of each rank/match cycle is recorded separately as the performance
metric (BASELINE.md: match-cycle p50/p99 + placements/sec).

Trace format (one job per entry):
  {"uuid": ..., "user": "u1", "submit_time": ms, "duration": ms,
   "cpus": 1.0, "mem": 100.0, "gpus": 0, "priority": 50, "pool": "default"}
Host file: [{"hostname": "h1", "cpus": 8, "mem": 8192, "gpus": 0, ...}]
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from ..cluster.fake import FakeCluster, FakeHost
from ..config import Config
from ..sched.scheduler import Scheduler
from ..state.schema import InstanceStatus, Job, JobState, Resources, new_uuid
from ..state.store import Store


@dataclass
class SimResult:
    completed: int = 0
    total: int = 0
    preemptions: int = 0
    makespan_ms: int = 0
    wait_times_ms: List[int] = field(default_factory=list)
    match_wall_ms: List[float] = field(default_factory=list)
    rank_wall_ms: List[float] = field(default_factory=list)
    placements: int = 0
    task_records: List[Dict] = field(default_factory=list)
    # flight-recorder aggregate over this run's cycles (utils/flight.py):
    # cycle count/percentiles, recompiles, transfer bytes, skip reasons
    flight: Dict = field(default_factory=dict)
    # per-job audit-trail aggregate (utils/audit.py): jobs tracked +
    # event counts by kind — sanity that attribution engaged
    audit: Dict = field(default_factory=dict)
    # goodput aggregate (docs/GANG.md elasticity; the optimizer loop's
    # replay score and the elastic_cycle bench read this): busy-capacity
    # fraction, placed-gang-member fraction, resize counts, and the
    # never-placed demand the autoscale decision sizes against
    goodput: Dict = field(default_factory=dict)

    def summary(self) -> Dict:
        wt = np.asarray(self.wait_times_ms or [0])
        mw = np.asarray(self.match_wall_ms or [0.0])
        rw = np.asarray(self.rank_wall_ms or [0.0])
        wall_s = (np.sum(mw) + np.sum(rw)) / 1000.0
        return {
            "jobs_total": self.total,
            "jobs_completed": self.completed,
            "preemptions": self.preemptions,
            "makespan_virtual_s": self.makespan_ms / 1000.0,
            "wait_time_p50_s": float(np.percentile(wt, 50)) / 1000.0,
            "wait_time_p99_s": float(np.percentile(wt, 99)) / 1000.0,
            "match_cycle_p50_ms": float(np.percentile(mw, 50)),
            "match_cycle_p99_ms": float(np.percentile(mw, 99)),
            "rank_cycle_p50_ms": float(np.percentile(rw, 50)),
            "placements": self.placements,
            "placements_per_wall_s": (self.placements / wall_s
                                      if wall_s > 0 else float("inf")),
            "flight": self.flight,
            "audit": self.audit,
            "goodput": self.goodput,
        }


def load_trace(entries: List[Dict]) -> List[Job]:
    jobs = []
    for e in entries:
        jobs.append(Job(
            uuid=e.get("uuid") or new_uuid(),
            user=e["user"],
            command=e.get("command", "sim"),
            resources=Resources(cpus=float(e.get("cpus", 1.0)),
                                mem=float(e.get("mem", 100.0)),
                                gpus=float(e.get("gpus", 0.0))),
            priority=int(e.get("priority", 50)),
            max_retries=int(e.get("max_retries", 3)),
            pool=e.get("pool", "default"),
            submit_time_ms=int(e["submit_time"]),
            labels={"sim/duration_ms": str(int(e.get("duration", 1000)))},
        ))
    jobs.sort(key=lambda j: j.submit_time_ms)
    return jobs


def load_hosts(entries: List[Dict]) -> List[FakeHost]:
    return [FakeHost(
        hostname=e["hostname"],
        capacity=Resources(cpus=float(e.get("cpus", 8.0)),
                           mem=float(e.get("mem", 8192.0)),
                           gpus=float(e.get("gpus", 0.0))),
        pool=e.get("pool", "default"),
        attributes=dict(e.get("attributes", {})),
        gpu_model=e.get("gpu_model", ""))
        for e in entries]


class Simulator:
    def __init__(self, trace: List[Job], hosts: List[FakeHost],
                 config: Optional[Config] = None, backend: str = "tpu",
                 rank_interval_ms: int = 5000, match_interval_ms: int = 1000,
                 rebalance_interval_ms: int = 30000,
                 cycle_mode: Optional[str] = None,
                 groups: Optional[Dict[str, object]] = None,
                 rate_limits=None):
        self.trace = trace
        # gang groups keyed by uuid (docs/GANG.md): members referencing
        # a group here are CO-SUBMITTED as one batch with the Group at
        # the earliest member's submit time — gangs never trickle in
        self.groups = dict(groups or {})
        self.config = config or Config()
        if backend == "cpu":
            self.config.default_matcher.backend = "cpu"
        self.store = Store()
        self.cluster = FakeCluster("sim", hosts)
        self.scheduler = Scheduler(self.store, self.config, [self.cluster],
                                   rank_backend=backend,
                                   rate_limits=rate_limits)
        # overload-replay hooks (sim/overload.py): ``admit`` gates each
        # trace submission like the REST front door would (return False
        # = shed, the uuid lands in ``shed_job_uuids`` instead of the
        # store); ``on_tick`` runs once per loop iteration on the
        # virtual clock (the overload harness drives monitor sweeps —
        # and thus the admission controller — through it)
        self.admit = None
        self.on_tick = None
        self.shed_job_uuids: List[str] = []
        self.rank_interval_ms = rank_interval_ms
        self.match_interval_ms = match_interval_ms
        self.rebalance_interval_ms = rebalance_interval_ms
        # "fused": drive the production one-dispatch cycle
        # (Scheduler.step_cycle) instead of split rank/match steps.
        # Default follows Config.cycle_mode, except the no-JAX cpu backend
        # which only has the split path.
        if cycle_mode is None:
            cycle_mode = "split" if backend == "cpu" else self.config.cycle_mode
        self.cycle_mode = cycle_mode
        # job uuid -> virtual duration; the fake cluster resolves durations
        # at launch time through this shared mapping
        self._job_durations: Dict[str, int] = {}
        self.cluster.job_durations_ms = self._job_durations

    def run(self, until_ms: Optional[int] = None,
            max_virtual_ms: int = 24 * 3600 * 1000) -> SimResult:
        from ..utils.flight import recorder as flight_recorder
        result = SimResult(total=len(self.trace))
        if not self.trace:
            return result
        # the flight-recorder summary covers only THIS run's cycles
        flight_seq0 = flight_recorder.last_seq()
        pending = list(self.trace)
        now = pending[0].submit_time_ms
        # every stamp (queue/start/end times, heartbeats, reaper sweeps)
        # follows the store clock; one patch keeps the whole system in
        # virtual trace time
        self.store.clock = lambda: now
        next_rank = now
        next_match = now
        next_rebalance = now + self.rebalance_interval_ms
        deadline = until_ms if until_ms is not None \
            else pending[-1].submit_time_ms + max_virtual_ms
        start_ms = now

        elastic_on = getattr(self.config.elastic, "enabled", False) \
            and self.scheduler.elastic is not None
        while now <= deadline:
            # deliver submissions due now
            while pending and pending[0].submit_time_ms <= now:
                job = pending.pop(0)
                if self.admit is not None and not self.admit(job, now):
                    self.shed_job_uuids.append(job.uuid)
                    continue
                self._job_durations[job.uuid] = int(
                    job.labels["sim/duration_ms"])
                if job.group and job.group in self.groups:
                    # gang cohort: pull the siblings forward and submit
                    # the whole gang with its Group in one batch (gangs
                    # are co-submitted, REST enforces exactly this)
                    cohort = [job] + [j for j in pending
                                      if j.group == job.group]
                    pending = [j for j in pending
                               if j.group != job.group]
                    for m in cohort:
                        self._job_durations[m.uuid] = int(
                            m.labels["sim/duration_ms"])
                    self.store.create_jobs(
                        cohort, groups=[self.groups[job.group]])
                else:
                    self.store.create_jobs([job])
            # cycles (virtual-time frozen during computation)
            if now >= next_rank and self.cycle_mode != "fused":
                t0 = time.perf_counter()
                self.scheduler.step_rank()
                result.rank_wall_ms.append((time.perf_counter() - t0) * 1000)
                next_rank = now + self.rank_interval_ms
            if now >= next_match:
                t0 = time.perf_counter()
                if self.cycle_mode == "fused":
                    match_results = self.scheduler.step_cycle()
                else:
                    match_results = self.scheduler.step_match()
                result.match_wall_ms.append((time.perf_counter() - t0) * 1000)
                for res in match_results.values():
                    result.placements += len(res.launched_task_ids)
                next_match = now + self.match_interval_ms
            if now >= next_rebalance:
                # split mode re-ranks so the rebalancer sees post-launch
                # queues; the fused cycle already pruned launched jobs
                if self.cycle_mode != "fused":
                    self.scheduler.step_rank()
                decisions = self.scheduler.step_rebalance()
                for pool_decisions in decisions.values():
                    for d in pool_decisions:
                        result.preemptions += len(d.victim_task_ids)
                next_rebalance = now + self.rebalance_interval_ms
            self.scheduler.step_reapers(current_ms=now)
            if self.on_tick is not None:
                self.on_tick(now)
            if elastic_on:
                # elastic resize plane (docs/GANG.md elasticity): execute
                # grace-expired shrinks and the optimizer's standing
                # shrink pressure on the virtual clock
                self.scheduler.step_resize()

            # advance the clock to the next interesting moment
            candidates = [next_rank, next_match, next_rebalance]
            if pending:
                candidates.append(pending[0].submit_time_ms)
            completion = self._next_completion_ms()
            if completion is not None:
                candidates.append(completion)
            nxt = min(candidates)
            if nxt <= now:
                nxt = now + self.match_interval_ms
            now = nxt
            self.cluster.advance_to(now)
            if not pending and self._all_done():
                break

        # harvest
        result.flight = flight_recorder.summary(since_seq=flight_seq0)
        result.audit = self.store.audit.stats()
        result.makespan_ms = now - start_ms
        for job in self.trace:
            stored = self.store.job(job.uuid)
            if stored is None:
                continue
            if stored.state is JobState.COMPLETED:
                result.completed += 1
            for tid in stored.instances:
                inst = self.store.instance(tid)
                if inst is None:
                    continue
                result.task_records.append({
                    "job": job.uuid, "user": job.user, "task": tid,
                    "host": inst.hostname,
                    "status": inst.status.value,
                    "start": inst.start_time_ms, "end": inst.end_time_ms,
                    "wait_ms": inst.queue_time_ms,
                    "preempted": inst.preempted,
                })
                if inst.queue_time_ms is not None:
                    result.wait_times_ms.append(inst.queue_time_ms)
        result.goodput = self._goodput(result, now)
        return result

    def _goodput(self, result: SimResult, now: int) -> Dict:
        """Goodput aggregate over the finished run (docs/GANG.md
        elasticity): ``util`` — busy cpu-seconds as a fraction of
        capacity cpu-seconds over the makespan; ``gang_goodput`` —
        placed gang-member-seconds as a fraction of the member-seconds a
        fully-placed gang workload would have run (the bench's
        placed-member goodput, higher when elastic gangs run at partial
        strength instead of waiting whole); plus resize counts and the
        never-placed cpu demand the autoscale decision sizes against."""
        span_ms = max(result.makespan_ms, 1)
        cap_cpus = sum(h.capacity.cpus
                       for h in self.cluster._hosts.values())
        by_uuid = {j.uuid: j for j in self.trace}
        busy_cpu_ms = 0.0
        member_ms = 0.0
        placed_jobs = set()
        for r in result.task_records:
            if r.get("start") is None:
                continue
            placed_jobs.add(r["job"])
            job = by_uuid.get(r["job"])
            if job is None:
                continue
            dur = (r["end"] or now) - r["start"]
            if dur <= 0:
                continue
            busy_cpu_ms += dur * job.resources.cpus
            if job.group and job.group in self.groups:
                member_ms += dur
        gang_members = 0
        demand_ms = 0.0
        for j in self.trace:
            if j.group and j.group in self.groups:
                gang_members += 1
                demand_ms += int(j.labels.get("sim/duration_ms", 0))
        unplaced_cpus = sum(
            j.resources.cpus for j in self.trace
            if j.uuid not in placed_jobs)
        mgr = self.scheduler.elastic
        out = {
            "util": (busy_cpu_ms / (cap_cpus * span_ms)
                     if cap_cpus > 0 else 0.0),
            "unplaced_cpus": unplaced_cpus,
            "preemptions": result.preemptions,
            "grows": getattr(mgr, "grows", 0),
            "shrinks": getattr(mgr, "shrinks", 0),
        }
        if gang_members:
            # placed member-time over DEMANDED member-time: 1.0 = every
            # member ran exactly its duration; a rigid gang waiting
            # whole scores 0 where an elastic one running at gang_min
            # already banks min/size
            out["gang_goodput"] = (member_ms / demand_ms
                                   if demand_ms > 0 else 0.0)
            out["gang_members"] = gang_members
        return out

    def _next_completion_ms(self) -> Optional[int]:
        with self.cluster._lock:
            times = [t.started_at_ms + t.duration_ms
                     for t in self.cluster._tasks.values()
                     if t.duration_ms is not None]
        return min(times) if times else None

    def _all_done(self) -> bool:
        return not self.store.jobs_where(
            lambda j: j.state is not JobState.COMPLETED)


def generate_example_trace(n_jobs: int = 200, n_users: int = 6,
                           seed: int = 0, span_ms: int = 60_000,
                           duration_ms: int = 10_000) -> List[Dict]:
    """Statistical workload generator (reference: simulator/ subproject)."""
    rng = np.random.default_rng(seed)
    return [{
        "user": f"user{int(rng.integers(0, n_users)):02d}",
        "submit_time": int(rng.integers(0, span_ms)),
        "duration": int(rng.exponential(duration_ms)) + 100,
        "cpus": float(rng.integers(1, 8)),
        "mem": float(rng.integers(64, 2048)),
        "priority": int(rng.integers(0, 100)),
    } for _ in range(n_jobs)]


def generate_example_hosts(n_hosts: int = 20, seed: int = 0) -> List[Dict]:
    rng = np.random.default_rng(seed)
    return [{"hostname": f"host{i:03d}",
             "cpus": float(rng.choice([8, 16, 32])),
             "mem": float(rng.choice([8192, 16384, 32768]))}
            for i in range(n_hosts)]


def run_pipeline_parity(seed: int = 0, n_jobs: int = 60, n_hosts: int = 10,
                        depth: int = 2, backend: str = "tpu",
                        span_ms: int = 60_000,
                        duration_ms: int = 10_000) -> Dict:
    """Deterministic pipelined-vs-sync parity harness (docs/PERFORMANCE.md):
    two identical seeded worlds driven through the PRODUCTION fused cycle
    (Scheduler.step_cycle), one with ``pipeline_depth=0`` (strictly
    synchronous) and one pipelined at ``depth``.  Asserted by
    tests/test_pipeline.py and runnable standalone
    (``python -m cook_tpu.sim --parity-pipeline``):

    - both runs complete every job;
    - both runs LAUNCH the same job set (the per-cycle schedule may
      differ by the pipeline's one-cycle speculation, the work may not);
    - no job ever holds two live instances (store-level re-check);
    - the pipelined run's reconciliation conflict drops are reported
      (zero expected here: the speculation mask makes back-to-back
      cycles disjoint, and a single-threaded sim has no racing writers).
    """
    from ..utils.flight import recorder as _flight

    def run_one(d: int):
        cfg = Config()
        cfg.pipeline.depth = d
        entries = generate_example_trace(n_jobs, seed=seed,
                                         span_ms=span_ms,
                                         duration_ms=duration_ms)
        # FIXED uuids: load_trace otherwise mints fresh ones, and the two
        # runs' launched sets must be comparable by identity
        for i, e in enumerate(entries):
            e["uuid"] = f"00000000-0000-4000-8000-{i:012d}"
        trace = load_trace(entries)
        hosts = load_hosts(generate_example_hosts(n_hosts, seed=seed))
        seq0 = _flight.last_seq()
        sim = Simulator(trace, hosts, config=cfg, backend=backend,
                        cycle_mode="fused")
        res = sim.run()
        flight = _flight.summary(since_seq=seq0)
        launched = {r["job"] for r in res.task_records}
        # store-level duplicate-live re-check (the chaos harness checks
        # per-tick; end-state must hold too)
        dup = []
        for job in sim.store.jobs_where(lambda j: True):
            live = [t for t in job.instances
                    if (i := sim.store.instance(t)) is not None
                    and i.status.value in ("unknown", "running")]
            if len(live) > 1:
                dup.append(job.uuid)
        return res, launched, flight, dup

    res_sync, launched_sync, _fl_sync, dup_sync = run_one(0)
    res_pipe, launched_pipe, fl_pipe, dup_pipe = run_one(depth)
    return {
        "ok": (launched_sync == launched_pipe
               and res_sync.completed == res_sync.total
               and res_pipe.completed == res_pipe.total
               and not dup_sync and not dup_pipe),
        "jobs": n_jobs,
        "depth": depth,
        "sync_completed": res_sync.completed,
        "pipelined_completed": res_pipe.completed,
        "launched_equal": launched_sync == launched_pipe,
        "launched_only_sync": sorted(launched_sync - launched_pipe),
        "launched_only_pipelined": sorted(launched_pipe - launched_sync),
        "duplicate_live": sorted(dup_sync + dup_pipe),
        "pipelined_conflicts": fl_pipe.get("pipeline_conflicts", 0),
        "sync_placements": res_sync.placements,
        "pipelined_placements": res_pipe.placements,
    }
