"""System simulator: generated workloads against a LIVE cook_tpu daemon.

The analog of the reference's simulator subproject (reference:
simulator/src/main/cook/sim/{schedule,runner,reporting}.clj) — distinct
from ``cook_tpu.sim.simulator``'s faster-than-real-time scheduler
simulation: this one exercises the FULL system (REST submission, real
scheduler cadence, backend execution) the way a fleet of users would.

    python -m cook_tpu.sim.system generate -f sched.json \
        --users 4 --jobs-per-user 25 --duration-s 60 --seed 7
    python -m cook_tpu.sim.system simulate -f sched.json \
        --url http://localhost:12321 --out results.json --time-scale 10
    python -m cook_tpu.sim.system report -f results.json

Schedule shape (JSON; reference: sim/schedule.clj create-db-job):
    {"label": ..., "duration_seconds": S,
     "users": [{"username": u, "jobs": [
         {"at_ms": t, "name": n, "priority": p, "duration_ms": d,
          "cpus": c, "mem": m, "exit_code": e}]}]}

``simulate`` submits every job at its ``at_ms`` offset (divided by
--time-scale so an hour-long schedule can replay in minutes), waits for
completion, and records per-job submit/start/finish timestamps.
``report`` computes the reference's metrics: wait (first start -
submit), turnaround (finish - submit), overhead (turnaround - the job's
intended duration), per user and overall (reporting.clj:166-202), plus
preemption counts and never-scheduled warnings (:101-155).
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from typing import Dict, List, Optional

import numpy as np


def generate_schedule(users: int, jobs_per_user: int, duration_s: float,
                      seed: int, label: str,
                      mean_duration_ms: float = 2000.0) -> Dict:
    """Random schedule (reference: schedule.clj generate-job-schedule —
    arrival times uniform over the window, durations/resources drawn per
    job, a small failure rate via exit codes)."""
    rng = np.random.default_rng(seed)
    out_users = []
    for u in range(users):
        jobs = []
        arrivals = np.sort(rng.uniform(0, duration_s * 1000.0,
                                       jobs_per_user))
        for j, at in enumerate(arrivals):
            jobs.append({
                "at_ms": int(at),
                "name": f"sim-u{u}-j{j}",
                "priority": int(rng.integers(0, 100)),
                "duration_ms": int(rng.exponential(mean_duration_ms)) + 50,
                "cpus": float(rng.integers(1, 4)),
                "mem": float(rng.integers(64, 1024)),
                # ~5% of jobs fail (reference schedules exit codes)
                "exit_code": int(rng.random() < 0.05),
            })
        out_users.append({"username": f"sim{u:03d}", "jobs": jobs})
    return {"label": label, "duration_seconds": duration_s,
            "seed": seed, "users": out_users}


def run_simulation(schedule: Dict, url: str, time_scale: float = 1.0,
                   settle_timeout_s: float = 120.0,
                   fake_hints: bool = True) -> Dict:
    """Submit the schedule against a live daemon and record outcomes.

    Each user runs as its own thread of JobClient submissions at the
    scheduled (scaled) offsets — the reference's Simulant agents
    (runner.clj).  ``fake_hints`` attaches COOK_FAKE_* env so FakeCluster
    backends honor durations/exit codes; real agents run the sleep
    command itself."""
    from ..client import JobClient

    t0 = time.time()
    lock = threading.Lock()
    submitted: List[Dict] = []
    errors: List[str] = []

    def run_user(user: Dict) -> None:
        client = JobClient(url, user=user["username"])
        for job in user["jobs"]:
            target = t0 + (job["at_ms"] / 1000.0) / time_scale
            delay = target - time.time()
            if delay > 0:
                time.sleep(delay)
            dur_s = (job["duration_ms"] / 1000.0) / time_scale
            spec = {
                "command": f"sleep {dur_s:.3f}; exit {job['exit_code']}",
                "name": job["name"], "priority": job["priority"],
                "cpus": job["cpus"], "mem": job["mem"], "max_retries": 1,
            }
            if fake_hints:
                spec["env"] = {
                    "COOK_FAKE_DURATION_MS":
                        str(max(1, int(job["duration_ms"] / time_scale))),
                    "COOK_FAKE_EXIT_CODE": str(job["exit_code"]),
                }
            try:
                [uuid] = client.submit([spec])
                with lock:
                    submitted.append({
                        "uuid": uuid, "user": user["username"],
                        "name": job["name"],
                        "intended_duration_ms":
                            job["duration_ms"] / time_scale,
                        "submit_ms": int(time.time() * 1000)})
            except Exception as e:  # noqa: BLE001 - recorded, not fatal
                with lock:
                    errors.append(f"{user['username']}/{job['name']}: {e}")

    threads = [threading.Thread(target=run_user, args=(u,), daemon=True)
               for u in schedule["users"]]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    # settle: wait for every submitted job to reach a terminal state.
    # Transient query failures (leader failover, brief 503) must not
    # discard a possibly hour-long replay — retry until the deadline.
    from ..client import TERMINAL_STATES
    client = JobClient(url, user="sim-reporter")
    deadline = time.time() + settle_timeout_s
    uuids = [s["uuid"] for s in submitted]
    jobs_by_uuid: Dict[str, Dict] = {}
    while time.time() < deadline:
        done = 0
        try:
            for i in range(0, len(uuids), 100):
                for j in client.query(uuids[i:i + 100], partial=True):
                    jobs_by_uuid[j["uuid"]] = j
                    if j["state"] in TERMINAL_STATES:
                        done += 1
        except Exception as e:  # noqa: BLE001 - transient; keep settling
            with lock:
                errors.append(f"settle query: {e}")
        if done == len(uuids):
            break
        time.sleep(0.5)

    results = []
    for s in submitted:
        job = jobs_by_uuid.get(s["uuid"], {})
        insts = job.get("instances", [])
        start = min((i.get("start_time") or 0 for i in insts
                     if i.get("start_time")), default=None)
        finish = max((i.get("end_time") or 0 for i in insts
                      if i.get("end_time")), default=None)
        results.append({
            **s,
            "state": job.get("state", "unknown"),
            "instance_count": len(insts),
            "preempted": sum(1 for i in insts if i.get("preempted")),
            # the DAEMON's clock for submit too: mixing the simulator
            # host's clock with server-side start/end timestamps would
            # skew wait/overhead by clock offset + POST round trip
            "submit_ms": job.get("submit_time") or s["submit_ms"],
            "start_ms": start, "finish_ms": finish,
        })
    return {"label": schedule.get("label", ""),
            "time_scale": time_scale,
            "wall_s": round(time.time() - t0, 1),
            "errors": errors, "jobs": results}


def _metric_block(values: List[float]) -> Dict:
    if not values:
        return {}
    a = np.asarray(values, dtype=np.float64)
    return {"mean_ms": round(float(a.mean()), 1),
            "p50_ms": round(float(np.percentile(a, 50)), 1),
            "p95_ms": round(float(np.percentile(a, 95)), 1),
            "max_ms": round(float(a.max()), 1),
            "count": int(len(a))}


def build_report(results: Dict) -> Dict:
    """Wait/turnaround/overhead per user + overall (reference:
    reporting.clj show-average-{wait,turnaround,overhead} + the
    unscheduled/unfinished warnings)."""
    jobs = results["jobs"]
    never_scheduled = [j for j in jobs if not j.get("start_ms")]
    unfinished = [j for j in jobs
                  if j.get("start_ms") and not j.get("finish_ms")]
    per_user: Dict[str, Dict[str, List[float]]] = {}
    overall: Dict[str, List[float]] = {"wait": [], "turnaround": [],
                                       "overhead": []}
    for j in jobs:
        if not (j.get("start_ms") and j.get("finish_ms")):
            continue
        wait = j["start_ms"] - j["submit_ms"]
        turnaround = j["finish_ms"] - j["submit_ms"]
        overhead = turnaround - j["intended_duration_ms"]
        bucket = per_user.setdefault(
            j["user"], {"wait": [], "turnaround": [], "overhead": []})
        for key, v in (("wait", wait), ("turnaround", turnaround),
                       ("overhead", overhead)):
            bucket[key].append(v)
            overall[key].append(v)
    return {
        "label": results.get("label", ""),
        "jobs_total": len(jobs),
        "finished": sum(1 for j in jobs
                        if j.get("start_ms") and j.get("finish_ms")),
        "failed": sum(1 for j in jobs if j.get("state") == "failed"),
        "preemptions": sum(j.get("preempted", 0) for j in jobs),
        "never_scheduled": [j["uuid"] for j in never_scheduled],
        "unfinished": [j["uuid"] for j in unfinished],
        "submit_errors": results.get("errors", []),
        "overall": {k: _metric_block(v) for k, v in overall.items()},
        "by_user": {u: {k: _metric_block(v) for k, v in m.items()}
                    for u, m in sorted(per_user.items())},
    }


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="cook-sim-system", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    g = sub.add_parser("generate", help="write a random job schedule")
    g.add_argument("-f", "--file", required=True)
    g.add_argument("--users", type=int, default=4)
    g.add_argument("--jobs-per-user", type=int, default=25)
    g.add_argument("--duration-s", type=float, default=60.0)
    g.add_argument("--mean-job-duration-ms", type=float, default=2000.0)
    g.add_argument("--seed", type=int, default=1)
    g.add_argument("--label", default="generated")

    s = sub.add_parser("simulate", help="run a schedule against a daemon")
    s.add_argument("-f", "--file", required=True)
    s.add_argument("--url", required=True)
    s.add_argument("--out", required=True)
    s.add_argument("--time-scale", type=float, default=1.0,
                   help="replay N× faster than the schedule's clock")
    s.add_argument("--settle-timeout-s", type=float, default=120.0)
    s.add_argument("--no-fake-hints", action="store_true",
                   help="omit COOK_FAKE_* env (real agent backends)")

    r = sub.add_parser("report", help="summarize simulation results")
    r.add_argument("-f", "--file", required=True)

    args = p.parse_args(argv)
    if args.cmd == "generate":
        schedule = generate_schedule(
            args.users, args.jobs_per_user, args.duration_s, args.seed,
            args.label, mean_duration_ms=args.mean_job_duration_ms)
        with open(args.file, "w", encoding="utf-8") as f:
            json.dump(schedule, f, indent=2)
        total = sum(len(u["jobs"]) for u in schedule["users"])
        print(f"wrote {args.file}: {len(schedule['users'])} users, "
              f"{total} jobs over {args.duration_s}s")
        return 0
    if args.cmd == "simulate":
        with open(args.file, encoding="utf-8") as f:
            schedule = json.load(f)
        results = run_simulation(
            schedule, args.url, time_scale=args.time_scale,
            settle_timeout_s=args.settle_timeout_s,
            fake_hints=not args.no_fake_hints)
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(results, f, indent=2)
        print(f"wrote {args.out}: {len(results['jobs'])} jobs in "
              f"{results['wall_s']}s wall ({len(results['errors'])} "
              "submit errors)")
        return 0
    with open(args.file, encoding="utf-8") as f:
        results = json.load(f)
    print(json.dumps(build_report(results), indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
