from .simulator import (  # noqa: F401
    SimResult,
    Simulator,
    generate_example_hosts,
    generate_example_trace,
    load_hosts,
    load_trace,
)
