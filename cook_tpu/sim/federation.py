"""Full-cell-outage chaos for the federation front door.

``python -m cook_tpu.sim --chaos --cell-outage [--cells N] [--soak]``
assembles N REAL cells in one process — each a Store + FakeCluster +
Scheduler + CookApi on its own threaded HTTP server, journal-backed so
commit tokens mint — puts the federation router in front, drives
multi-user traffic (plain batches and whole gangs) through the front
door, then KILLS one cell's server mid-stream and reclaims it.

The run fails (exit 1) unless every survival invariant holds:

1. **zero lost committed submissions** — every batch the front door
   positively acknowledged is queryable through the front door after
   the outage (the dead cell's accepted demand re-lands on survivors
   via the commit ledger's mea-culpa re-route, Reasons.CELL_RECLAIMED);
2. **whole-gang re-route** — every gang's members live on ONE cell
   after the outage: a gang re-lands whole or not at all, never split;
3. **surviving-cell read-your-writes** — the client's cell-qualified
   session token still gates reads on surviving cells, and reads that
   can no longer be fresh with respect to the dead cell say so in
   ``X-Cook-Federation-Stale-Cells`` instead of faking freshness;
4. **no breaker cascade** — the dead cell's breaker opens; every
   surviving cell's breaker stays closed (the survivors never absorb
   the dead cell's failures);
5. **goodput continues** — surviving cells schedule and run the
   re-routed demand (the outage degrades capacity, not the service).
"""

from __future__ import annotations

import json
import shutil
import tempfile
import urllib.request
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..client import JobClient
from ..cluster import FakeCluster, FakeHost
from ..config import Config
from ..rest import ApiServer, CookApi
from ..sched import Scheduler
from ..state import Resources, Store

__all__ = ["CellOutageConfig", "CellOutageResult", "run_cell_outage"]


@dataclass
class CellOutageConfig:
    seed: int = 0
    n_cells: int = 2
    #: batches submitted before + after the kill (half each side)
    n_batches: int = 16
    #: every k-th batch is a whole gang
    gang_every: int = 4
    gang_size: int = 3
    n_users: int = 3
    hosts_per_cell: int = 3
    #: soak mode (the slow tier): more cells, much more traffic
    soak: bool = False

    def __post_init__(self):
        if self.soak:
            self.n_cells = max(self.n_cells, 3)
            self.n_batches = max(self.n_batches, 80)
        if self.n_cells < 2:
            raise ValueError("--cell-outage needs at least 2 cells "
                             "(one dies, the rest must carry it)")


@dataclass
class _Cell:
    cell_id: str
    data_dir: str
    store: Store
    cluster: FakeCluster
    sched: Scheduler
    api: CookApi
    server: ApiServer


@dataclass
class CellOutageResult:
    ok: bool = False
    violations: List[str] = field(default_factory=list)
    cells: int = 0
    batches_acked: int = 0
    jobs_acked: int = 0
    gangs: int = 0
    victim: str = ""
    acked_before_kill: int = 0
    rerouted_batches: int = 0
    rerouted_jobs: int = 0
    lost_jobs: int = 0
    split_gangs: int = 0
    running_after: int = 0
    stale_cells_header: str = ""
    breaker_states: Dict[str, str] = field(default_factory=dict)

    def summary(self) -> Dict[str, Any]:
        return {
            "ok": self.ok,
            "violations": self.violations,
            "cells": self.cells,
            "victim": self.victim,
            "batches_acked": self.batches_acked,
            "jobs_acked": self.jobs_acked,
            "gangs": self.gangs,
            "acked_before_kill": self.acked_before_kill,
            "rerouted_batches": self.rerouted_batches,
            "rerouted_jobs": self.rerouted_jobs,
            "lost_jobs": self.lost_jobs,
            "split_gangs": self.split_gangs,
            "running_after": self.running_after,
            "stale_cells_header": self.stale_cells_header,
            "breaker_states": self.breaker_states,
        }


def _make_cell(name: str, n_hosts: int) -> _Cell:
    data_dir = tempfile.mkdtemp(prefix=f"cook-cell-{name}-")
    store = Store.open(data_dir)
    cluster = FakeCluster(
        f"{name}-cluster",
        [FakeHost(f"{name}-h{i}", Resources(cpus=8, mem=8192))
         for i in range(n_hosts)])
    cfg = Config()
    cfg.default_matcher.backend = "cpu"
    sched = Scheduler(store, cfg, [cluster], rank_backend="cpu")
    api = CookApi(store, scheduler=sched, config=cfg)
    server = ApiServer(api)
    server.start()
    return _Cell(name, data_dir, store, cluster, sched, api, server)


def _step_all(cells: List[_Cell]) -> None:
    for cell in cells:
        cell.sched.step_rank()
        cell.sched.step_match()


def run_cell_outage(config: Optional[CellOutageConfig] = None
                    ) -> CellOutageResult:
    cc = config or CellOutageConfig()
    res = CellOutageResult(cells=cc.n_cells)
    from ..federation.rest import build_federation_node

    cells = [_make_cell(f"cell{i}", cc.hosts_per_cell)
             for i in range(cc.n_cells)]
    by_id = {c.cell_id: c for c in cells}
    fed = build_federation_node(
        {"cells": [{"id": c.cell_id, "url": c.server.url}
                   for c in cells],
         # tight enough that a dead cell trips fast, loose enough that
         # one slow accept does not
         "breaker_failures": 2, "breaker_reset_seconds": 30.0,
         "request_timeout_seconds": 5.0})
    fed.start()
    router = fed.router
    clients = [JobClient(fed.url, user=f"user{u}")
               for u in range(cc.n_users)]

    #: batch index -> {"uuids": [...], "gang": bool, "client": idx}
    acked: List[Dict[str, Any]] = []
    import uuid as _uuid

    def submit_batch(i: int) -> None:
        client = clients[i % cc.n_users]
        gang = cc.gang_every > 0 and i % cc.gang_every == 0
        if gang:
            g = str(_uuid.uuid4())
            specs = [{"command": f"sleep-{i}", "cpus": 1.0, "mem": 128.0,
                      "group": g, "labels": {"sim/duration_ms": "60000"}}
                     for _ in range(cc.gang_size)]
            uuids = client.submit(
                specs, groups=[{"uuid": g,
                                "gang": {"size": cc.gang_size}}])
        else:
            specs = [{"command": f"run-{i}-{j}", "cpus": 1.0,
                      "mem": 128.0,
                      "labels": {"sim/duration_ms": "60000"}}
                     for j in range(2)]
            uuids = client.submit(specs)
        acked.append({"uuids": uuids, "gang": gang,
                      "client": i % cc.n_users})

    try:
        half = cc.n_batches // 2
        for i in range(half):
            submit_batch(i)
        res.acked_before_kill = sum(len(b["uuids"]) for b in acked)
        _step_all(cells)

        # ---- the outage: hard-stop one cell that actually owns demand
        owned = {}
        for b in acked:
            c = router.cell_of_uuid(b["uuids"][0])
            owned[c] = owned.get(c, 0) + 1
        victim_id = max(owned, key=lambda k: owned[k]) \
            if owned else cells[0].cell_id
        res.victim = victim_id
        # hard kill: listener closed AND established keep-alive
        # connections severed, exactly what a dead process looks like
        # from the router's socket pool
        by_id[victim_id].server.kill()

        # ---- traffic continues: every post-kill batch must still land
        for i in range(half, cc.n_batches):
            submit_batch(i)

        # ---- reclaim: the dead cell's ACCEPTED demand re-routes whole
        reclaim = router.reclaim_cell(victim_id)
        res.rerouted_batches = len(reclaim["rerouted_batches"])
        res.rerouted_jobs = sum(b["jobs"]
                                for b in reclaim["rerouted_batches"])
        if reclaim["failed_batches"]:
            res.violations.append(
                f"{len(reclaim['failed_batches'])} ledgered batches of "
                f"{victim_id} could not be re-routed: "
                f"{reclaim['failed_batches'][:3]}")
        if not reclaim["mea_culpa"]:
            res.violations.append(
                "cell reclaim must be mea-culpa (free retries)")

        survivors = [c for c in cells if c.cell_id != victim_id]
        _step_all(survivors)

        res.batches_acked = len(acked)
        res.jobs_acked = sum(len(b["uuids"]) for b in acked)
        res.gangs = sum(1 for b in acked if b["gang"])

        # ---- invariant 1: zero lost committed submissions
        for b in acked:
            for u in b["uuids"]:
                try:
                    clients[b["client"]].job(u)
                except Exception as exc:
                    res.lost_jobs += 1
                    if len(res.violations) < 5:
                        res.violations.append(
                            f"acked job {u} lost after outage: {exc}")

        # ---- invariant 2: whole-gang re-route (never split)
        for b in acked:
            if not b["gang"]:
                continue
            owners = {router.cell_of_uuid(u) for u in b["uuids"]}
            if len(owners) != 1 or None in owners:
                res.split_gangs += 1
                res.violations.append(
                    f"gang split across cells {owners} "
                    f"(uuids {b['uuids'][:2]}...)")

        # ---- invariant 3: surviving-cell read-your-writes + honest
        # staleness toward the dead cell
        probe = clients[0]
        token = probe.last_commit_offset or ""
        if not any(token.startswith(s.cell_id + "/")
                   or ("," + s.cell_id + "/") in ("," + token)
                   for s in survivors):
            res.violations.append(
                f"session token {token!r} names no surviving cell — "
                "read-your-writes cannot span the outage")
        some_uuid = acked[0]["uuids"][0]
        req = urllib.request.Request(
            f"{fed.url}/jobs/{some_uuid}",
            headers={"X-Cook-User": probe.user,
                     "X-Cook-Min-Offset": token} if token else {})
        with urllib.request.urlopen(req) as r:
            res.stale_cells_header = \
                r.headers.get("X-Cook-Federation-Stale-Cells", "")
        if token and victim_id in {c for e in token.split(",")
                                   for c in [e.partition("/")[0]]} \
                and victim_id not in res.stale_cells_header:
            res.violations.append(
                f"token names {victim_id} but the read did not declare "
                "it stale (X-Cook-Federation-Stale-Cells="
                f"{res.stale_cells_header!r}) — staleness must be "
                "honest, never faked fresh")

        # ---- invariant 4: breaker opens on the victim ONLY
        for cid, handle in router.cells.items():
            res.breaker_states[cid] = handle.breaker.state
        if res.breaker_states.get(victim_id) not in ("open", "half-open"):
            res.violations.append(
                f"victim breaker is {res.breaker_states.get(victim_id)!r}"
                " — a dead cell must trip its breaker")
        for c in survivors:
            if res.breaker_states.get(c.cell_id) != "closed":
                res.violations.append(
                    f"survivor {c.cell_id} breaker "
                    f"{res.breaker_states.get(c.cell_id)!r}: the dead "
                    "cell's failures cascaded")

        # ---- invariant 5: survivors keep scheduling (goodput)
        _step_all(survivors)
        res.running_after = sum(
            len(c.store.running_instances()) for c in survivors)
        if res.running_after == 0 and res.jobs_acked > 0:
            res.violations.append(
                "no instance running on any survivor after the outage")

        res.ok = not res.violations
        return res
    finally:
        fed.stop()
        for c in cells:
            if c.cell_id != res.victim:
                try:
                    c.server.stop()
                except Exception:
                    pass
            shutil.rmtree(c.data_dir, ignore_errors=True)


def main_summary(res: CellOutageResult) -> str:  # pragma: no cover
    return json.dumps(res.summary(), indent=2)
