"""Chaos-mode simulator: a workload driven under an injected fault
schedule, with the robustness invariants asserted, not assumed.

The fault-injection counterpart of the faster-than-real-time simulator
(Basiri et al., *Chaos Engineering*, IEEE Software 2016; Borg treats
failover/requeue behavior as first-class tested behavior, Verma et al.,
EuroSys 2015): replay a generated trace against the REAL scheduler +
store + fake cluster on a virtual clock while injecting

- **node loss** — a loaded host's tasks all fail ``NODE_LOST``
  (mea-culpa) on a fixed cadence;
- **launch RPC faults** — ``utils/faults.py`` point ``cluster.launch``
  rejects backend launches with a seeded probability (mea-culpa
  ``pod-submission-failed``), feeding the per-cluster circuit breaker;
- **one leader kill + promotion** — the leader "crashes" between the
  match transaction and the backend launch-ack (the classic
  crash-consistency window), the journal is reopened the way a promoted
  follower re-reads state, and scheduling resumes.

Invariants checked (violations are collected, not raised, so a run
reports everything it broke):

1. every job reaches a terminal state;
2. retry budgets are only consumed by non-mea-culpa failures (chaos only
   injects mea-culpa faults, so every job must end with
   ``attempts_used == 0``);
3. no job ever has two concurrently-live instances (checked every tick,
   and cross-checked against the backend's running set);
4. promotion loses zero committed transactions: the reopened store's
   state equals the pre-crash store's state, byte-for-value, and the
   final journal replays to exactly the final in-memory state.

Run it:  ``python -m cook_tpu.sim --chaos [--seed N]`` or
``pytest -m chaos``; see docs/ROBUSTNESS.md.
"""

from __future__ import annotations

import json
import os
import random
import tempfile
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..cluster.fake import FakeCluster
from ..config import Config
from ..sched.scheduler import Scheduler
from ..state.integrity import JournalCorruptionError
from ..state.schema import InstanceStatus, JobState, Reasons
from ..state.store import Store
from ..utils.faults import injector
from ..utils.flight import recorder as flight_recorder
from ..utils.retry import breakers
from .simulator import (
    generate_example_hosts,
    generate_example_trace,
    load_hosts,
    load_trace,
)


@dataclass
class ChaosConfig:
    seed: int = 0
    n_jobs: int = 40
    n_users: int = 4
    n_hosts: int = 8
    submit_span_ms: int = 30_000
    job_duration_ms: int = 6_000
    tick_ms: int = 1_000
    # fault schedule.  node_loss_max stays BELOW n_hosts: the novel-host
    # constraint permanently excludes a job's failed hosts, so losing
    # every host at least once could make an unlucky job unschedulable
    # forever — a real small-cluster liveness hazard, but not the
    # invariant under test here
    node_loss_every_ms: int = 9_000
    node_loss_max: int = 5
    rpc_fault_probability: float = 0.15
    # cap on injected RPC rejects: each reject marks one host failed for
    # the job (novel-host), so an unbounded storm over a small pool can
    # legitimately exclude every host for an unlucky job
    rpc_fault_max: Optional[int] = None
    leader_kill_at_ms: Optional[int] = 15_000
    # breaker policy (virtual-clock): small threshold so chaos actually
    # exercises trip + half-open heal inside a short run
    breaker_failure_threshold: int = 3
    breaker_reset_timeout_s: float = 5.0
    max_virtual_ms: int = 30 * 60 * 1000
    data_dir: Optional[str] = None   # journal dir; tempdir when None
    # > 0 drives the PRODUCTION pipelined fused cycle (sched/pipeline.py,
    # Scheduler.step_cycle) under the fault schedule instead of the split
    # host path — the no-duplicate-live-instances invariant is checked
    # every tick against the overlapped optimistic dispatches
    pipeline_depth: int = 0
    # gang chaos (docs/GANG.md): n_gangs all-or-nothing groups of
    # gang_size members ride the trace; hosts get slice-id topology
    # attributes in gang_size-sized slices, and the zero-partial-gangs
    # invariant is checked every tick — node loss, launch-RPC faults,
    # and a leader kill landing mid-gang-launch must all leave either a
    # whole gang or no gang, never a partial one
    n_gangs: int = 0
    gang_size: int = 3
    gang_topology: bool = True
    # one gang is timed to submit just before the leader kill so the
    # crash window reliably lands inside a gang launch
    gang_at_kill: bool = True
    # ELASTIC gang chaos (docs/GANG.md elasticity): gangs declare
    # gang_min = max(1, gang_size // 2) and may legally run anywhere in
    # [min, size].  The zero-partial invariant becomes "live == 0 or
    # live (+completed) >= gang_min" every tick; a grace SHRINK is
    # requested just before the leader kill so the crash window races
    # the resize ledger — the shrink may be delayed by failover (the
    # in-memory deadline dies with the leader) but must never be
    # half-applied or lose a member
    elastic: bool = False
    # resident-mode chaos (ISSUE 7, docs/PERFORMANCE.md): drive the
    # fused cycle off the columnar index with the DEVICE-RESIDENT pack
    # on (the production wire form), optionally storming the
    # delta.extract / delta.apply fault points — every hit must degrade
    # that cycle to a clean full repack (cook_kernel_fallback_total,
    # cook_resident_repack_total{reason="fault"}) while scheduling
    # continues, and the leader kill's journal-replay promotion must
    # rebuild the resident pack from scratch on the successor's driver
    resident: bool = False
    delta_fault_probability: float = 0.0
    # overload chaos (ISSUE 17, docs/ROBUSTNESS.md): run the admission
    # controller in the loop — a small launch-token bucket on the
    # virtual clock drives saturation genuinely, monitor sweeps run
    # every tick, and the brownout ladder engages BEFORE the leader
    # kill.  The invariant under test: the promoted leader's controller
    # restores the journaled brownout stage (the flip rode the
    # dynamic-config journal record), so a failover mid-brownout never
    # resets the ladder to "everything open" under standing overload
    overload: bool = False
    overload_launch_rate_per_min: float = 30.0
    overload_launch_burst: float = 2.0
    # disk-fault chaos (docs/ROBUSTNESS.md WAL v2): silent bit flips on
    # the leader's journal stream at this per-append probability
    # (``store.journal.bitflip``).  The leader-kill leg then asserts
    # the storage-integrity contract end to end: the scrub self-heal
    # detects and repairs every flip (checkpoint from the in-memory
    # authority), and promotion replays with zero committed-txn loss —
    # a flip the scrub missed would REFUSE the successor's open
    disk_fault_probability: float = 0.0


@dataclass
class ChaosResult:
    total: int = 0
    completed: int = 0
    gangs: int = 0
    gang_requeues: int = 0
    # elastic chaos (docs/GANG.md elasticity)
    elastic_grows: int = 0
    elastic_shrinks: int = 0
    shrink_at_kill: str = ""   # outcome of the shrink racing the kill
    violations: List[str] = field(default_factory=list)
    node_losses: int = 0
    rpc_faults: int = 0
    delta_faults: int = 0
    # audit-trail continuity (docs/OBSERVABILITY.md): True when the
    # promoted leader's journal replay reconstructed a pre-kill job's
    # full timeline (submit -> ranked -> launched) — `cs why` keeps
    # answering across the failover
    audit_timeline_ok: bool = True
    leader_kills: int = 0
    intents_open_at_kill: int = 0
    relaunched_after_kill: int = 0
    breaker_trips: int = 0
    user_retries_charged: int = 0
    makespan_ms: int = 0
    flight: Dict = field(default_factory=dict)
    # overload chaos: the ladder's state across the failover
    brownout_stage_at_kill: int = -1
    brownout_stage_recovered: int = -1
    min_admission_level: float = 1.0
    # disk-fault chaos: journal corruptions the pre-promotion scrub
    # detected and healed (each one was a silent bit flip the CRC
    # envelope caught)
    disk_corruptions_healed: int = 0

    @property
    def ok(self) -> bool:
        return not self.violations

    def summary(self) -> Dict:
        return {
            "ok": self.ok,
            "jobs_total": self.total,
            "jobs_completed": self.completed,
            "gangs": self.gangs,
            "gang_requeues": self.gang_requeues,
            "elastic_grows": self.elastic_grows,
            "elastic_shrinks": self.elastic_shrinks,
            "shrink_at_kill": self.shrink_at_kill,
            "violations": list(self.violations),
            "node_losses": self.node_losses,
            "rpc_faults": self.rpc_faults,
            "delta_faults": self.delta_faults,
            "audit_timeline_ok": self.audit_timeline_ok,
            "leader_kills": self.leader_kills,
            "intents_open_at_kill": self.intents_open_at_kill,
            "relaunched_after_kill": self.relaunched_after_kill,
            "breaker_trips": self.breaker_trips,
            "user_retries_charged": self.user_retries_charged,
            "makespan_virtual_s": self.makespan_ms / 1000.0,
            "brownout_stage_at_kill": self.brownout_stage_at_kill,
            "brownout_stage_recovered": self.brownout_stage_recovered,
            "min_admission_level": round(self.min_admission_level, 4),
            "disk_corruptions_healed": self.disk_corruptions_healed,
            "flight": self.flight,
        }


def _scrub_heal(store: Store, result: "ChaosResult") -> None:
    """Drain the background-scrub contract over the whole journal in
    one call: disarm the flip point, then scrub windows until the file
    verifies end to end, healing every CRC hit via the checkpoint
    self-repair (state/store.py Store.scrub)."""
    injector.disarm("store.journal.bitflip")
    while True:
        doc = store.scrub(max_bytes=1 << 20, repair=True)
        if doc.get("corrupt"):
            result.disk_corruptions_healed += 1
            if not doc.get("repaired"):
                result.violations.append(
                    "disk-fault scrub detected corruption but failed "
                    f"to self-heal: {doc}")
                return
            continue
        if not doc.get("enabled") or doc.get("verified_offset", 0) \
                >= doc.get("journal_bytes", 0):
            return


class _LeaderCrash(BaseException):
    """Simulated process death mid-launch.  BaseException so no
    defensive ``except Exception`` on the dispatch path can swallow the
    'crash' and ack the launch anyway."""


def _scheduler_config(cc: ChaosConfig) -> Config:
    cfg = Config()
    if cc.pipeline_depth > 0 or cc.resident:
        # production fused cycle under chaos (pipelined when depth > 0):
        # overlapped optimistic dispatches + reconciliation are exactly
        # what the duplicate-live invariant must hold against
        cfg.cycle_mode = "fused"
        cfg.pipeline.depth = cc.pipeline_depth
    else:
        # deterministic host path: the chaos run asserts scheduling
        # INVARIANTS, not kernel behavior (kernel fallback has its own
        # tests)
        cfg.cycle_mode = "split"
        cfg.pipeline.depth = 0
    # resident mode needs the columnar compact wire form; otherwise the
    # entity pack keeps chaos deterministic as before
    cfg.columnar_index = bool(cc.resident)
    cfg.resident_pack = bool(cc.resident)
    cfg.default_matcher.backend = "cpu"
    cfg.circuit_breaker.failure_threshold = cc.breaker_failure_threshold
    cfg.circuit_breaker.reset_timeout_s = cc.breaker_reset_timeout_s
    if cc.overload:
        # admission ladder in the loop (sched/admission.py), tuned so
        # the stage flips land well before the leader kill
        cfg.admission.enabled = True
        cfg.admission.stage_hold_seconds = 4.0
    return cfg


def run_chaos(cc: Optional[ChaosConfig] = None) -> ChaosResult:
    cc = cc or ChaosConfig()
    data_dir = cc.data_dir or tempfile.mkdtemp(prefix="cook-chaos-")
    rng = random.Random(cc.seed)
    trace = load_trace(generate_example_trace(
        cc.n_jobs, n_users=cc.n_users, seed=cc.seed,
        span_ms=cc.submit_span_ms, duration_ms=cc.job_duration_ms))
    hosts = load_hosts(generate_example_hosts(cc.n_hosts, seed=cc.seed))

    # gang workload (docs/GANG.md): n_gangs groups of gang_size members,
    # uniform duration (members complete together), hosts carved into
    # gang_size-wide topology slices
    from ..state.schema import Group, Job, Resources
    gang_jobs: List[Job] = []
    gang_sets: List[tuple] = []  # (submit_ms, [jobs], Group)
    gang_index: Dict[str, List[str]] = {}
    if cc.n_gangs > 0:
        if cc.gang_topology:
            for i, h in enumerate(hosts):
                h.attributes["slice-id"] = f"s{i // cc.gang_size}"
        t0 = trace[0].submit_time_ms if trace else 0
        for k in range(cc.n_gangs):
            submit = t0 + (k + 1) * cc.submit_span_ms // (cc.n_gangs + 1)
            if (cc.gang_at_kill and k == cc.n_gangs - 1
                    and cc.leader_kill_at_ms is not None):
                # the last gang lands just before the leader kill so the
                # crash window reliably interrupts a gang launch
                submit = max(t0, t0 + cc.leader_kill_at_ms - cc.tick_ms)
            guuid = f"gang-{k}"
            members = [Job(
                uuid=f"{guuid}-m{i}", user=f"gang{k % cc.n_users}",
                command="sim", group=guuid,
                resources=Resources(cpus=2.0, mem=256.0),
                max_retries=3, submit_time_ms=submit,
                labels={"sim/duration_ms": str(cc.job_duration_ms)})
                for i in range(cc.gang_size)]
            gang_min = max(1, cc.gang_size // 2) if cc.elastic else 0
            group = Group(
                uuid=guuid, gang=True, gang_size=cc.gang_size,
                gang_min=gang_min,
                gang_max=cc.gang_size if cc.elastic else 0,
                gang_topology="slice-id" if cc.gang_topology else None,
                jobs=[m.uuid for m in members])
            gang_jobs.extend(members)
            gang_sets.append((submit, members, group))
            gang_index[guuid] = [m.uuid for m in members]
        gang_sets.sort(key=lambda s: s[0])

    result = ChaosResult(total=len(trace) + len(gang_jobs),
                         gangs=cc.n_gangs)
    if not trace and not gang_jobs:
        return result

    now_box = [trace[0].submit_time_ms if trace else gang_sets[0][0]]
    clock = lambda: now_box[0]  # noqa: E731 - one timebase for everything

    # process-global planes: seed/arm for this run, restore after
    injector.clear()
    injector.reseed(cc.seed)
    breakers.reset()
    breakers.configure(failure_threshold=cc.breaker_failure_threshold,
                       reset_timeout_s=cc.breaker_reset_timeout_s,
                       clock=lambda: now_box[0] / 1000.0)
    if cc.rpc_fault_probability > 0:
        injector.arm("cluster.launch",
                     probability=cc.rpc_fault_probability,
                     max_fires=cc.rpc_fault_max)
    if cc.delta_fault_probability > 0:
        # resident-pack kernel faults: extraction and scatter-apply each
        # degrade that cycle to a full repack, never kill it (both armed
        # at the configured per-call probability, as --delta-faults
        # documents)
        injector.arm("delta.extract",
                     probability=cc.delta_fault_probability)
        injector.arm("delta.apply",
                     probability=cc.delta_fault_probability)
    if cc.disk_fault_probability > 0:
        # silent media rot under the live appender: no error surfaces
        # at flip time by design — the CRC envelope must catch it at
        # scrub/replay (state/integrity.py)
        injector.arm("store.journal.bitflip",
                     probability=cc.disk_fault_probability)
    flight_seq0 = flight_recorder.last_seq()

    cfg = _scheduler_config(cc)
    store = Store.open(data_dir)
    store.clock = clock
    cluster = FakeCluster("chaos", hosts)
    cluster.job_durations_ms = {
        j.uuid: int(j.labels["sim/duration_ms"])
        for j in list(trace) + gang_jobs}
    # overload mode: a small launch-token bucket on the virtual clock is
    # the genuine saturation driver the monitor sweep reads (the same
    # RateLimits object survives the failover — token debt is leader
    # memory, the journaled brownout STAGE is the durable part)
    rate_limits = None
    if cc.overload:
        from ..policy import RateLimits, TokenBucketRateLimiter
        rate_limits = RateLimits(job_launch=TokenBucketRateLimiter(
            cc.overload_launch_rate_per_min, cc.overload_launch_burst,
            enforce=True, clock=lambda: now_box[0] / 1000.0))
    scheduler = Scheduler(store, cfg, [cluster], rank_backend="cpu",
                          rate_limits=rate_limits)

    def check_single_live(when: str) -> None:
        live_by_job: Dict[str, int] = {}
        for job, inst in store.running_instances():
            live_by_job[job.uuid] = live_by_job.get(job.uuid, 0) + 1
        for uuid, n in live_by_job.items():
            if n > 1:
                result.violations.append(
                    f"{when}: job {uuid} has {n} live instances")
        # backend cross-check: every task the cluster runs maps to a
        # still-live store instance (no zombie double-running attempt)
        for tid in cluster.running_task_ids():
            inst = store.instance(tid)
            if inst is None or inst.status not in (
                    InstanceStatus.UNKNOWN, InstanceStatus.RUNNING):
                result.violations.append(
                    f"{when}: cluster runs {tid} but store says "
                    f"{inst.status.value if inst else 'missing'}")

    # the elastic legal minimum (docs/GANG.md elasticity); None = rigid
    gang_lo = max(1, cc.gang_size // 2) if cc.elastic else None

    def check_no_partial_gang(when: str) -> None:
        """THE gang invariant (docs/GANG.md): at every consistent point,
        a gang is whole or absent — never a strict subset of members
        holding capacity while the rest wait.  ELASTIC gangs relax
        "whole" to "at least gang_min live (or wound down to
        completion)": any live count in [min, size] is a legal size,
        below min is the same partial-gang hazard as before."""
        for guuid, member_uuids in gang_index.items():
            live = completed = known = 0
            for uuid in member_uuids:
                j = store.job(uuid)
                if j is None:
                    continue
                known += 1
                if any((mi := store.instance(t)) is not None
                       and mi.status in (InstanceStatus.UNKNOWN,
                                         InstanceStatus.RUNNING)
                       for t in j.instances):
                    live += 1
                elif j.state is JobState.COMPLETED:
                    completed += 1
            whole = known if gang_lo is None else min(known, gang_lo)
            if known and live and live + completed < whole:
                result.violations.append(
                    f"{when}: gang {guuid} partial — {live} live + "
                    f"{completed} completed of {known} members "
                    f"(requires {whole})")

    def fail_one_node() -> None:
        if result.node_losses >= cc.node_loss_max:
            return
        with cluster._lock:
            loaded: Dict[str, List[str]] = {}
            for tid, t in cluster._tasks.items():
                loaded.setdefault(t.spec.hostname, []).append(tid)
        if not loaded:
            return
        host = rng.choice(sorted(loaded))
        result.node_losses += 1
        for tid in loaded[host]:
            cluster.fail_task(tid, Reasons.NODE_LOST.code)

    # jobs whose dispatch the leader kill interrupted, with their
    # instance counts at kill time: a post-kill instance PROVES the
    # refund->relaunch path ran (reported as relaunched_after_kill)
    crashed_jobs: Dict[str, int] = {}

    def find_surplus_member():
        """A (task_id, job_uuid, gang_uuid) of a RUNNING elastic gang
        member above gang_min — a legal grace-shrink victim."""
        for guuid, member_uuids in gang_index.items():
            live = []
            for uuid in member_uuids:
                j = store.job(uuid)
                if j is None:
                    continue
                for t in j.instances:
                    mi = store.instance(t)
                    if mi is not None and mi.status in (
                            InstanceStatus.UNKNOWN,
                            InstanceStatus.RUNNING):
                        live.append((t, uuid))
            if gang_lo is not None and len(live) > gang_lo:
                tid, uuid = live[-1]
                return tid, uuid, guuid
        return None

    def kill_leader_and_promote() -> None:
        nonlocal store, scheduler
        result.leader_kills += 1
        stage_at_kill = (scheduler.admission.stage
                         if scheduler.admission is not None else -1)
        # elastic: open a grace shrink RIGHT before the crash so the
        # kill window races the resize ledger (docs/GANG.md elasticity:
        # a shrink may be DELAYED by failover — the in-memory deadline
        # dies with the leader — but never half-applied)
        racing_shrink = None
        if cc.elastic:
            victim = find_surplus_member()
            if victim is not None:
                tid, juuid, guuid = victim
                scheduler.elastic.request_shrink(
                    tid, juuid, guuid, cluster.name, scheduler.clusters,
                    reason="chaos-race")
                racing_shrink = tid
        # crash INSIDE the match->launch window: the guard transaction
        # (instances + intents) commits, the backend dispatch never lands
        orig_launch = FakeCluster.launch_tasks

        def crash(self, pool, specs):
            raise _LeaderCrash()

        FakeCluster.launch_tasks = crash
        try:
            if cc.pipeline_depth > 0 or cc.resident:
                scheduler.step_cycle()
            else:
                scheduler.step_rank()
                scheduler.step_match()
        except _LeaderCrash:
            pass
        finally:
            FakeCluster.launch_tasks = orig_launch
        open_intents = store.launch_intents()
        result.intents_open_at_kill = len(open_intents)
        for intent in open_intents:
            j = store.job(intent["job_uuid"])
            if j is not None:
                crashed_jobs[j.uuid] = len(j.instances)
        # audit-continuity probe: a job LAUNCHED in an earlier (fully
        # flushed) cycle — after promotion its timeline must replay
        # whole from the journal.  Crash-window jobs are excluded: their
        # launch rode the txn record, but the interrupted cycle's
        # advisory flush legitimately never ran.
        probe_uuid = next(
            (j.uuid for j, _i in store.running_instances()
             if j.uuid not in crashed_jobs), None)
        if cc.disk_fault_probability > 0:
            # drain the background-scrub contract before the crash: the
            # injected flips are SILENT, so promotion only survives if
            # the CRC scrub detects every one and self-heals (checkpoint
            # from the in-memory authority).  A missed flip refuses the
            # successor's open below — that's the violation under test.
            _scrub_heal(store, result)
        pre = json.loads(store.snapshot())
        store.close()  # crash-equivalent: no checkpoint, journal as-is
        # promotion: the successor re-reads everything the dead leader
        # committed (snapshot + journal replay)
        try:
            store = Store.open(data_dir)
        except JournalCorruptionError as e:
            # a flip the scrub heal missed: committed history is
            # unreadable — record the contract violation, then restore
            # the pre-crash snapshot so the rest of the run still
            # reports its other invariants
            result.violations.append(
                "promotion refused the journal after the scrub heal: "
                f"{e}")
            from ..state.repair import quarantine
            from ..utils.fsatomic import write_atomic_text
            quarantine(data_dir)
            write_atomic_text(
                os.path.join(data_dir, "snapshot.json"),
                json.dumps(pre))
            store = Store.open(data_dir)
        post = json.loads(store.snapshot())
        # tx_id counts every transaction including write-free ones (an
        # all-deny launch guard journals nothing); entity state is the
        # committed truth being compared
        pre.pop("tx_id", None)
        post.pop("tx_id", None)
        if post != pre:
            result.violations.append(
                "promotion lost committed transactions: replayed state "
                "differs from the pre-crash store")
        if probe_uuid is not None:
            # the NEW store's trail was rebuilt purely from journal
            # replay (the old process's in-memory trail died with it):
            # `cs why` on a pre-kill job must still show the lifecycle
            kinds = {e["kind"] for e in store.audit.timeline(probe_uuid)}
            expect = {"submitted", "ranked", "launched"}
            if cc.overload and stage_at_kill >= 1:
                # brownout stage >= 1 sheds ADVISORY observability: the
                # ranked lane's advisory flushes fold by design
                # (utils/audit.py shed_advisory) — only the journal-
                # transaction-backed kinds must survive the failover
                expect = {"submitted", "launched"}
            missing = expect - kinds
            if missing:
                result.audit_timeline_ok = False
                result.violations.append(
                    f"audit trail lost across failover: job "
                    f"{probe_uuid} timeline missing {sorted(missing)} "
                    f"after promotion (has {sorted(kinds)})")
        store.clock = clock
        # the new leader adopts the (still-running) cluster and sweeps
        # the open launch intents in its constructor
        scheduler = Scheduler(store, cfg, [cluster], rank_backend="cpu",
                              rate_limits=rate_limits)
        if cc.overload:
            # the promoted controller must RESTORE the journaled
            # brownout stage (sched/admission.py restore()): a failover
            # mid-brownout that reset the ladder would reopen every
            # shed path under standing overload — the metastable trap
            recovered = (scheduler.admission.stage
                         if scheduler.admission is not None else -1)
            result.brownout_stage_at_kill = stage_at_kill
            result.brownout_stage_recovered = recovered
            if recovered != stage_at_kill:
                result.violations.append(
                    f"promotion lost the brownout stage: was "
                    f"{stage_at_kill} at kill, restored {recovered}")
        if racing_shrink is not None:
            # never half-applied: after promotion the victim is either
            # UNTOUCHED (ledger + deadline died with the leader — the
            # shrink was delayed) or cleanly shed with the mea-culpa
            # gang-resized reason; anything else is a violation
            mi = store.instance(racing_shrink)
            if mi is None:
                result.violations.append(
                    "shrink-at-kill: victim instance vanished")
                result.shrink_at_kill = "lost"
            elif mi.status in (InstanceStatus.UNKNOWN,
                               InstanceStatus.RUNNING):
                result.shrink_at_kill = "delayed"
            elif mi.reason_code == Reasons.GANG_RESIZED.code:
                result.shrink_at_kill = "applied"
            elif mi.status is InstanceStatus.SUCCESS:
                result.shrink_at_kill = "completed"
            else:
                result.violations.append(
                    f"shrink-at-kill: victim {racing_shrink} ended "
                    f"{mi.status.value}/{mi.reason_code} — neither "
                    "delayed nor a clean gang-resized shed")
                result.shrink_at_kill = "corrupt"

    pending = list(trace)
    pending_gangs = list(gang_sets)
    last_submits = [s[0] for s in pending_gangs]
    if pending:
        last_submits.append(pending[-1].submit_time_ms)
    deadline = max(last_submits) + cc.max_virtual_ms
    start_ms = now_box[0]
    next_node_loss = start_ms + cc.node_loss_every_ms
    kill_at = (start_ms + cc.leader_kill_at_ms
               if cc.leader_kill_at_ms is not None else None)
    # elastic: drive ordinary grace shrinks through the run (up to 3,
    # spaced so at least one grace window expires AWAY from the leader
    # kill and actually executes; the kill gets its own racing shrink)
    shrink_at = (start_ms + (cc.leader_kill_at_ms or 20_000) // 2
                 if cc.elastic else None)
    shrinks_requested = 0
    breaker = breakers.get(cluster.name)
    last_breaker_state = breaker.state

    while now_box[0] <= deadline:
        now = now_box[0]
        while pending and pending[0].submit_time_ms <= now:
            store.create_jobs([pending.pop(0)])
        while pending_gangs and pending_gangs[0][0] <= now:
            _t, members, group = pending_gangs.pop(0)
            store.create_jobs(members, groups=[group])
        if kill_at is not None and now >= kill_at:
            kill_at = None
            kill_leader_and_promote()
        if now >= next_node_loss:
            next_node_loss = now + cc.node_loss_every_ms
            fail_one_node()
        if cc.pipeline_depth > 0 or cc.resident:
            scheduler.step_cycle()
        else:
            scheduler.step_rank()
            scheduler.step_match()
        scheduler.step_reapers(current_ms=now)
        if cc.overload:
            # the production control loop: each sweep recomputes the
            # saturation layer and steps the admission controller
            scheduler.monitor.sweep()
            if scheduler.admission is not None:
                result.min_admission_level = min(
                    result.min_admission_level,
                    scheduler.admission.level)
        if cc.elastic:
            # a mid-run grace shrink well before the kill: the grace
            # deadline expires through step_resize ticks on the virtual
            # clock while node loss + RPC faults keep firing
            if shrink_at is not None and now >= shrink_at:
                victim = find_surplus_member()
                if victim is not None:
                    tid, juuid, guuid = victim
                    scheduler.elastic.request_shrink(
                        tid, juuid, guuid, cluster.name,
                        scheduler.clusters, reason="chaos")
                    shrinks_requested += 1
                    shrink_at = (None if shrinks_requested >= 3
                                 else now + 8_000)
            scheduler.step_resize()
        state = breaker.state
        if state == "open" and last_breaker_state != "open":
            result.breaker_trips += 1
        last_breaker_state = state
        # deferred backend kills (gang-policy siblings killed while the
        # launch path held the kill-lock read side) must land before the
        # tick's invariants are judged
        scheduler.drain_side_effects()
        check_single_live(f"t={now}")
        check_no_partial_gang(f"t={now}")
        if result.violations:
            break  # a broken invariant only compounds; stop and report
        now_box[0] = now + cc.tick_ms
        cluster.advance_to(now_box[0])
        if not pending and not pending_gangs and not store.jobs_where(
                lambda j: j.state is not JobState.COMPLETED):
            break

    result.makespan_ms = now_box[0] - start_ms
    result.rpc_faults = injector.active().get(
        "cluster.launch", {}).get("fires", 0)
    result.delta_faults = sum(
        injector.active().get(p, {}).get("fires", 0)
        for p in ("delta.extract", "delta.apply"))
    # MEASURED relaunches: a crash-window job gained an instance after
    # the kill (the refund->relaunch path actually ran, not assumed)
    result.relaunched_after_kill = sum(
        1 for uuid, n_at_kill in crashed_jobs.items()
        if (j := store.job(uuid)) is not None
        and len(j.instances) > n_at_kill)

    check_no_partial_gang("final")
    # gang requeues actually exercised (observed, not assumed): count
    # the gang-member-lost sibling kills the policy transacted
    for uuids in gang_index.values():
        for uuid in uuids:
            j = store.job(uuid)
            if j is None:
                continue
            result.gang_requeues += sum(
                1 for t in j.instances
                if (mi := store.instance(t)) is not None
                and mi.reason_code == Reasons.GANG_MEMBER_LOST.code)
            if cc.elastic:
                # shrinks observed as transacted gang-resized sheds
                result.elastic_shrinks += sum(
                    1 for t in j.instances
                    if (mi := store.instance(t)) is not None
                    and mi.reason_code == Reasons.GANG_RESIZED.code)
    if cc.elastic:
        result.elastic_grows = scheduler.elastic.grows

    # terminal-state + retry-budget invariants
    for job in list(trace) + gang_jobs:
        stored = store.job(job.uuid)
        if stored is None:
            result.violations.append(f"job {job.uuid} vanished")
            continue
        if stored.state is JobState.COMPLETED:
            result.completed += 1
            # every finished job's audit timeline tells its whole story
            # (submit -> ... -> terminal), across the mid-run failover
            kinds = {e["kind"]
                     for e in store.audit.timeline(job.uuid)}
            if not {"submitted", "terminal"} <= kinds:
                result.audit_timeline_ok = False
                result.violations.append(
                    f"job {job.uuid} completed with an incomplete audit "
                    f"timeline: {sorted(kinds)}")
        else:
            result.violations.append(
                f"job {job.uuid} not terminal: {stored.state.value}")
        insts = {t: i for t in stored.instances
                 if (i := store.instance(t)) is not None}
        charged = stored.attempts_used(insts)
        result.user_retries_charged += charged
        if charged:
            # chaos injects only mea-culpa failures; any consumed budget
            # means a cluster fault was charged to the user
            result.violations.append(
                f"job {job.uuid}: {charged} user retr"
                f"{'y' if charged == 1 else 'ies'} consumed by "
                "injected (mea-culpa) failures")

    # the journal IS the state: a fresh replay must reproduce the final
    # store exactly (what the NEXT promotion would read).  Under disk
    # faults the scrub heal runs first — flips injected since the last
    # sweep would otherwise (correctly) refuse this replay.
    if cc.disk_fault_probability > 0:
        _scrub_heal(store, result)
    final_live = json.loads(store.snapshot())
    try:
        final_replayed = json.loads(
            Store.replay_only(data_dir).snapshot())
    except JournalCorruptionError as e:
        final_replayed = None
        result.violations.append(
            f"final journal replay refused after scrub heal: {e}")
    if final_replayed is not None:
        final_live.pop("tx_id", None)
        final_replayed.pop("tx_id", None)
        if final_live != final_replayed:
            result.violations.append(
                "final journal replay diverges from the live store")

    result.flight = flight_recorder.summary(since_seq=flight_seq0)
    if cc.overload:
        # the controller flips process-global planes (request-capture
        # ring); a run ending mid-brownout must not leak the shed
        from ..rest.instrument import request_log
        request_log.capture = True
    store.close()
    injector.clear()
    breakers.reset()
    return result


# --------------------------------------------------------------------------
# Multi-standby failover chaos: the quorum-aware promotion protocol under
# fire (candidate ranking, standby→standby delta pull, old-leader fencing,
# indeterminate commits), over REAL socket replication — native framed-TCP
# mirrors, real journals, real fencing files (docs/DEPLOY.md).
# --------------------------------------------------------------------------

@dataclass
class FailoverChaosConfig:
    seed: int = 0
    #: "sigkill" — the leader process dies outright (store closed, server
    #: gone); "partition" — the leader stays ALIVE but cut off, and must
    #: end up fenced end-to-end (journal append, replication serving,
    #: REST writes)
    leader_mode: str = "sigkill"
    #: which standby wins the election lock race: "advanced" (the synced
    #: one — promotes directly), "laggard" (must pull the delta from the
    #: advanced peer first), or None (seeded coin flip)
    winner: Optional[str] = None
    n_jobs_before_lag: int = 15    # committed while BOTH standbys synced
    n_jobs_after_lag: int = 10     # committed while standby B lags
    inject_indeterminate: bool = True
    ack_timeout_s: float = 5.0
    data_root: Optional[str] = None
    #: leader-side group-commit admission batching (state/store.py):
    #: concurrent submissions share one fsync + one replication ack
    #: round.  The scenario adds two concurrent phases — a healthy batch
    #: (all members must commit and survive the failover) and a batch
    #: whose ack round is fault-lost mid-flight (every waiter must
    #: resolve committed or indeterminate, never hang or silently drop;
    #: the records reached the synced mirror either way, so ALL must
    #: survive the failover)
    group_commit: bool = True
    group_commit_writers: int = 4


@dataclass
class FailoverChaosResult:
    violations: List[str] = field(default_factory=list)
    committed: int = 0
    winner: str = ""
    winner_was_laggard: bool = False
    delta_pulled: bool = False
    laggard_converged: bool = False
    indeterminate_commits: int = 0
    fenced_appends_rejected: int = 0
    fenced_rest_writes_rejected: int = 0
    # group-commit accounting: durability rounds the stage ran, the
    # demuxed outcome histogram of the concurrent phases, and waiters
    # that never resolved (must stay 0 — the never-silently-dropped
    # contract)
    group_commit_batches: int = 0
    group_commit_outcomes: Dict[str, int] = field(default_factory=dict)
    group_commit_unresolved: int = 0
    # True when the promoted store's replayed audit trail carries the
    # pre-failover jobs' timelines (journal-backed lane mirrored over
    # socket replication, docs/OBSERVABILITY.md)
    audit_timeline_ok: bool = True

    @property
    def ok(self) -> bool:
        return not self.violations

    def summary(self) -> Dict:
        return {
            "ok": self.ok, "violations": list(self.violations),
            "committed": self.committed, "winner": self.winner,
            "winner_was_laggard": self.winner_was_laggard,
            "delta_pulled": self.delta_pulled,
            "laggard_converged": self.laggard_converged,
            "indeterminate_commits": self.indeterminate_commits,
            "fenced_appends_rejected": self.fenced_appends_rejected,
            "fenced_rest_writes_rejected":
                self.fenced_rest_writes_rejected,
            "audit_timeline_ok": self.audit_timeline_ok,
            "group_commit_batches": self.group_commit_batches,
            "group_commit_outcomes": dict(self.group_commit_outcomes),
            "group_commit_unresolved": self.group_commit_unresolved,
        }


def _failover_job(i: int):
    from ..state.schema import Job, Resources
    return Job(uuid=f"00000000-0000-4000-8000-{i:012d}", user="chaos",
               command=f"echo {i}", resources=Resources(cpus=1, mem=64))


def _journal_bytes(d: str) -> int:
    import os
    try:
        return os.path.getsize(os.path.join(d, "journal.jsonl"))
    except OSError:
        return 0


def _wait(pred, timeout_s: float = 15.0) -> bool:
    import time
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.005)
    return bool(pred())


# --------------------------------------------------------------------------
# Partitioned write-plane chaos: kill ONE partition's leader mid-batch and
# prove the sibling partitions' commit streams never stall while the
# victim's standby promotes via the PR 3 candidate ranking — zero
# committed transactions lost, per-partition indeterminate demux asserted
# (ISSUE 12; docs/DEPLOY.md "partitioned write plane").
# --------------------------------------------------------------------------

@dataclass
class PartitionChaosConfig:
    seed: int = 0
    partitions: int = 2
    #: which partition's leader is killed mid-batch
    victim: int = 0
    #: committed per partition before the fault schedule starts
    jobs_before: int = 8
    #: concurrent writers per phase (the group-commit batch width)
    writers: int = 3
    #: how long the sibling writer threads keep streaming commits
    #: through the kill + promotion window
    sibling_stream_s: float = 2.0
    ack_timeout_s: float = 5.0
    data_root: Optional[str] = None
    group_commit: bool = True
    #: True (the default since ISSUE 19): each partition leader is a
    #: REAL shard worker process (sched/shard.py) and the victim is
    #: SIGKILLed — journal stops mid-write exactly as a host loss.
    #: False keeps the original thread-based in-process variant.
    process_kill: bool = True


@dataclass
class PartitionChaosResult:
    violations: List[str] = field(default_factory=list)
    partitions: int = 0
    committed: int = 0
    committed_by_partition: Dict[str, int] = field(default_factory=dict)
    victim_indeterminate: int = 0
    sibling_commits_during_promotion: int = 0
    sibling_errors: int = 0
    promotion_window_s: float = 0.0
    promoted_epoch: int = 0
    unresolved_writers: int = 0
    #: whether the victim loss was a real SIGKILL of a worker process
    process_kill: bool = False

    @property
    def ok(self) -> bool:
        return not self.violations

    def summary(self) -> Dict:
        return {
            "ok": self.ok, "violations": list(self.violations),
            "partitions": self.partitions,
            "committed": self.committed,
            "committed_by_partition": dict(self.committed_by_partition),
            "victim_indeterminate": self.victim_indeterminate,
            "sibling_commits_during_promotion":
                self.sibling_commits_during_promotion,
            "sibling_errors": self.sibling_errors,
            "promotion_window_s": round(self.promotion_window_s, 3),
            "promoted_epoch": self.promoted_epoch,
            "unresolved_writers": self.unresolved_writers,
            "process_kill": self.process_kill,
        }


def run_partition_chaos(cc: Optional[PartitionChaosConfig] = None
                        ) -> PartitionChaosResult:
    """One partition-leader loss under write load, over REAL per-
    partition socket replication (each partition: its own journal,
    fsync stream, group-commit stage, ReplicationServer, synced
    standby, and lease epoch — the N-leases-over-P-partitions layout):

    1. P partition leaders + one synced standby each; a
       :class:`~cook_tpu.state.partition.PartitionedStore` facade
       routes per-pool writes;
    2. a concurrent batch on the VICTIM partition has its replication
       ack fault-lost mid-flight — every waiter must demux committed or
       indeterminate (never hang), and ONLY the victim partition's
       writers may see the ambiguous outcome;
    3. the victim's leader dies; sibling partitions' writer threads
       keep streaming commits THROUGH the whole promotion window —
       zero sibling errors, nonzero sibling commits inside the window
       (the commit stream never stalls);
    4. the victim's standby promotes via the PR 3 machinery (candidate
       position, promotion gate, epoch 2 fencing) and must hold EVERY
       committed-or-indeterminate transaction (zero loss — the
       indeterminate records reached the synced mirror before the ack
       was lost);
    5. the rebuilt facade serves every committed job from every
       partition.
    """
    import os
    import tempfile
    import threading
    import time as _time

    from ..state import replication as repl
    from ..state.partition import PartitionedStore, PartitionMap
    from ..state.schema import Pool
    from ..state.store import ReplicationIndeterminate
    from ..utils.fsatomic import write_atomic_int

    cc = cc or PartitionChaosConfig()
    result = PartitionChaosResult(partitions=cc.partitions)
    if cc.partitions < 2:
        result.violations.append("partition chaos needs >= 2 partitions")
        return result
    if not 0 <= cc.victim < cc.partitions:
        result.violations.append(f"victim {cc.victim} out of range")
        return result
    if not repl.replication_available():
        result.violations.append("native replication library unavailable")
        return result
    root = cc.data_root or tempfile.mkdtemp(prefix="cook-partchaos-")
    election = os.path.join(root, "election")
    os.makedirs(election, exist_ok=True)
    pools = {f"pool-p{p}": p for p in range(cc.partitions)}
    pmap = PartitionMap(count=cc.partitions, pools=pools)
    committed: Dict[int, List[str]] = {p: [] for p in range(cc.partitions)}
    cleanup = []
    stores: List[Store] = []
    servers = []
    followers = []

    def _job(p: int, i: int):
        from ..state.schema import Job, Resources
        return Job(uuid=f"0000000{p}-0000-4000-8000-{i:012d}",
                   user=f"chaos{p}", command=f"echo {i}",
                   pool=f"pool-p{p}",
                   resources=Resources(cpus=1, mem=64))

    try:
        # ---- per-partition leadership: leader + synced standby -------
        from ..sched.election import partition_lock_path
        for p in range(cc.partitions):
            authority = partition_lock_path(election, p) + ".epoch"
            write_atomic_int(authority, 1)
            d_leader = os.path.join(root, f"p{p}", "leader")
            store = Store.open(d_leader, epoch=1, shared=False,
                               partition=p)
            store.attach_fence_authority(authority)
            srv = repl.ReplicationServer(d_leader, 0)
            srv.epoch = 1
            srv.partition = p
            cleanup.append(srv.stop)
            store.attach_replication(srv, sync=True,
                                     timeout_s=cc.ack_timeout_s)
            if cc.group_commit:
                store.enable_group_commit(window_ms=2.0)
            d_standby = os.path.join(root, f"p{p}", "standby")
            f = repl.ReplicationFollower("127.0.0.1", srv.port, d_standby)
            cleanup.append(f.stop)
            repl.record_followed_epoch(d_standby, 1)
            stores.append(store)
            servers.append(srv)
            followers.append(f)
        for p, srv in enumerate(servers):
            if not _wait(lambda s=srv: s.synced_follower_count >= 1):
                result.violations.append(
                    f"partition {p} standby never synced")
                return result
        facade = PartitionedStore(stores, pmap)
        for name in pools:
            facade.put_pool(Pool(name=name))
        for p in range(cc.partitions):
            for i in range(cc.jobs_before):
                job = _job(p, i)
                facade.create_jobs([job])
                committed[p].append(job.uuid)

        # ---- victim batch with a fault-lost ack ----------------------
        outcomes: List[tuple] = []

        def victim_writer(i: int):
            job = _job(cc.victim, 10_000 + i)
            try:
                stores[cc.victim].create_jobs([job])
                outcomes.append(("committed", job.uuid))
            except ReplicationIndeterminate:
                outcomes.append(("indeterminate", job.uuid))
            except Exception as e:
                outcomes.append((f"unexpected:{type(e).__name__}",
                                 job.uuid))

        injector.arm("repl.ack", probability=1.0, max_fires=1)
        try:
            threads = [threading.Thread(target=victim_writer, args=(i,))
                       for i in range(cc.writers)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30.0)
        finally:
            injector.disarm("repl.ack")
        result.unresolved_writers += sum(1 for t in threads
                                         if t.is_alive())
        for outcome, uuid in outcomes:
            if outcome == "indeterminate":
                result.victim_indeterminate += 1
                committed[cc.victim].append(uuid)  # on the synced mirror
            elif outcome == "committed":
                committed[cc.victim].append(uuid)
            else:
                result.violations.append(
                    f"victim-batch writer got {outcome}")
        if not result.victim_indeterminate:
            result.violations.append(
                "injected ack loss demuxed no indeterminate outcome on "
                "the victim partition")

        # sibling writers must see the fault-point ONLY on the victim:
        # arm/disarm above is global, so their phase runs after disarm —
        # what stays per-partition is the demux (asserted below: zero
        # sibling indeterminates while their streams run through the
        # victim's whole promotion window)

        # ---- sibling streams through the kill + promotion ------------
        stop_siblings = threading.Event()
        sibling_log: List[tuple] = []  # (ts, partition, uuid | error)
        sibling_errors = [0]

        def sibling_writer(p: int):
            i = 20_000
            while not stop_siblings.is_set():
                job = _job(p, i)
                i += 1
                try:
                    stores[p].create_jobs([job])
                    sibling_log.append((_time.monotonic(), p, job.uuid))
                except Exception as e:
                    sibling_errors[0] += 1
                    sibling_log.append(
                        (_time.monotonic(), p,
                         f"error:{type(e).__name__}"))
                    return

        sibling_threads = [threading.Thread(target=sibling_writer,
                                            args=(p,))
                           for p in range(cc.partitions)
                           if p != cc.victim]
        for t in sibling_threads:
            t.start()
        _time.sleep(0.1)  # streams flowing before the kill

        # ---- kill the victim's leader (sigkill-equivalent) -----------
        kill_ts = _time.monotonic()
        if not _wait(lambda: followers[cc.victim].offset
                     >= _journal_bytes(os.path.join(
                         root, f"p{cc.victim}", "leader"))):
            result.violations.append(
                "victim standby never reached the head pre-kill")
        followers[cc.victim].stop()
        servers[cc.victim].stop()
        stores[cc.victim].close()  # crash: no checkpoint

        # ---- promote the victim's standby (PR 3 machinery, lease p) --
        d_standby = os.path.join(root, f"p{cc.victim}", "standby")
        pos = repl.candidate_position(d_standby)
        if not pos.get("synced"):
            result.violations.append(
                f"victim standby position not synced: {pos}")
        authority = partition_lock_path(election, cc.victim) + ".epoch"
        write_atomic_int(authority, 2)
        try:
            repl.assert_promotable(d_standby)
        except RuntimeError as e:
            result.violations.append(f"promotion gate refused: {e}")
            return result
        promoted = Store.open(d_standby, epoch=2, shared=False,
                              partition=cc.victim)
        promoted.attach_fence_authority(authority)
        cleanup.append(promoted.close)
        result.promoted_epoch = 2
        promote_ts = _time.monotonic()
        result.promotion_window_s = promote_ts - kill_ts

        # siblings keep streaming a little past the promotion, then stop
        deadline = _time.monotonic() + max(
            0.0, cc.sibling_stream_s - (promote_ts - kill_ts))
        while _time.monotonic() < deadline and not sibling_errors[0]:
            _time.sleep(0.01)
        stop_siblings.set()
        for t in sibling_threads:
            t.join(timeout=30.0)
        result.unresolved_writers += sum(1 for t in sibling_threads
                                         if t.is_alive())
        result.sibling_errors = sibling_errors[0]
        in_window = [e for e in sibling_log
                     if kill_ts <= e[0] <= promote_ts
                     and not str(e[2]).startswith("error:")]
        result.sibling_commits_during_promotion = len(in_window)
        if sibling_errors[0]:
            result.violations.append(
                f"{sibling_errors[0]} sibling writer(s) errored during "
                "the victim's failover — sibling partitions must keep "
                "committing uninterrupted")
        if not in_window:
            result.violations.append(
                "no sibling commit landed inside the victim's promotion "
                "window — the sibling commit stream stalled")
        for ts, p, uuid in sibling_log:
            if not str(uuid).startswith("error:"):
                committed[p].append(uuid)

        # ---- zero loss: promoted store + rebuilt facade --------------
        for uuid in committed[cc.victim]:
            if promoted.job(uuid) is None:
                result.violations.append(
                    f"victim-partition commit {uuid} lost by the "
                    "promotion")
        new_stores = list(stores)
        new_stores[cc.victim] = promoted
        facade = PartitionedStore(new_stores, pmap)
        for p, uuids in committed.items():
            result.committed_by_partition[f"p{p}"] = len(uuids)
            result.committed += len(uuids)
            for uuid in uuids:
                if facade.job(uuid) is None:
                    result.violations.append(
                        f"committed job {uuid} (partition {p}) missing "
                        "from the rebuilt facade")
                    break
    finally:
        for fn in reversed(cleanup):
            try:
                fn()
            except Exception:
                pass
        for store in stores:
            try:
                store.close()
            except Exception:
                pass
        injector.disarm("repl.ack")
    return result


def run_partition_chaos_procs(cc: Optional[PartitionChaosConfig] = None
                              ) -> PartitionChaosResult:
    """The multi-CONTROLLER form of :func:`run_partition_chaos` (ISSUE
    19): each partition's leader is a real shard worker PROCESS
    (sched/shard.py store role — own journal, fence authority, group
    commit, sync socket replication), the parent mirrors each journal
    with a synced standby follower, and the victim partition's worker
    is lost to a real ``SIGKILL`` mid-batch.  The same invariants as
    the thread-based variant, now across process boundaries:

    - the fault-lost replication ack (armed INSIDE the victim process)
      demuxes every concurrent writer to committed or indeterminate —
      never a hang, never a silent drop;
    - sibling shard processes keep committing THROUGH the kill and the
      whole promotion window (zero errors, nonzero in-window commits);
    - the victim's standby promotes via the PR 3 candidate ranking
      (candidate position, promotion gate, epoch-2 fencing) and holds
      every committed-or-indeterminate transaction — zero loss;
    - every sibling partition still serves every commit it acked.
    """
    import os
    import signal as _signal
    import tempfile
    import threading
    import time as _time

    from ..sched.election import partition_lock_path
    from ..sched.shard import ShardSupervisor, rpc
    from ..state import replication as repl
    from ..state.schema import Job, Resources
    from ..state.schema import to_json as _to_json
    from ..state.store import Store
    from ..utils.fsatomic import write_atomic_int

    cc = cc or PartitionChaosConfig()
    result = PartitionChaosResult(partitions=cc.partitions,
                                  process_kill=True)
    if cc.partitions < 2:
        result.violations.append("partition chaos needs >= 2 partitions")
        return result
    if not 0 <= cc.victim < cc.partitions:
        result.violations.append(f"victim {cc.victim} out of range")
        return result
    if not repl.replication_available():
        result.violations.append("native replication library unavailable")
        return result
    root = cc.data_root or tempfile.mkdtemp(prefix="cook-partchaos-")
    election = os.path.join(root, "election")
    os.makedirs(election, exist_ok=True)
    committed: Dict[int, List[str]] = {p: [] for p in range(cc.partitions)}

    def _job(p: int, i: int) -> Dict:
        return _to_json(Job(
            uuid=f"0000000{p}-0000-4000-8000-{i:012d}",
            user=f"chaos{p}", command=f"echo {i}", pool=f"pool-p{p}",
            resources=Resources(cpus=1, mem=64)))

    per_shard = []
    for p in range(cc.partitions):
        authority = partition_lock_path(election, p) + ".epoch"
        write_atomic_int(authority, 1)
        per_shard.append({
            "role": "store", "data_dir": os.path.join(root, f"p{p}",
                                                      "leader"),
            "authority": authority, "epoch": 1, "replicate": True,
            "group_commit": cc.group_commit,
            "ack_timeout_s": cc.ack_timeout_s})
    sup = ShardSupervisor(cc.partitions, {"role": "store"},
                          root=os.path.join(root, "run"),
                          per_shard=per_shard)
    followers = []
    promoted = None
    try:
        sup.start()
        # ---- parent-side synced standby per partition worker ---------
        for p in range(cc.partitions):
            d_standby = os.path.join(root, f"p{p}", "standby")
            f = repl.ReplicationFollower(
                "127.0.0.1", int(sup.procs[p].addr["repl_port"]), d_standby)
            repl.record_followed_epoch(d_standby, 1)
            followers.append(f)
        for p in range(cc.partitions):
            if not _wait(lambda p=p: sup.rpc(
                    p, {"cmd": "repl_status"})["synced_followers"] >= 1):
                result.violations.append(
                    f"partition {p} standby never synced")
                return result
            sup.rpc(p, {"cmd": "put_pool", "name": f"pool-p{p}"})
            for i in range(cc.jobs_before):
                doc = _job(p, i)
                sup.rpc(p, {"cmd": "submit", "jobs": [doc]})
                committed[p].append(doc["uuid"])

        # ---- victim batch with the ack fault armed IN the worker -----
        sup.rpc(cc.victim, {"cmd": "arm_fault", "point": "repl.ack",
                            "probability": 1.0, "max_fires": 1})
        outcomes: List[tuple] = []

        def victim_writer(i: int):
            doc = _job(cc.victim, 10_000 + i)
            try:
                resp = sup.rpc(cc.victim, {"cmd": "submit", "jobs": [doc]},
                               timeout_s=cc.ack_timeout_s + 25.0)
                outcomes.append((resp["outcome"], doc["uuid"]))
            except Exception as e:
                outcomes.append((f"unexpected:{type(e).__name__}",
                                 doc["uuid"]))

        threads = [threading.Thread(target=victim_writer, args=(i,))
                   for i in range(cc.writers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60.0)
        result.unresolved_writers += sum(1 for t in threads if t.is_alive())
        for outcome, uuid in outcomes:
            if outcome in ("indeterminate", "committed"):
                if outcome == "indeterminate":
                    result.victim_indeterminate += 1
                committed[cc.victim].append(uuid)  # on the synced mirror
            else:
                result.violations.append(f"victim-batch writer got {outcome}")
        if not result.victim_indeterminate:
            result.violations.append(
                "injected ack loss demuxed no indeterminate outcome on "
                "the victim partition")

        # ---- sibling streams through the kill + promotion ------------
        stop_siblings = threading.Event()
        sibling_log: List[tuple] = []
        sibling_errors = [0]

        def sibling_writer(p: int):
            i = 20_000
            port = sup.procs[p].port
            while not stop_siblings.is_set():
                doc = _job(p, i)
                i += 1
                try:
                    rpc(port, {"cmd": "submit", "jobs": [doc]},
                        timeout_s=30.0)
                    sibling_log.append((_time.monotonic(), p, doc["uuid"]))
                except Exception as e:
                    sibling_errors[0] += 1
                    sibling_log.append((_time.monotonic(), p,
                                        f"error:{type(e).__name__}"))
                    return

        sibling_threads = [threading.Thread(target=sibling_writer, args=(p,))
                           for p in range(cc.partitions) if p != cc.victim]
        for t in sibling_threads:
            t.start()
        _time.sleep(0.1)  # streams flowing before the kill

        # ---- REAL process kill of the victim's worker ----------------
        if not _wait(lambda: followers[cc.victim].offset
                     >= sup.rpc(cc.victim,
                                {"cmd": "repl_status"})["journal_bytes"]):
            result.violations.append(
                "victim standby never reached the head pre-kill")
        kill_ts = _time.monotonic()
        sup.kill(cc.victim, _signal.SIGKILL)
        followers[cc.victim].stop()

        # ---- promote the standby (PR 3 machinery, parent side) -------
        d_standby = os.path.join(root, f"p{cc.victim}", "standby")
        pos = repl.candidate_position(d_standby)
        if not pos.get("synced"):
            result.violations.append(
                f"victim standby position not synced: {pos}")
        authority = partition_lock_path(election, cc.victim) + ".epoch"
        write_atomic_int(authority, 2)
        try:
            repl.assert_promotable(d_standby)
        except RuntimeError as e:
            result.violations.append(f"promotion gate refused: {e}")
            return result
        promoted = Store.open(d_standby, epoch=2, shared=False,
                              partition=cc.victim)
        promoted.attach_fence_authority(authority)
        result.promoted_epoch = 2
        promote_ts = _time.monotonic()
        result.promotion_window_s = promote_ts - kill_ts

        # siblings stream a little past the promotion, then stop
        deadline = _time.monotonic() + max(
            0.0, cc.sibling_stream_s - (promote_ts - kill_ts))
        while _time.monotonic() < deadline and not sibling_errors[0]:
            _time.sleep(0.01)
        stop_siblings.set()
        for t in sibling_threads:
            t.join(timeout=60.0)
        result.unresolved_writers += sum(1 for t in sibling_threads
                                         if t.is_alive())
        result.sibling_errors = sibling_errors[0]
        in_window = [e for e in sibling_log
                     if kill_ts <= e[0] <= promote_ts
                     and not str(e[2]).startswith("error:")]
        result.sibling_commits_during_promotion = len(in_window)
        if sibling_errors[0]:
            result.violations.append(
                f"{sibling_errors[0]} sibling writer(s) errored during "
                "the victim's failover — sibling shard processes must "
                "keep committing uninterrupted")
        if not in_window:
            result.violations.append(
                "no sibling commit landed inside the victim's promotion "
                "window — the sibling commit stream stalled")
        for _ts, p, uuid in sibling_log:
            if not str(uuid).startswith("error:"):
                committed[p].append(uuid)

        # ---- zero loss: promoted store + live sibling workers --------
        for uuid in committed[cc.victim]:
            if promoted.job(uuid) is None:
                result.violations.append(
                    f"victim-partition commit {uuid} lost by the "
                    "promotion")
        for p, uuids in committed.items():
            result.committed_by_partition[f"p{p}"] = len(uuids)
            result.committed += len(uuids)
            if p == cc.victim:
                continue
            for uuid in uuids:
                if not sup.rpc(p, {"cmd": "job", "uuid": uuid})["found"]:
                    result.violations.append(
                        f"committed job {uuid} (partition {p}) missing "
                        "from its shard worker after the failover")
                    break
    finally:
        for f in followers:
            try:
                f.stop()
            except Exception:
                pass
        if promoted is not None:
            try:
                promoted.close()
            except Exception:
                pass
        sup.stop()
    return result


def run_failover_chaos(cc: Optional[FailoverChaosConfig] = None
                       ) -> FailoverChaosResult:
    """One full quorum-aware failover under an adverse schedule:

    1. leader + two synced standbys, sync replication;
    2. standby B drops off (once-synced-then-lagged) and the leader
       keeps committing — including one commit whose ack is fault-lost
       (``repl.ack``): a first-class INDETERMINATE outcome;
    3. the leader dies (``sigkill``) or is partitioned (``partition``);
    4. a seeded lock race decides the election winner; the candidate
       ranking must still make the BEST-SYNCED position the authority —
       a laggard winner pulls the delta from the advanced peer before
       opening its store;
    5. the loser re-follows the winner and must converge
       byte-identically;
    6. (partition mode) the deposed leader's journal appends AND REST
       writes must be rejected — no split brain.

    Invariants are collected as violations, not raised, so one run
    reports everything it broke."""
    import json as _json
    import os
    import random
    import tempfile
    import urllib.error
    import urllib.request

    import threading

    from ..state import replication as repl
    from ..state.store import (ReplicationIndeterminate,
                               ReplicationTimeout, StaleEpochError)
    from ..utils.fsatomic import read_int_file, write_atomic_int

    def _concurrent_submits(store, base_i: int, n: int, outcomes: list):
        """n concurrent single-job submissions (one group-commit batch's
        worth of independent REST writers); each thread records its
        demuxed outcome — the never-silently-dropped contract is
        'every thread appends exactly one entry'."""
        def worker(i: int):
            job = _failover_job(base_i + i)
            try:
                store.create_jobs([job])
                outcomes.append(("committed", job.uuid))
            except ReplicationIndeterminate:
                outcomes.append(("indeterminate", job.uuid))
            except (StaleEpochError, ReplicationTimeout, RuntimeError):
                # clean refusals: nothing journaled (or the journal was
                # already poisoned by an earlier fence) — safe to retry
                outcomes.append(("aborted", job.uuid))
            except Exception as e:  # a waiter must never die opaquely
                outcomes.append((f"unexpected:{type(e).__name__}",
                                 job.uuid))
        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        return threads

    cc = cc or FailoverChaosConfig()
    result = FailoverChaosResult()
    if not repl.replication_available():
        result.violations.append("native replication library unavailable")
        return result
    rng = random.Random(cc.seed)
    root = cc.data_root or tempfile.mkdtemp(prefix="cook-failover-")
    d_leader = os.path.join(root, "leader")
    d_a = os.path.join(root, "standby-a")
    d_b = os.path.join(root, "standby-b")
    epoch_authority = os.path.join(root, "election", "cook-leader.lock"
                                                     ".epoch")
    os.makedirs(os.path.dirname(epoch_authority), exist_ok=True)
    write_atomic_int(epoch_authority, 1)

    committed: List[str] = []
    cleanup = []
    try:
        # ---- epoch-1 leadership: leader + two synced standbys --------
        store = Store.open(d_leader, epoch=1, shared=False)
        store.attach_fence_authority(epoch_authority)
        srv = repl.ReplicationServer(d_leader, 0)
        srv.epoch = 1
        cleanup.append(srv.stop)
        store.attach_replication(srv, sync=True,
                                 timeout_s=cc.ack_timeout_s)
        if cc.group_commit:
            # a wide coalescing window so the concurrent phases reliably
            # share durability rounds (production default is sub-ms)
            store.enable_group_commit(window_ms=5.0)
        fa = repl.ReplicationFollower("127.0.0.1", srv.port, d_a)
        fb = repl.ReplicationFollower("127.0.0.1", srv.port, d_b)
        cleanup += [fa.stop, fb.stop]
        repl.record_followed_epoch(d_a, 1)
        repl.record_followed_epoch(d_b, 1)
        if not _wait(lambda: srv.synced_follower_count >= 2):
            result.violations.append("standbys never synced")
            return result
        for i in range(cc.n_jobs_before_lag):
            store.create_jobs([_failover_job(i)])
            committed.append(_failover_job(i).uuid)
        if cc.group_commit:
            # ---- healthy group-commit batch: concurrent writers share
            # durability rounds; every member commits and must survive
            # the failover like any other committed transaction
            outcomes: list = []
            threads = _concurrent_submits(store, 500_000,
                                          cc.group_commit_writers,
                                          outcomes)
            for t in threads:
                t.join(timeout=30.0)
            result.group_commit_unresolved += sum(
                1 for t in threads if t.is_alive())
            for outcome, uuid in outcomes:
                result.group_commit_outcomes[outcome] = \
                    result.group_commit_outcomes.get(outcome, 0) + 1
                if outcome == "committed":
                    committed.append(uuid)
                else:
                    result.violations.append(
                        f"healthy group-commit writer got {outcome}")
            gstats = store.group_commit_stats() or {}
            if gstats.get("max_batch", 0) < 2:
                result.violations.append(
                    "concurrent submissions never shared a group-commit "
                    f"durability round: {gstats}")
        # ---- standby B lags (once-synced-then-lagged candidate) ------
        if not _wait(lambda: os.path.exists(
                os.path.join(d_b, "repl_synced"))):
            result.violations.append("standby B never got its marker")
        fb.stop()
        # the server only notices the dead conn when the next append's
        # JDATA send fails — the first post-lag commit flushes it out
        # (wait_acked unblocks the moment the worker erases the conn)
        n = cc.n_jobs_before_lag
        for i in range(n, n + cc.n_jobs_after_lag):
            store.create_jobs([_failover_job(i)])
            committed.append(_failover_job(i).uuid)
        if srv.synced_follower_count != 1:
            result.violations.append(
                "server still counts the lagged standby as synced after "
                f"{cc.n_jobs_after_lag} commits")
        if cc.inject_indeterminate:
            # one commit's ack is lost AFTER the record is durable: the
            # store must report indeterminate, NOT excise the record —
            # standby A pulls it anyway, so the failover must keep it
            # (the phantom-commit hole this PR closes; ADVICE r5)
            amb = _failover_job(n + cc.n_jobs_after_lag)
            injector.arm("repl.ack", probability=1.0, max_fires=1)
            try:
                store.create_jobs([amb])
                result.violations.append(
                    "injected ack loss did not surface as indeterminate")
            except ReplicationIndeterminate:
                result.indeterminate_commits += 1
                committed.append(amb.uuid)  # it IS on the synced mirror
            finally:
                injector.disarm("repl.ack")
            if store.job(amb.uuid) is None:
                result.violations.append(
                    "indeterminate commit was rolled back locally")
        if cc.group_commit:
            # ---- ack lost MID-BATCH: the leader's durability round for
            # a whole batch of concurrent writers fails (the shape a
            # leader death mid-group-commit leaves behind).  Every
            # waiter must resolve — the faulted round's members all
            # demux indeterminate, any straggler batch commits — and
            # since each record was written+streamed to the synced
            # mirror before its ack round, ALL must survive failover.
            outcomes2: list = []
            injector.arm("repl.ack", probability=1.0, max_fires=1)
            try:
                threads = _concurrent_submits(store, 600_000,
                                              cc.group_commit_writers,
                                              outcomes2)
                for t in threads:
                    t.join(timeout=30.0)
            finally:
                injector.disarm("repl.ack")
            result.group_commit_unresolved += sum(
                1 for t in threads if t.is_alive())
            saw_indeterminate = False
            for outcome, uuid in outcomes2:
                result.group_commit_outcomes[outcome] = \
                    result.group_commit_outcomes.get(outcome, 0) + 1
                if outcome == "indeterminate":
                    saw_indeterminate = True
                    committed.append(uuid)  # on the synced mirror
                elif outcome == "committed":
                    committed.append(uuid)
                else:
                    result.violations.append(
                        f"mid-batch ack loss: writer got {outcome} "
                        "(must be committed or indeterminate)")
            if not saw_indeterminate:
                result.violations.append(
                    "injected mid-batch ack loss demuxed no "
                    "indeterminate outcome to its waiters")
            if result.group_commit_unresolved:
                result.violations.append(
                    f"{result.group_commit_unresolved} group-commit "
                    "waiter(s) never resolved (silently dropped)")
            gstats = store.group_commit_stats() or {}
            result.group_commit_batches = int(gstats.get("batches", 0))
        result.committed = len(committed)
        if not _wait(lambda: fa.offset >= _journal_bytes(d_leader)):
            result.violations.append("standby A never reached the head")

        # ---- leader loss ---------------------------------------------
        # either way the standbys lose their stream (fa released so a
        # winning candidate can reopen d_a as its own store/server)
        fa.stop()
        old_store = None
        if cc.leader_mode == "sigkill":
            srv.stop()
            store.close()
        else:  # partition: alive but cut off from the standbys
            old_store = store
            cleanup.append(store.close)  # incl. its group-commit stage
        pos_a = dict(repl.candidate_position(d_a), ts=None)
        pos_b = dict(repl.candidate_position(d_b), ts=None)
        if repl.rank_key(pos_a) <= repl.rank_key(pos_b):
            result.violations.append(
                f"ranking failed to order the synced-ahead candidate "
                f"first: {pos_a} vs {pos_b}")
        # ---- election: a seeded lock race, then candidate ranking ----
        winner = cc.winner or rng.choice(["advanced", "laggard"])
        result.winner = winner
        result.winner_was_laggard = winner == "laggard"
        write_atomic_int(epoch_authority, 2)
        if winner == "laggard":
            # B won the lock but A's position is strictly ahead: B must
            # pull the delta from A over the carrier before promoting
            ahead = repl.choose_successor(pos_b, {"a": pos_a})
            if ahead is None or ahead[0] != "a":
                result.violations.append(
                    f"laggard winner did not choose the advanced peer "
                    f"({ahead!r})")
            catchup_srv = repl.ReplicationServer(d_a, 0)
            cleanup.append(catchup_srv.stop)
            if not repl.catch_up_from_peer("127.0.0.1", catchup_srv.port,
                                           d_b, pos_a["offset"]):
                result.violations.append("delta pull from peer failed")
            else:
                result.delta_pulled = True
            catchup_srv.stop()
            d_winner, d_loser = d_b, d_a
        else:
            if repl.choose_successor(pos_a, {"b": pos_b}) is not None:
                result.violations.append(
                    "advanced winner was told to catch up from a "
                    "lagging peer")
            d_winner, d_loser = d_a, d_b
        try:
            repl.assert_promotable(d_winner)
        except RuntimeError as e:
            result.violations.append(f"promotion gate refused the "
                                     f"winner: {e}")
            return result
        promoted = Store.open(d_winner, epoch=2, shared=False)
        promoted.attach_fence_authority(epoch_authority)
        new_srv = repl.ReplicationServer(d_winner, 0)
        new_srv.epoch = 2
        cleanup.append(new_srv.stop)
        promoted.attach_replication(new_srv, sync=True,
                                    timeout_s=cc.ack_timeout_s)
        # ---- zero loss ----------------------------------------------
        for uuid in committed:
            if promoted.job(uuid) is None:
                result.violations.append(
                    f"committed job {uuid} lost by the failover")
            elif not any(
                    e["kind"] == "submitted"
                    for e in promoted.audit.timeline(uuid)):
                # the audit lane rode the mirrored journal bytes: the
                # winner's replay must reconstruct each committed job's
                # timeline too (a laggard winner gets it via delta pull)
                result.audit_timeline_ok = False
                result.violations.append(
                    f"audit timeline for committed job {uuid} lost by "
                    "the failover")
        # ---- the loser re-follows the winner and converges ----------
        loser_f = repl.ReplicationFollower("127.0.0.1", new_srv.port,
                                           d_loser)
        cleanup.append(loser_f.stop)
        repl.record_followed_epoch(d_loser, 2)
        promoted.create_jobs([_failover_job(999_999)])  # post-failover tx
        result.laggard_converged = _wait(
            lambda: open(os.path.join(d_loser, "journal.jsonl"),
                         "rb").read()
            == open(os.path.join(d_winner, "journal.jsonl"), "rb").read()
            if os.path.exists(os.path.join(d_loser, "journal.jsonl"))
            else False)
        if not result.laggard_converged:
            result.violations.append(
                "the losing standby did not converge on the winner")
        loser_f.stop()
        # ---- fencing the deposed-but-alive leader -------------------
        if old_store is not None:
            try:
                old_store.create_jobs([_failover_job(666_666)])
                result.violations.append(
                    "deposed leader's journal append was accepted")
            except StaleEpochError:
                result.fenced_appends_rejected += 1
            srv.fence()
            if srv.wait_acked(10 ** 9, timeout_s=0.01):
                result.violations.append(
                    "fenced replication server confirmed an ack wait")
            # REST write path flips the moment the epoch is superseded
            from ..rest.api import ApiServer, CookApi
            api = CookApi(old_store)
            api.fence_guard = lambda: (
                (read_int_file(epoch_authority) or 0)
                > (old_store._journal_epoch or 0))
            rest = ApiServer(api)
            rest.start()
            cleanup.append(rest.stop)
            req = urllib.request.Request(
                rest.url + "/jobs", method="POST",
                data=_json.dumps({"jobs": [{"command": "x"}]}).encode(),
                headers={"Content-Type": "application/json",
                         "X-Cook-User": "chaos"})
            try:
                urllib.request.urlopen(req, timeout=5)
                result.violations.append(
                    "deposed leader accepted a REST write")
            except urllib.error.HTTPError as e:
                if e.code == 503:
                    result.fenced_rest_writes_rejected += 1
                else:
                    result.violations.append(
                        f"deposed leader's REST write got {e.code}, "
                        "not 503")
            # no split brain: the deposed leader holds no commit the
            # successor lacks (its last accepted tx was pre-partition)
            if old_store.job(_failover_job(666_666).uuid) is not None:
                result.violations.append(
                    "fenced append landed in the deposed leader's store")
        promoted.close()
    finally:
        for fn in reversed(cleanup):
            try:
                fn()
            except Exception:
                pass
        injector.disarm("repl.ack")
    return result
