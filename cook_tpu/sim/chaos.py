"""Chaos-mode simulator: a workload driven under an injected fault
schedule, with the robustness invariants asserted, not assumed.

The fault-injection counterpart of the faster-than-real-time simulator
(Basiri et al., *Chaos Engineering*, IEEE Software 2016; Borg treats
failover/requeue behavior as first-class tested behavior, Verma et al.,
EuroSys 2015): replay a generated trace against the REAL scheduler +
store + fake cluster on a virtual clock while injecting

- **node loss** — a loaded host's tasks all fail ``NODE_LOST``
  (mea-culpa) on a fixed cadence;
- **launch RPC faults** — ``utils/faults.py`` point ``cluster.launch``
  rejects backend launches with a seeded probability (mea-culpa
  ``pod-submission-failed``), feeding the per-cluster circuit breaker;
- **one leader kill + promotion** — the leader "crashes" between the
  match transaction and the backend launch-ack (the classic
  crash-consistency window), the journal is reopened the way a promoted
  follower re-reads state, and scheduling resumes.

Invariants checked (violations are collected, not raised, so a run
reports everything it broke):

1. every job reaches a terminal state;
2. retry budgets are only consumed by non-mea-culpa failures (chaos only
   injects mea-culpa faults, so every job must end with
   ``attempts_used == 0``);
3. no job ever has two concurrently-live instances (checked every tick,
   and cross-checked against the backend's running set);
4. promotion loses zero committed transactions: the reopened store's
   state equals the pre-crash store's state, byte-for-value, and the
   final journal replays to exactly the final in-memory state.

Run it:  ``python -m cook_tpu.sim --chaos [--seed N]`` or
``pytest -m chaos``; see docs/ROBUSTNESS.md.
"""

from __future__ import annotations

import json
import random
import tempfile
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..cluster.fake import FakeCluster
from ..config import Config
from ..sched.scheduler import Scheduler
from ..state.schema import InstanceStatus, JobState, Reasons
from ..state.store import Store
from ..utils.faults import injector
from ..utils.flight import recorder as flight_recorder
from ..utils.retry import breakers
from .simulator import (
    generate_example_hosts,
    generate_example_trace,
    load_hosts,
    load_trace,
)


@dataclass
class ChaosConfig:
    seed: int = 0
    n_jobs: int = 40
    n_users: int = 4
    n_hosts: int = 8
    submit_span_ms: int = 30_000
    job_duration_ms: int = 6_000
    tick_ms: int = 1_000
    # fault schedule.  node_loss_max stays BELOW n_hosts: the novel-host
    # constraint permanently excludes a job's failed hosts, so losing
    # every host at least once could make an unlucky job unschedulable
    # forever — a real small-cluster liveness hazard, but not the
    # invariant under test here
    node_loss_every_ms: int = 9_000
    node_loss_max: int = 5
    rpc_fault_probability: float = 0.15
    # cap on injected RPC rejects: each reject marks one host failed for
    # the job (novel-host), so an unbounded storm over a small pool can
    # legitimately exclude every host for an unlucky job
    rpc_fault_max: Optional[int] = None
    leader_kill_at_ms: Optional[int] = 15_000
    # breaker policy (virtual-clock): small threshold so chaos actually
    # exercises trip + half-open heal inside a short run
    breaker_failure_threshold: int = 3
    breaker_reset_timeout_s: float = 5.0
    max_virtual_ms: int = 30 * 60 * 1000
    data_dir: Optional[str] = None   # journal dir; tempdir when None


@dataclass
class ChaosResult:
    total: int = 0
    completed: int = 0
    violations: List[str] = field(default_factory=list)
    node_losses: int = 0
    rpc_faults: int = 0
    leader_kills: int = 0
    intents_open_at_kill: int = 0
    relaunched_after_kill: int = 0
    breaker_trips: int = 0
    user_retries_charged: int = 0
    makespan_ms: int = 0
    flight: Dict = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.violations

    def summary(self) -> Dict:
        return {
            "ok": self.ok,
            "jobs_total": self.total,
            "jobs_completed": self.completed,
            "violations": list(self.violations),
            "node_losses": self.node_losses,
            "rpc_faults": self.rpc_faults,
            "leader_kills": self.leader_kills,
            "intents_open_at_kill": self.intents_open_at_kill,
            "relaunched_after_kill": self.relaunched_after_kill,
            "breaker_trips": self.breaker_trips,
            "user_retries_charged": self.user_retries_charged,
            "makespan_virtual_s": self.makespan_ms / 1000.0,
            "flight": self.flight,
        }


class _LeaderCrash(BaseException):
    """Simulated process death mid-launch.  BaseException so no
    defensive ``except Exception`` on the dispatch path can swallow the
    'crash' and ack the launch anyway."""


def _scheduler_config(cc: ChaosConfig) -> Config:
    cfg = Config()
    # deterministic host path: the chaos run asserts scheduling
    # INVARIANTS, not kernel behavior (kernel fallback has its own tests)
    cfg.cycle_mode = "split"
    cfg.default_matcher.backend = "cpu"
    cfg.columnar_index = False
    cfg.circuit_breaker.failure_threshold = cc.breaker_failure_threshold
    cfg.circuit_breaker.reset_timeout_s = cc.breaker_reset_timeout_s
    return cfg


def run_chaos(cc: Optional[ChaosConfig] = None) -> ChaosResult:
    cc = cc or ChaosConfig()
    data_dir = cc.data_dir or tempfile.mkdtemp(prefix="cook-chaos-")
    rng = random.Random(cc.seed)
    trace = load_trace(generate_example_trace(
        cc.n_jobs, n_users=cc.n_users, seed=cc.seed,
        span_ms=cc.submit_span_ms, duration_ms=cc.job_duration_ms))
    hosts = load_hosts(generate_example_hosts(cc.n_hosts, seed=cc.seed))
    result = ChaosResult(total=len(trace))
    if not trace:
        return result

    now_box = [trace[0].submit_time_ms]
    clock = lambda: now_box[0]  # noqa: E731 - one timebase for everything

    # process-global planes: seed/arm for this run, restore after
    injector.clear()
    injector.reseed(cc.seed)
    breakers.reset()
    breakers.configure(failure_threshold=cc.breaker_failure_threshold,
                       reset_timeout_s=cc.breaker_reset_timeout_s,
                       clock=lambda: now_box[0] / 1000.0)
    if cc.rpc_fault_probability > 0:
        injector.arm("cluster.launch",
                     probability=cc.rpc_fault_probability,
                     max_fires=cc.rpc_fault_max)
    flight_seq0 = flight_recorder.last_seq()

    cfg = _scheduler_config(cc)
    store = Store.open(data_dir)
    store.clock = clock
    cluster = FakeCluster("chaos", hosts)
    cluster.job_durations_ms = {
        j.uuid: int(j.labels["sim/duration_ms"]) for j in trace}
    scheduler = Scheduler(store, cfg, [cluster], rank_backend="cpu")

    def check_single_live(when: str) -> None:
        live_by_job: Dict[str, int] = {}
        for job, inst in store.running_instances():
            live_by_job[job.uuid] = live_by_job.get(job.uuid, 0) + 1
        for uuid, n in live_by_job.items():
            if n > 1:
                result.violations.append(
                    f"{when}: job {uuid} has {n} live instances")
        # backend cross-check: every task the cluster runs maps to a
        # still-live store instance (no zombie double-running attempt)
        for tid in cluster.running_task_ids():
            inst = store.instance(tid)
            if inst is None or inst.status not in (
                    InstanceStatus.UNKNOWN, InstanceStatus.RUNNING):
                result.violations.append(
                    f"{when}: cluster runs {tid} but store says "
                    f"{inst.status.value if inst else 'missing'}")

    def fail_one_node() -> None:
        if result.node_losses >= cc.node_loss_max:
            return
        with cluster._lock:
            loaded: Dict[str, List[str]] = {}
            for tid, t in cluster._tasks.items():
                loaded.setdefault(t.spec.hostname, []).append(tid)
        if not loaded:
            return
        host = rng.choice(sorted(loaded))
        result.node_losses += 1
        for tid in loaded[host]:
            cluster.fail_task(tid, Reasons.NODE_LOST.code)

    # jobs whose dispatch the leader kill interrupted, with their
    # instance counts at kill time: a post-kill instance PROVES the
    # refund->relaunch path ran (reported as relaunched_after_kill)
    crashed_jobs: Dict[str, int] = {}

    def kill_leader_and_promote() -> None:
        nonlocal store, scheduler
        result.leader_kills += 1
        # crash INSIDE the match->launch window: the guard transaction
        # (instances + intents) commits, the backend dispatch never lands
        orig_launch = FakeCluster.launch_tasks

        def crash(self, pool, specs):
            raise _LeaderCrash()

        FakeCluster.launch_tasks = crash
        try:
            scheduler.step_rank()
            scheduler.step_match()
        except _LeaderCrash:
            pass
        finally:
            FakeCluster.launch_tasks = orig_launch
        open_intents = store.launch_intents()
        result.intents_open_at_kill = len(open_intents)
        for intent in open_intents:
            j = store.job(intent["job_uuid"])
            if j is not None:
                crashed_jobs[j.uuid] = len(j.instances)
        pre = json.loads(store.snapshot())
        store.close()  # crash-equivalent: no checkpoint, journal as-is
        # promotion: the successor re-reads everything the dead leader
        # committed (snapshot + journal replay)
        store = Store.open(data_dir)
        post = json.loads(store.snapshot())
        # tx_id counts every transaction including write-free ones (an
        # all-deny launch guard journals nothing); entity state is the
        # committed truth being compared
        pre.pop("tx_id", None)
        post.pop("tx_id", None)
        if post != pre:
            result.violations.append(
                "promotion lost committed transactions: replayed state "
                "differs from the pre-crash store")
        store.clock = clock
        # the new leader adopts the (still-running) cluster and sweeps
        # the open launch intents in its constructor
        scheduler = Scheduler(store, cfg, [cluster], rank_backend="cpu")

    pending = list(trace)
    deadline = pending[-1].submit_time_ms + cc.max_virtual_ms
    start_ms = now_box[0]
    next_node_loss = start_ms + cc.node_loss_every_ms
    kill_at = (start_ms + cc.leader_kill_at_ms
               if cc.leader_kill_at_ms is not None else None)
    breaker = breakers.get(cluster.name)
    last_breaker_state = breaker.state

    while now_box[0] <= deadline:
        now = now_box[0]
        while pending and pending[0].submit_time_ms <= now:
            store.create_jobs([pending.pop(0)])
        if kill_at is not None and now >= kill_at:
            kill_at = None
            kill_leader_and_promote()
        if now >= next_node_loss:
            next_node_loss = now + cc.node_loss_every_ms
            fail_one_node()
        scheduler.step_rank()
        scheduler.step_match()
        scheduler.step_reapers(current_ms=now)
        state = breaker.state
        if state == "open" and last_breaker_state != "open":
            result.breaker_trips += 1
        last_breaker_state = state
        check_single_live(f"t={now}")
        if result.violations:
            break  # a broken invariant only compounds; stop and report
        now_box[0] = now + cc.tick_ms
        cluster.advance_to(now_box[0])
        if not pending and not store.jobs_where(
                lambda j: j.state is not JobState.COMPLETED):
            break

    result.makespan_ms = now_box[0] - start_ms
    result.rpc_faults = injector.active().get(
        "cluster.launch", {}).get("fires", 0)
    # MEASURED relaunches: a crash-window job gained an instance after
    # the kill (the refund->relaunch path actually ran, not assumed)
    result.relaunched_after_kill = sum(
        1 for uuid, n_at_kill in crashed_jobs.items()
        if (j := store.job(uuid)) is not None
        and len(j.instances) > n_at_kill)

    # terminal-state + retry-budget invariants
    for job in trace:
        stored = store.job(job.uuid)
        if stored is None:
            result.violations.append(f"job {job.uuid} vanished")
            continue
        if stored.state is JobState.COMPLETED:
            result.completed += 1
        else:
            result.violations.append(
                f"job {job.uuid} not terminal: {stored.state.value}")
        insts = {t: i for t in stored.instances
                 if (i := store.instance(t)) is not None}
        charged = stored.attempts_used(insts)
        result.user_retries_charged += charged
        if charged:
            # chaos injects only mea-culpa failures; any consumed budget
            # means a cluster fault was charged to the user
            result.violations.append(
                f"job {job.uuid}: {charged} user retr"
                f"{'y' if charged == 1 else 'ies'} consumed by "
                "injected (mea-culpa) failures")

    # the journal IS the state: a fresh replay must reproduce the final
    # store exactly (what the NEXT promotion would read)
    final_live = json.loads(store.snapshot())
    final_replayed = json.loads(Store.replay_only(data_dir).snapshot())
    final_live.pop("tx_id", None)
    final_replayed.pop("tx_id", None)
    if final_live != final_replayed:
        result.violations.append(
            "final journal replay diverges from the live store")

    result.flight = flight_recorder.summary(since_seq=flight_seq0)
    store.close()
    injector.clear()
    breakers.reset()
    return result
