"""CLI: python -m cook_tpu.sim --trace trace.json --hosts hosts.json
     or: python -m cook_tpu.sim --workload spec.json [--emit-trace t.json]
     or: python -m cook_tpu.sim --chaos [--seed N]  (fault-schedule run
         with invariant checks, sim/chaos.py; exit 1 on violations)
     or: python -m cook_tpu.sim --crashpoints  (exhaustive disk-fault /
         crash-point recovery matrix, sim/crashpoint.py; exit 1 on any
         storage-contract violation)"""

import argparse
import json
import sys

from .simulator import (
    Simulator,
    generate_example_hosts,
    generate_example_trace,
    load_hosts,
    load_trace,
)
from .workload import generate_hosts, generate_trace


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="cook_tpu.sim")
    p.add_argument("--trace", help="trace JSON file (default: generated)")
    p.add_argument("--workload",
                   help="statistical workload spec JSON; synthesizes the "
                        "trace instead of --trace (simulator/ parity)")
    p.add_argument("--seed", type=int, default=None,
                   help="workload generation seed (overrides spec)")
    p.add_argument("--emit-trace",
                   help="also write the synthesized trace JSON here")
    p.add_argument("--hosts", help="hosts JSON file (default: generated)")
    p.add_argument("--backend", default="tpu", choices=["tpu", "cpu"])
    p.add_argument("--jobs", type=int, default=None,
                   help="generated trace size (default 200; chaos "
                        "mode's own default is smaller)")
    p.add_argument("--n-hosts", type=int, default=None,
                   help="generated host count (default 20)")
    p.add_argument("--out", help="write task records CSV here")
    p.add_argument("--chaos", action="store_true",
                   help="run the fault-schedule chaos mode (node loss + "
                        "RPC faults + leader kill/promotion) and assert "
                        "the robustness invariants; exit 1 on violations")
    p.add_argument("--leader-kill-at-ms", type=int, default=None,
                   help="chaos: virtual ms offset of the leader kill "
                        "(default 15000; negative disables)")
    p.add_argument("--chaos-failover", action="store_true",
                   help="run the multi-standby failover chaos (candidate "
                        "ranking, delta pull, old-leader fencing, "
                        "indeterminate commits) over real socket "
                        "replication; exit 1 on violations")
    p.add_argument("--leader-mode", default="sigkill",
                   choices=["sigkill", "partition"],
                   help="chaos-failover: how the leader is lost")
    p.add_argument("--partitions", type=int, default=None,
                   help="chaos-failover: run the PARTITIONED write-plane "
                        "scenario over N partitions instead — kill ONE "
                        "partition leader mid-batch, its standby "
                        "promotes via the candidate ranking while "
                        "sibling partitions keep committing "
                        "uninterrupted; zero committed txns lost, "
                        "per-partition indeterminate demux asserted "
                        "(docs/DEPLOY.md partitioned write plane)")
    p.add_argument("--pipeline-depth", type=int, default=None,
                   help="chaos: drive the production pipelined fused "
                        "cycle at this depth instead of the split host "
                        "path (duplicate-live invariant under overlapped "
                        "optimistic dispatches)")
    p.add_argument("--gangs", type=int, default=None,
                   help="chaos: ride N all-or-nothing gang groups on the "
                        "trace and assert the zero-partial-gangs "
                        "invariant every tick (docs/GANG.md)")
    p.add_argument("--gang-size", type=int, default=None,
                   help="chaos: members per gang (default 3)")
    p.add_argument("--elastic", action="store_true",
                   help="chaos: make the gangs ELASTIC (gang_min = "
                        "size//2, docs/GANG.md elasticity) — asserts "
                        "zero partial gangs at the relaxed minimum and "
                        "drives grace shrinks through the fault "
                        "schedule, including one racing the leader "
                        "kill (defaults --gangs 2 when unset)")
    p.add_argument("--resident", action="store_true",
                   help="chaos: drive the fused cycle off the columnar "
                        "index with the DEVICE-RESIDENT pack on (ISSUE "
                        "7); leader kill rebuilds the resident pack on "
                        "the promoted driver")
    p.add_argument("--delta-faults", type=float, default=None,
                   help="chaos: per-call fire probability for the "
                        "delta.extract/delta.apply fault points (each "
                        "hit degrades that cycle to a full repack)")
    p.add_argument("--no-group-commit", action="store_true",
                   help="chaos-failover: disable the leader's "
                        "group-commit admission batching (default ON: "
                        "concurrent submissions share one journal "
                        "fsync + replication ack round, and an ack "
                        "lost mid-batch must demux indeterminate to "
                        "every waiter — never a silent drop)")
    p.add_argument("--overload", action="store_true",
                   help="overload replay (sim/overload.py): heavy-tailed "
                        "users at --overload-multiple x sustainable "
                        "offered load with the admission controller in "
                        "the loop; asserts the brownout ladder engages "
                        "in shed order, recovers, and loses zero "
                        "committed writes.  Combine with --chaos for a "
                        "leader kill MID-BROWNOUT (the promoted "
                        "controller must restore the journaled stage)")
    p.add_argument("--overload-multiple", type=float, default=None,
                   help="overload: offered load as a multiple of "
                        "sustainable capacity (default 10)")
    p.add_argument("--crashpoints", action="store_true",
                   help="run the exhaustive crash-point recovery matrix "
                        "(sim/crashpoint.py): every disk-fault site at "
                        "every append index, every record byte boundary "
                        "truncation, per-record bit flips with peer "
                        "repair, checkpoint crash windows; exit 1 on "
                        "any committed-write loss, phantom, refused "
                        "torn tail, or non-byte-identical repair")
    p.add_argument("--crashpoint-stride", type=int, default=None,
                   help="crashpoints: subsample the fault-site append "
                        "indices (default 1 = every index)")
    p.add_argument("--disk-faults", type=float, default=None,
                   help="chaos: per-append fire probability for the "
                        "store.journal.bitflip point on the leader's "
                        "journal during the failover legs — recovery "
                        "must detect the damage and still converge "
                        "(docs/ROBUSTNESS.md WAL v2)")
    p.add_argument("--in-process", action="store_true",
                   help="partitioned chaos-failover: keep every partition "
                        "leader a thread inside THIS process (the pre-"
                        "scale-out variant).  Default since the multi-"
                        "controller scale-out: one real shard worker "
                        "process per partition, the victim is SIGKILLed")
    p.add_argument("--cell-outage", action="store_true",
                   help="chaos: multi-cell federation outage "
                        "(sim/federation.py): N real cells behind the "
                        "front-door router, one cell hard-killed "
                        "mid-traffic then reclaimed; exit 1 on any lost "
                        "acked submission, split gang, faked-fresh "
                        "read, breaker cascade, or stalled survivors")
    p.add_argument("--cells", type=int, default=None,
                   help="cell-outage: number of federated cells "
                        "(default 2; soak raises to 3)")
    p.add_argument("--soak", action="store_true",
                   help="cell-outage: the slow-tier soak shape (more "
                        "cells, ~5x the traffic)")
    p.add_argument("--parity-pipeline", action="store_true",
                   help="run the pipelined-vs-sync parity harness "
                        "(sim/simulator.py run_pipeline_parity): same "
                        "launched job set, no duplicate live instances; "
                        "exit 1 on divergence")
    args = p.parse_args(argv)

    if args.crashpoints:
        from .crashpoint import run_crashpoints
        cres = run_crashpoints(
            n_jobs=args.jobs or 4,
            stride=args.crashpoint_stride or 1)
        print(json.dumps(cres.summary(), indent=2))
        return 0 if cres.ok else 1

    if args.parity_pipeline:
        from .simulator import run_pipeline_parity
        result = run_pipeline_parity(
            seed=args.seed or 0, n_jobs=args.jobs or 60,
            n_hosts=args.n_hosts or 10,
            depth=args.pipeline_depth or 2, backend=args.backend)
        print(json.dumps(result, indent=2))
        return 0 if result["ok"] else 1

    if args.chaos and args.cell_outage:
        from .federation import CellOutageConfig, run_cell_outage
        occ = CellOutageConfig(seed=args.seed or 0, soak=args.soak)
        if args.cells is not None:
            occ.n_cells = args.cells
            occ.__post_init__()
        if args.jobs is not None:
            occ.n_batches = max(args.jobs // 2, 4)
        oresult = run_cell_outage(occ)
        print(json.dumps(oresult.summary(), indent=2))
        return 0 if oresult.ok else 1

    if args.chaos_failover:
        if args.partitions and args.partitions > 1:
            from .chaos import (PartitionChaosConfig, run_partition_chaos,
                                run_partition_chaos_procs)
            pcc = PartitionChaosConfig(
                seed=args.seed or 0, partitions=args.partitions,
                group_commit=not args.no_group_commit,
                process_kill=not args.in_process)
            runner = (run_partition_chaos if args.in_process
                      else run_partition_chaos_procs)
            presult = runner(pcc)
            print(json.dumps(presult.summary(), indent=2))
            return 0 if presult.ok else 1
        from .chaos import FailoverChaosConfig, run_failover_chaos
        result = run_failover_chaos(FailoverChaosConfig(
            seed=args.seed or 0, leader_mode=args.leader_mode,
            group_commit=not args.no_group_commit))
        print(json.dumps(result.summary(), indent=2))
        return 0 if result.ok else 1

    if args.overload and not args.chaos:
        from .overload import run_overload
        summary = run_overload(
            offered_multiple=args.overload_multiple or 10.0,
            seed=args.seed if args.seed is not None else 17)
        print(json.dumps(summary, indent=2))
        return 0 if summary["ok"] else 1

    if args.chaos:
        from .chaos import ChaosConfig, run_chaos
        cc = ChaosConfig(seed=args.seed or 0)
        if args.overload:
            cc.overload = True
        if args.jobs is not None:
            cc.n_jobs = args.jobs
        if args.n_hosts is not None:
            cc.n_hosts = args.n_hosts
        if args.leader_kill_at_ms is not None:
            cc.leader_kill_at_ms = (None if args.leader_kill_at_ms < 0
                                    else args.leader_kill_at_ms)
        if args.pipeline_depth is not None:
            cc.pipeline_depth = args.pipeline_depth
        if args.gangs is not None:
            cc.n_gangs = args.gangs
        if args.gang_size is not None:
            cc.gang_size = args.gang_size
        if args.elastic:
            cc.elastic = True
            if not cc.n_gangs:
                cc.n_gangs = 2
        if args.resident:
            cc.resident = True
        if args.delta_faults is not None:
            cc.delta_fault_probability = args.delta_faults
        if args.disk_faults is not None:
            cc.disk_fault_probability = args.disk_faults
        result = run_chaos(cc)
        print(json.dumps(result.summary(), indent=2))
        return 0 if result.ok else 1

    if args.workload:
        spec = json.load(open(args.workload))
        trace_entries = generate_trace(spec, seed=args.seed)
        if args.emit_trace:
            with open(args.emit_trace, "w") as f:
                json.dump(trace_entries, f)
        host_entries = (json.load(open(args.hosts)) if args.hosts
                        else generate_hosts(args.n_hosts or 20))
    else:
        trace_entries = (json.load(open(args.trace)) if args.trace
                         else generate_example_trace(
                             args.jobs or 200, seed=args.seed or 0))
        host_entries = (json.load(open(args.hosts)) if args.hosts
                        else generate_example_hosts(args.n_hosts or 20))
    sim = Simulator(load_trace(trace_entries), load_hosts(host_entries),
                    backend=args.backend)
    result = sim.run()
    print(json.dumps(result.summary(), indent=2))
    if args.out:
        import csv
        with open(args.out, "w", newline="") as f:
            writer = csv.DictWriter(f, fieldnames=[
                "job", "user", "task", "host", "status", "start", "end",
                "wait_ms", "preempted"])
            writer.writeheader()
            writer.writerows(result.task_records)
    return 0


if __name__ == "__main__":
    sys.exit(main())
