"""Faster-than-real-time overload replay (docs/ROBUSTNESS.md).

The admission plane's proof harness: synthesize a Borg-trace-shaped
workload — heavy-tailed users (a couple of heavy hitters dominating
offered load over a long light tail), lognormal service times — at a
configurable multiple of sustainable capacity, replay it through the
REAL scheduler with the admission controller enabled, and report what
the brownout ladder actually did:

- the front door sheds excess offered load (per-user token buckets whose
  refill the controller scales by the admission level), so ADMITTED work
  keeps completing instead of every submission timing out together — the
  goodput-under-overload property (DAGOR, SoCC '18; metastable-failure
  avoidance, Bronson et al., HotOS '21);
- saturation is driven GENUINELY: a small launch-token bucket on the
  virtual clock saturates under pressure exactly the way the production
  monitor sweep reads it (sched/fleet.py ``launch_tokens``), no gauges
  are faked;
- brownout stages must engage in shed order (observability -> stale
  reads -> writes) and every flip is journaled via the dynamic-config
  plane (sched/admission.py);
- zero committed-write loss: every ADMITTED job exists in the store and
  reaches a terminal state; shed jobs were refused up front with an
  attributable reason, never accepted-then-dropped.

Run it: ``python -m cook_tpu.sim --overload [--overload-multiple N]``;
asserted by tests/test_overload.py and benched by the ``overload`` leg
in bench.py (docs/BENCH_CPU_r17_overload.json).
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ..config import Config
from ..policy import RateLimits, TokenBucketRateLimiter, submission_limiter
from .simulator import Simulator, load_hosts, load_trace
from .workload import generate_hosts, generate_trace

#: heavy-tailed user mix (offered-load share, user count) — two heavy
#: hitters carry half the load, a long tail of light users the rest,
#: the shape cluster traces actually have (Borg trace; PAPER.md)
USER_MIX = (("heavy", 2, 0.50), ("medium", 6, 0.35), ("light", 16, 0.15))


def overload_spec(offered_per_min: float, horizon_ms: int = 45_000,
                  duration_mu: float = 8.0, duration_sigma: float = 0.6,
                  seed: int = 17) -> Dict:
    """A workload spec totalling ``offered_per_min`` arrivals across the
    heavy-tailed :data:`USER_MIX`; lognormal service times (median
    ``e**duration_mu`` ms)."""
    classes = []
    for name, users, share in USER_MIX:
        classes.append({
            "name": name, "users": users,
            "arrival_rate_per_min": offered_per_min * share / users,
            "duration_ms": {"dist": "lognormal", "mu": duration_mu,
                            "sigma": duration_sigma},
            "cpus": {"dist": "choice", "values": [1, 2],
                     "weights": [0.8, 0.2]},
            "mem": {"dist": "uniform", "low": 64, "high": 512},
            # a slice of every class is low-priority — the stage-3
            # write shed needs sheddable traffic to act on
            "priority": {"dist": "choice", "values": [10, 50, 80],
                         "weights": [0.3, 0.5, 0.2]},
        })
    return {"seed": seed, "horizon_ms": int(horizon_ms),
            "user_classes": classes}


def _overload_config(stage_hold_s: float) -> Config:
    cfg = Config()
    cfg.default_matcher.backend = "cpu"
    cfg.admission.enabled = True
    # per-user front-door budget: generous for the light tail, a hard
    # wall for the heavy hitters once the level scales refill down
    cfg.admission.submissions_per_minute = 60.0
    cfg.admission.submission_burst = 10.0
    cfg.admission.stage_hold_seconds = float(stage_hold_s)
    return cfg


def run_overload(offered_multiple: float = 10.0,
                 sustainable_per_min: float = 60.0,
                 n_hosts: int = 3, horizon_ms: int = 30_000,
                 launch_rate_per_min: float = 30.0,
                 launch_burst: float = 2.0,
                 sweep_interval_ms: int = 1_000,
                 stage_hold_s: float = 4.0,
                 seed: int = 17,
                 admission: bool = True,
                 max_virtual_ms: int = 20 * 60 * 1000) -> Dict:
    """Replay ``offered_multiple`` x sustainable offered load through the
    real scheduler, admission controller in the loop (or bypassed with
    ``admission=False`` for the melt-down baseline), and summarize the
    ladder's behavior.  Deterministic for a given seed: the virtual
    clock drives arrivals, sweeps, bucket refills, and stage dwell."""
    spec = overload_spec(offered_multiple * sustainable_per_min,
                         horizon_ms=horizon_ms, seed=seed)
    trace = load_trace(generate_trace(spec, seed=seed))
    hosts = load_hosts(generate_hosts(n_hosts, cpus=8.0, mem=32768.0))

    cfg = _overload_config(stage_hold_s)
    cfg.admission.enabled = bool(admission)

    # one virtual timebase for EVERYTHING: the sim run patches
    # store.clock, and the token buckets read the same box in seconds
    now_box = [trace[0].submit_time_ms / 1000.0 if trace else 0.0]
    clock_s = lambda: now_box[0]  # noqa: E731 - one timebase
    launch_rl = TokenBucketRateLimiter(
        launch_rate_per_min, launch_burst, enforce=True, clock=clock_s)
    limits = RateLimits(job_launch=launch_rl)
    limits.job_submission = submission_limiter(
        cfg.admission if admission else None, clock=clock_s)

    sim = Simulator(trace, hosts, config=cfg, backend="cpu",
                    rate_limits=limits)
    ctrl = sim.scheduler.admission
    shed: Dict[str, int] = {}
    min_level = [1.0]
    next_sweep = [trace[0].submit_time_ms if trace else 0]

    def admit(job, now_ms: int) -> bool:
        now_box[0] = now_ms / 1000.0
        ac = cfg.admission
        stage = ctrl.stage if ctrl is not None else 0
        if ac.enabled and stage >= 3 \
                and job.priority < ac.shed_priority_below:
            shed["brownout-shed"] = shed.get("brownout-shed", 0) + 1
            return False
        rl = limits.job_submission
        if getattr(rl, "enforce", False) and not rl.try_spend(job.user):
            shed["rate-limited"] = shed.get("rate-limited", 0) + 1
            return False
        return True

    def tick(now_ms: int) -> None:
        now_box[0] = now_ms / 1000.0
        if now_ms >= next_sweep[0]:
            sim.scheduler.monitor.sweep()
            if ctrl is not None:
                min_level[0] = min(min_level[0], ctrl.level)
            next_sweep[0] = now_ms + sweep_interval_ms

    sim.admit = admit
    sim.on_tick = tick
    try:
        res = sim.run(max_virtual_ms=max_virtual_ms)
    finally:
        # the controller flips process-global planes (request-capture
        # ring, audit advisory shed); a run that ENDS mid-brownout must
        # not leak the shed into the caller's process
        from ..rest.instrument import request_log
        request_log.capture = True
        sim.store.audit.shed_advisory = False

    admitted = res.total - len(sim.shed_job_uuids)
    # zero committed-write loss: every admitted job is in the store and
    # reached a terminal state; sheds were refused up front, never
    # accepted-then-dropped
    lost = [j.uuid for j in trace
            if j.uuid not in set(sim.shed_job_uuids)
            and sim.store.job(j.uuid) is None]
    transitions = list(ctrl.transitions) if ctrl is not None else []
    first_engaged: Dict[int, int] = {}
    for t in transitions:
        for k in range(1, int(t["to"]) + 1):
            first_engaged.setdefault(k, t["ts_ms"])
    engaged = sorted(first_engaged)
    # shed order: observability (1) never engages AFTER stale reads (2),
    # which never engages after the write shed (3) — the ladder is
    # monotone even across multi-threshold jumps
    order_ok = all(
        first_engaged[a] <= first_engaged[b]
        for a, b in zip(engaged, engaged[1:]))
    wt = np.asarray(res.wait_times_ms or [0])
    summary = {
        "offered": res.total,
        "offered_multiple": offered_multiple,
        "admitted": admitted,
        "shed": dict(sorted(shed.items())),
        "shed_total": len(sim.shed_job_uuids),
        "completed": res.completed,
        "completion_rate_of_admitted": (res.completed / admitted
                                        if admitted else 1.0),
        "committed_writes_lost": len(lost),
        "wait_p50_s": float(np.percentile(wt, 50)) / 1000.0,
        "wait_p99_s": float(np.percentile(wt, 99)) / 1000.0,
        "makespan_virtual_s": res.makespan_ms / 1000.0,
        "admission": {
            "enabled": bool(admission),
            "min_level": round(min_level[0], 4),
            "final_level": round(ctrl.level, 4) if ctrl else None,
            "max_stage": max((int(t["to"]) for t in transitions),
                             default=0),
            "final_stage": ctrl.stage if ctrl else 0,
            "transitions": len(transitions),
            "stage_order_ok": order_ok,
            "stages_engaged": engaged,
        },
    }
    summary["ok"] = (not lost
                     and order_ok
                     and (not admission or summary["admission"]
                          ["max_stage"] >= 1 or admitted == res.total)
                     and summary["completion_rate_of_admitted"] > 0.95)
    return summary
