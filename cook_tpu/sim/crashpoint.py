"""Exhaustive crash-point recovery harness for the persistence plane.

The CrashMonkey/ALICE discipline (Mohan et al., OSDI'18; Pillai et al.,
OSDI'14) applied to the store's WAL: run a scripted workload, crash or
corrupt it at EVERY registered disk-fault site and EVERY record byte
boundary, recover, and assert the storage contract (docs/ROBUSTNESS.md
"WAL v2"):

* **zero committed-transaction loss** — every operation that returned
  success before the crash is visible after recovery;
* **zero phantom resurrection** — every operation that FAILED (clean
  abort) is absent after recovery;
* **torn tails are excised, mid-file damage refuses** — a truncated
  final frame recovers silently; a CRC-failing complete frame (or
  garbage with valid records after it) raises
  :class:`~cook_tpu.state.integrity.JournalCorruptionError` instead of
  silently truncating committed history;
* **repair converges byte-identically** — healing a corrupt journal
  from a synced peer over the framed-TCP carrier (or the in-process
  scrub self-heal) ends with state equal to the pristine run, and the
  pulled journal bytes equal to the peer's;
* **read-view rebuild parity** — a
  :class:`~cook_tpu.state.read_replica.FollowerReadView` tailing the
  recovered directory reaches the same entity state as the recovered
  store.

Legs (each an independent matrix; ``python -m cook_tpu.sim
--crashpoints`` runs all of them, tests/test_crashpoint.py smoke-runs a
reduced matrix in tier-1 and the full soak under ``-m slow``):

==================  =====================================================
``fault-site``      every registered store fault point
                    (``store.journal.torn_write`` / ``bitflip`` /
                    ``fsync_lie`` / ``enospc`` / ``append``) armed at
                    every append index of the workload
``byte-boundary``   the clean run's journal truncated at every record
                    boundary and at cut points inside every frame —
                    the crash-mid-append shapes
``corruption``      one bit flipped in every record of the clean run's
                    journal — replay must refuse, then heal from a
                    synced peer (byte-identical) or quarantine+copy
                    when the native carrier is unavailable
``checkpoint``      checkpoint-time crash windows: manifest mismatch
                    falls back to the previous generation; an injected
                    ``fsatomic.fsync`` failure aborts the checkpoint
                    without losing the live store
==================  =====================================================
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..state.integrity import JournalCorruptionError, scan_journal
from ..state.read_replica import FollowerReadView
from ..state.schema import InstanceStatus, Job, Resources
from ..state.store import AbortTransaction, StorageFullError, Store

#: everything an op is allowed to fail with while a fault is armed —
#: the injected fault itself (OSError / StorageFullError), the store's
#: clean abort (AbortTransaction), and the follow-on failures of ops
#: whose predecessor aborted (launch of a never-created job, status of
#: a never-launched instance)
_OP_ABORTS = (AbortTransaction, OSError, StorageFullError, RuntimeError,
              ValueError, KeyError)
from ..utils.faults import injector

#: the disk-fault points this harness sweeps (registered in
#: utils/faults.py and documented in docs/ROBUSTNESS.md)
DISK_FAULT_POINTS = (
    "store.journal.append",
    "store.journal.torn_write",
    "store.journal.bitflip",
    "store.journal.fsync_lie",
    "store.journal.enospc",
)


# ---------------------------------------------------------------------------
# scripted workload
# ---------------------------------------------------------------------------

def _make_job(i: int) -> Job:
    return Job(uuid=f"00000000-0000-4000-8000-{i:012d}", user=f"u{i % 3}",
               command="echo crashpoint", pool="default",
               resources=Resources(cpus=1.0, mem=64.0), priority=50,
               max_retries=1)


def build_ops(n_jobs: int) -> List[Tuple]:
    """The deterministic op script: create / launch / run / finish /
    kill, interleaved so the journal carries every record shape the
    store emits (job create, instance launch, status transitions, kill
    tombstones, audit piggybacks)."""
    ops: List[Tuple] = []
    for i in range(n_jobs):
        ops.append(("create", i))
        ops.append(("launch", i, f"task-{i}", f"host-{i % 4}"))
        ops.append(("status", f"task-{i}", InstanceStatus.RUNNING))
        if i % 3 == 0:
            ops.append(("status", f"task-{i}", InstanceStatus.SUCCESS))
        elif i % 3 == 1:
            ops.append(("kill", i))
    return ops


def apply_op(store: Store, op: Tuple) -> None:
    kind = op[0]
    if kind == "create":
        store.create_jobs([_make_job(op[1])])
    elif kind == "launch":
        store.launch_instance(_make_job(op[1]).uuid, op[2], op[3])
    elif kind == "status":
        store.update_instance_status(op[1], op[2])
    elif kind == "kill":
        store.kill_job(_make_job(op[1]).uuid)
    else:  # pragma: no cover - script bug surface
        raise ValueError(f"unknown op {kind}")


def state_digest(store: Store) -> Tuple:
    """Order-independent entity-state fingerprint: job states plus
    per-instance statuses.  Two stores with equal digests agree on
    every committed transaction's visible effect."""
    rows = []
    for job in store.jobs_where(lambda j: True):
        insts = tuple(sorted(
            (t, store.instance(t).status.name)
            for t in job.instances if store.instance(t) is not None))
        rows.append((job.uuid, job.state.name, insts))
    return tuple(sorted(rows))


# ---------------------------------------------------------------------------
# result accounting
# ---------------------------------------------------------------------------

@dataclass
class CrashPointResult:
    cases: int = 0
    violations: List[Dict[str, Any]] = field(default_factory=list)
    legs: Dict[str, int] = field(default_factory=dict)
    notes: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations

    def case(self, leg: str) -> None:
        self.cases += 1
        self.legs[leg] = self.legs.get(leg, 0) + 1

    def violate(self, leg: str, case: str, detail: str) -> None:
        self.violations.append({"leg": leg, "case": case,
                                "detail": detail})

    def summary(self) -> Dict[str, Any]:
        return {"ok": self.ok, "cases": self.cases, "legs": self.legs,
                "violations": self.violations,
                **({"notes": self.notes} if self.notes else {})}


class _Run:
    """One pristine workload execution: the directory, the per-op
    committed byte offsets, and the digest after each op — the ground
    truth every crash case is judged against."""

    def __init__(self, directory: str, n_jobs: int):
        self.directory = directory
        self.ops = build_ops(n_jobs)
        store = Store.open(directory, fsync=True)
        self.op_offsets: List[int] = []   # journal bytes after op i
        self.op_digests: List[Tuple] = []  # digest after op i
        for op in self.ops:
            apply_op(store, op)
            self.op_offsets.append(store._commit_offset)
            self.op_digests.append(state_digest(store))
        self.final_digest = state_digest(store)
        store.close()
        with open(os.path.join(directory, "journal.jsonl"), "rb") as f:
            self.journal = f.read()
        # record boundaries: byte offset where each journal line starts
        self.line_starts: List[int] = [0]
        at = 0
        while True:
            nl = self.journal.find(b"\n", at)
            if nl < 0 or nl + 1 >= len(self.journal):
                break
            self.line_starts.append(nl + 1)
            at = nl + 1

    def digest_at(self, byte_offset: int) -> Tuple:
        """The expected digest after recovering a journal cut at
        ``byte_offset``: the last op whose commit offset fits."""
        best: Tuple = ()
        for off, dig in zip(self.op_offsets, self.op_digests):
            if off <= byte_offset:
                best = dig
            else:
                break
        return best


def _fresh_copy(run: _Run, base: str, name: str) -> str:
    d = os.path.join(base, name)
    shutil.copytree(run.directory, d)
    return d


def _flip_mid_byte(path: str) -> None:
    """Flip one bit in the middle byte of *path* in place."""
    with open(path, "r+b") as f:
        f.seek(max(0, os.path.getsize(path) // 2))
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0x40]))


def _read_view_digest(directory: str) -> Optional[Tuple]:
    view = FollowerReadView(directory, start=False)
    try:
        view.poll()
        if view.corrupt is not None:
            return None
        return state_digest(view.store)
    finally:
        view.stop()


# ---------------------------------------------------------------------------
# legs
# ---------------------------------------------------------------------------

def _leg_fault_sites(res: CrashPointResult, base: str, n_jobs: int,
                     stride: int) -> None:
    """Arm each disk-fault point at each append index, run the
    workload around the injected failure, crash, recover, and check
    the committed/aborted ledger."""
    probe = _Run(os.path.join(base, "probe"), n_jobs)
    n_appends = len(probe.ops)
    for point in DISK_FAULT_POINTS:
        for at in range(0, n_appends, max(1, stride)):
            res.case("fault-site")
            case = f"{point}@{at}"
            d = os.path.join(base, f"fs-{point.split('.')[-1]}-{at}")
            injector.clear()
            store = Store.open(d, fsync=True)
            injector.arm(point, schedule=[at], max_fires=1)
            silent_corruption = point == "store.journal.bitflip"
            try:
                for op in probe.ops:
                    try:
                        apply_op(store, op)
                    except _OP_ABORTS as e:
                        if isinstance(e, StorageFullError) \
                                and point != "store.journal.enospc":
                            res.violate("fault-site", case,
                                        f"unexpected StorageFullError: {e}")
                        # the aborted op — and any dependent op after it
                        # (a launch whose create aborted) — drops out of
                        # the ledger; the in-memory digest below is the
                        # pre-crash truth either way
            finally:
                injector.clear()
            expected = state_digest(store)  # pre-crash truth
            del store  # crash: no close(), no checkpoint
            try:
                recovered = Store.open(d, fsync=False)
            except JournalCorruptionError:
                if not silent_corruption:
                    res.violate("fault-site", case,
                                "recovery refused a journal that held "
                                "no mid-file damage")
                    continue
                # the bit flipped inside a committed frame: refusal IS
                # the contract.  Heal via the scrub path on a live
                # store: re-run the workload with the same flip, scrub
                # detects + checkpoints from memory, then recovery
                # succeeds.
                shutil.rmtree(d)
                store = Store.open(d, fsync=True)
                injector.arm(point, schedule=[at], max_fires=1)
                try:
                    for op in probe.ops:
                        try:
                            apply_op(store, op)
                        except _OP_ABORTS:
                            pass
                finally:
                    injector.clear()
                expected = state_digest(store)
                scrub_doc = {}
                while True:
                    scrub_doc = store.scrub(max_bytes=1 << 16,
                                            repair=True)
                    if scrub_doc.get("corrupt") \
                            or not scrub_doc.get("enabled") \
                            or scrub_doc.get("verified_offset", 0) \
                            >= scrub_doc.get("journal_bytes", 0):
                        break
                if scrub_doc.get("corrupt") \
                        and not scrub_doc.get("repaired"):
                    res.violate("fault-site", case,
                                "scrub detected corruption but did not "
                                "self-heal via checkpoint")
                    continue
                del store
                try:
                    recovered = Store.open(d, fsync=False)
                except JournalCorruptionError as e:
                    res.violate("fault-site", case,
                                f"post-scrub recovery still refused: {e}")
                    continue
            got = state_digest(recovered)
            if got != expected:
                res.violate("fault-site", case,
                            f"recovered state diverged: {len(got)} rows "
                            f"vs expected {len(expected)}")
            recovered.close()


def _leg_byte_boundary(res: CrashPointResult, run: _Run, base: str,
                       cuts_per_line: int) -> None:
    """Truncate the pristine journal at every record boundary and at
    cut points inside every frame — every shape a crash mid-append can
    leave — and assert recovery lands exactly on the committed
    prefix."""
    for li, start in enumerate(run.line_starts):
        end = (run.line_starts[li + 1]
               if li + 1 < len(run.line_starts) else len(run.journal))
        width = end - start
        cuts = {0}
        if width > 2 and cuts_per_line > 1:
            cuts.add(width // 2)
            cuts.add(width - 1)
        for cut in sorted(cuts):
            at = start + cut
            res.case("byte-boundary")
            case = f"line{li}+{cut}"
            d = os.path.join(base, f"bb-{li}-{cut}")
            os.makedirs(d)
            with open(os.path.join(d, "journal.jsonl"), "wb") as f:
                f.write(run.journal[:at])
            try:
                store = Store.open(d, fsync=False)
            except JournalCorruptionError as e:
                res.violate("byte-boundary", case,
                            f"torn tail refused instead of excised: {e}")
                continue
            expected = run.digest_at(at)
            got = state_digest(store)
            if got != expected:
                res.violate(
                    "byte-boundary", case,
                    f"recovered {len(got)} rows, expected "
                    f"{len(expected)} (committed-prefix mismatch)")
            store.close()


def _leg_corruption(res: CrashPointResult, run: _Run, base: str,
                    repl_port: Optional[int]) -> None:
    """Flip one bit in every record of the pristine journal: replay
    must REFUSE (never silently truncate the committed records beyond
    the damage), and repair must converge byte-identically — from a
    synced peer over the real carrier when available, else via
    quarantine + copy."""
    for li, start in enumerate(run.line_starts):
        end = (run.line_starts[li + 1]
               if li + 1 < len(run.line_starts) else len(run.journal))
        res.case("corruption")
        case = f"line{li}"
        d = os.path.join(base, f"cr-{li}")
        os.makedirs(d)
        flip_at = start + max(0, (end - start) // 2 - 1)
        damaged = bytearray(run.journal)
        damaged[flip_at] ^= 0x40
        with open(os.path.join(d, "journal.jsonl"), "wb") as f:
            f.write(bytes(damaged))
        refused = False
        try:
            store = Store.open(d, fsync=False)
            store.close()
        except JournalCorruptionError:
            refused = True
        if not refused:
            res.violate("corruption", case,
                        "mid-file corruption replayed without refusal "
                        "(silent truncation or bad-frame acceptance)")
            continue
        # heal: real peer pull when the native carrier is built,
        # quarantine+copy otherwise — both must converge byte-identical
        if repl_port is not None:
            from ..state.repair import open_with_repair
            try:
                store = open_with_repair(
                    d, peers=[("127.0.0.1", repl_port)], timeout_s=10.0)
            except JournalCorruptionError as e:
                res.violate("corruption", case,
                            f"peer repair failed: {e}")
                continue
        else:
            from ..state.repair import quarantine
            quarantine(d)
            shutil.copyfile(os.path.join(run.directory, "journal.jsonl"),
                            os.path.join(d, "journal.jsonl"))
            store = Store.open(d, fsync=False)
        if state_digest(store) != run.final_digest:
            res.violate("corruption", case,
                        "repaired state != pristine state")
        store.close()
        with open(os.path.join(d, "journal.jsonl"), "rb") as f:
            healed = f.read()
        if healed != run.journal:
            res.violate("corruption", case,
                        "repaired journal bytes != peer journal bytes "
                        f"({len(healed)} vs {len(run.journal)})")
        # read-view parity over the healed directory
        rv_digest = _read_view_digest(d)
        if rv_digest != run.final_digest:
            res.violate("corruption", case,
                        "read-view rebuild diverged from the healed "
                        "store")


def _leg_checkpoint(res: CrashPointResult, run: _Run, base: str,
                    n_jobs: int) -> None:
    """Checkpoint-time crash windows (state/store.py checkpoint
    rotation order): a damaged current snapshot falls back to the
    previous generation + rotated journal; a manifest-less snapshot
    loads legacy; an injected temp-fsync failure aborts the checkpoint
    with the live store intact."""
    ops = build_ops(n_jobs)
    half = len(ops) // 2

    # (a) snapshot bitflip with a previous generation on disk: two
    # checkpoints so the rotation has hard-linked gen N-1 aside
    # (snapshot.prev.json + journal.prev.jsonl), then damage gen N —
    # open must fall back and replay the prev chain to full state
    res.case("checkpoint")
    d = os.path.join(base, "ck-snap")
    store = Store.open(d, fsync=True)
    third = max(1, len(ops) // 3)
    for op in ops[:third]:
        apply_op(store, op)
    store.checkpoint()
    for op in ops[third:2 * third]:
        apply_op(store, op)
    store.checkpoint()
    for op in ops[2 * third:]:
        apply_op(store, op)
    expected = state_digest(store)
    store.close()
    _flip_mid_byte(os.path.join(d, "snapshot.json"))
    try:
        reopened = Store.open(d, fsync=False)
    except JournalCorruptionError as e:
        res.violate("checkpoint", "snapshot-bitflip",
                    f"prev-generation fallback failed: {e}")
    else:
        if state_digest(reopened) != expected:
            res.violate("checkpoint", "snapshot-bitflip",
                        "fallback chain lost state")
        reopened.close()

    # (a') snapshot bitflip with NO previous generation: refusing is
    # the contract — silently proceeding would serve poisoned state
    res.case("checkpoint")
    d = os.path.join(base, "ck-snap-sole")
    store = Store.open(d, fsync=True)
    for op in ops[:half]:
        apply_op(store, op)
    store.checkpoint()
    store.close()
    _flip_mid_byte(os.path.join(d, "snapshot.json"))
    try:
        Store.open(d, fsync=False).close()
    except JournalCorruptionError:
        pass
    else:
        res.violate("checkpoint", "snapshot-sole-bitflip",
                    "open accepted a damaged snapshot with no "
                    "fallback generation")

    # (b) fsatomic.fsync failure DURING checkpoint: abort, store live,
    # reopen replays the untouched journal
    res.case("checkpoint")
    d = os.path.join(base, "ck-fsync")
    store = Store.open(d, fsync=True)
    for op in ops[:half]:
        apply_op(store, op)
    injector.arm("fsatomic.fsync", schedule=[0], max_fires=1)
    ck_failed = False
    try:
        store.checkpoint()
    except OSError:
        ck_failed = True
    finally:
        injector.clear()
    if not ck_failed:
        res.notes.append("checkpoint fsync fault did not surface "
                         "(atomic-write path absorbed it)")
    for op in ops[half:]:
        try:
            apply_op(store, op)
        except (OSError, RuntimeError):
            res.violate("checkpoint", "fsync-abort",
                        "store unusable after aborted checkpoint")
            break
    expected = state_digest(store)
    store.close()
    try:
        reopened = Store.open(d, fsync=False)
    except JournalCorruptionError as e:
        res.violate("checkpoint", "fsync-abort",
                    f"recovery refused after aborted checkpoint: {e}")
    else:
        if state_digest(reopened) != expected:
            res.violate("checkpoint", "fsync-abort",
                        "aborted checkpoint lost committed state")
        # the aborted atomic write's temp is the hygiene sweep's prey:
        # nothing dot-tmp may survive the reopen
        leftovers = [n for n in os.listdir(d)
                     if n.startswith(".") and ".tmp." in n]
        if leftovers:
            res.notes.append(f"hygiene left temps (young): {leftovers}")
        reopened.close()


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def run_crashpoints(n_jobs: int = 4, stride: int = 1,
                    cuts_per_line: int = 3,
                    use_replication: bool = True,
                    workdir: Optional[str] = None) -> CrashPointResult:
    """Run every leg of the crash matrix.  ``n_jobs`` scales the
    scripted workload (the tier-1 smoke uses a small one; the slow
    soak and the CLI default drive the full script), ``stride``
    subsamples the fault-site append indices, ``cuts_per_line``
    bounds the intra-frame cut points (1 = boundaries only)."""
    res = CrashPointResult()
    injector.clear()
    own_tmp = workdir is None
    base = workdir or tempfile.mkdtemp(prefix="cook-crashpoint-")
    server = None
    repl_port = None
    try:
        run = _Run(os.path.join(base, "pristine"), n_jobs)
        # sanity: the pristine journal must scan clean end to end
        scan = scan_journal(os.path.join(run.directory, "journal.jsonl"))
        if scan.corrupt:
            res.violate("setup", "pristine",
                        f"clean run scanned corrupt: {scan.reason}")
            return res
        if use_replication:
            try:
                from ..state.replication import (ReplicationServer,
                                                 replication_available)
                if replication_available():
                    server = ReplicationServer(run.directory, port=0)
                    repl_port = server.port
                else:
                    res.notes.append("native replication unavailable — "
                                     "corruption leg heals via "
                                     "quarantine+copy")
            except Exception as e:
                res.notes.append(f"replication server unavailable: {e}")
        _leg_fault_sites(res, base, n_jobs, stride)
        _leg_byte_boundary(res, run, base, cuts_per_line)
        _leg_corruption(res, run, base, repl_port)
        _leg_checkpoint(res, run, base, n_jobs)
    finally:
        injector.clear()
        if server is not None:
            server.stop()
        if own_tmp:
            shutil.rmtree(base, ignore_errors=True)
    return res


def main(argv=None) -> int:  # pragma: no cover - CLI shim
    import argparse
    p = argparse.ArgumentParser(prog="cook_tpu.sim.crashpoint")
    p.add_argument("--jobs", type=int, default=4)
    p.add_argument("--stride", type=int, default=1)
    p.add_argument("--no-replication", action="store_true")
    args = p.parse_args(argv)
    res = run_crashpoints(n_jobs=args.jobs, stride=args.stride,
                          use_replication=not args.no_replication)
    print(json.dumps(res.summary(), indent=2))
    return 0 if res.ok else 1


if __name__ == "__main__":  # pragma: no cover
    import sys
    sys.exit(main())
