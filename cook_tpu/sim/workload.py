"""Statistical workload generator for the simulator.

The port of the reference's system simulator concept (reference:
simulator/README.md:1-6 — generate statistical workloads against a
fully-stood-up scheduler and report wait times): instead of replaying a
recorded trace, synthesize one from per-user-class distributions — Poisson
arrivals per user, pluggable duration/resource/priority distributions —
then feed it to :class:`cook_tpu.sim.Simulator` and read wait-time
percentiles off ``SimResult.summary()``.

Spec format (JSON-friendly):
  {"seed": 42, "horizon_ms": 3600000,
   "user_classes": [
     {"name": "batch", "users": 5, "arrival_rate_per_min": 6.0,
      "pool": "default",
      "duration_ms": {"dist": "lognormal", "mu": 10.0, "sigma": 1.0},
      "cpus":     {"dist": "choice", "values": [1, 2, 4],
                   "weights": [0.6, 0.3, 0.1]},
      "mem":      {"dist": "uniform", "low": 128, "high": 4096},
      "priority": {"dist": "constant", "value": 50}}]}

Distributions: constant(value), uniform(low, high), lognormal(mu, sigma),
exponential(scale), choice(values[, weights]).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


def sample(spec, rng: np.random.Generator, size: int) -> np.ndarray:
    """Draw ``size`` samples from a distribution spec (scalars allowed)."""
    if isinstance(spec, (int, float)):
        return np.full(size, float(spec))
    dist = spec.get("dist", "constant")
    if dist == "constant":
        return np.full(size, float(spec["value"]))
    if dist == "uniform":
        return rng.uniform(float(spec["low"]), float(spec["high"]), size)
    if dist == "lognormal":
        return rng.lognormal(float(spec["mu"]), float(spec["sigma"]), size)
    if dist == "exponential":
        return rng.exponential(float(spec["scale"]), size)
    if dist == "choice":
        values = np.asarray(spec["values"], dtype=float)
        weights = spec.get("weights")
        p = None
        if weights is not None:
            p = np.asarray(weights, dtype=float)
            p = p / p.sum()
        return rng.choice(values, size=size, p=p)
    raise ValueError(f"unknown distribution {dist!r}")


def _poisson_arrivals(rate_per_ms: float, horizon_ms: int,
                      rng: np.random.Generator) -> np.ndarray:
    """Arrival times of a Poisson process on [0, horizon)."""
    if rate_per_ms <= 0:
        return np.empty(0)
    expected = rate_per_ms * horizon_ms
    # draw enough exponential gaps to cover the horizon w.h.p., then trim
    n = max(16, int(expected + 6 * np.sqrt(expected) + 16))
    gaps = rng.exponential(1.0 / rate_per_ms, n)
    times = np.cumsum(gaps)
    while times.size and times[-1] < horizon_ms:  # tail top-up, rare
        extra = rng.exponential(1.0 / rate_per_ms, n)
        times = np.concatenate([times, times[-1] + np.cumsum(extra)])
    return times[times < horizon_ms]


def generate_trace(spec: Dict, seed: Optional[int] = None) -> List[Dict]:
    """Synthesize simulator trace entries from a workload spec.

    Deterministic for a given (spec, seed); entries are sorted by
    submit_time and match the Simulator/load_trace schema.
    """
    rng = np.random.default_rng(
        seed if seed is not None else spec.get("seed", 0))
    horizon_ms = int(spec.get("horizon_ms", 3_600_000))
    entries: List[Dict] = []
    for cls in spec.get("user_classes", []):
        name = cls.get("name", "class")
        n_users = int(cls.get("users", 1))
        rate_per_ms = float(cls.get("arrival_rate_per_min", 1.0)) / 60_000.0
        for u in range(n_users):
            user = f"{name}{u:03d}"
            arrivals = _poisson_arrivals(rate_per_ms, horizon_ms, rng)
            k = arrivals.size
            if k == 0:
                continue
            durations = sample(cls.get("duration_ms", 60_000), rng, k)
            cpus = sample(cls.get("cpus", 1.0), rng, k)
            mem = sample(cls.get("mem", 128.0), rng, k)
            gpus = sample(cls.get("gpus", 0.0), rng, k)
            priority = sample(cls.get("priority", 50), rng, k)
            for i in range(k):
                entries.append({
                    "user": user,
                    "submit_time": int(arrivals[i]),
                    "duration": max(1, int(durations[i])),
                    "cpus": float(cpus[i]),
                    "mem": float(mem[i]),
                    "gpus": float(gpus[i]),
                    "priority": int(np.clip(priority[i], 0, 100)),
                    "pool": cls.get("pool", "default"),
                })
    entries.sort(key=lambda e: e["submit_time"])
    return entries


def generate_hosts(n: int, cpus: float = 16.0, mem: float = 65536.0,
                   gpus: float = 0.0, pool: str = "default") -> List[Dict]:
    """Uniform host fleet for quick experiments."""
    return [{"hostname": f"host{i:04d}", "cpus": cpus, "mem": mem,
             "gpus": gpus, "pool": pool} for i in range(n)]
