"""CLI conformance tier against a live daemon (reference: the scenario
families of integration/tests/cook/test_cli.py — stdin submit, raw JSON
submit, multi-command submit, uuid piping, entity refs, duplicate-uuid
refusal, wait over multiple jobs, kill errors)."""

import json
import os
import subprocess
import sys
import time

import pytest

from test_integration_scenarios import spawn, wait_leader, wait_serving

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def daemon(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("cli-surface")
    conf = {
        "host": "127.0.0.1", "port": 0,
        "data_dir": str(tmp / "data"),
        "election_dir": str(tmp),
        "admins": ["admin"],
        "clusters": [{"factory": "cook_tpu.cluster.fake.factory",
                      "kwargs": {"name": "alpha", "n_hosts": 3,
                                 "cpus": 4.0, "mem": 4096.0,
                                 "default_task_duration_ms": 300,
                                 "auto_advance": True}}],
        "scheduler": {"rank_backend": "cpu", "cycle_mode": "split",
                      "match_interval_seconds": 0.1,
                      "rank_interval_seconds": 0.1},
    }
    p = spawn(conf, tmp, "cli")
    url = wait_serving(p)
    assert wait_leader(url)
    yield url, str(tmp)
    if p.poll() is None:
        p.kill()
    p.wait(timeout=10)


def cli(daemon, *args, stdin=None, user="alice", timeout=60):
    url, home = daemon
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
               COOK_URL=url, COOK_USER=user, HOME=home)
    return subprocess.run(
        [sys.executable, "-m", "cook_tpu.cli.main", *args],
        input=stdin, capture_output=True, text=True, cwd=REPO, env=env,
        timeout=timeout)


class TestStdinSubmit:
    def test_single_command_from_stdin(self, daemon):
        r = cli(daemon, "submit", "--cpus", "1", "--mem", "64",
                stdin="echo from-stdin\n")
        assert r.returncode == 0, r.stderr
        [uuid] = r.stdout.split()
        r = cli(daemon, "wait", uuid, "--timeout", "30")
        assert r.returncode == 0, r.stderr

    def test_multiple_commands_submit_multiple_jobs(self, daemon):
        r = cli(daemon, "submit", "--cpus", "1", "--mem", "64",
                stdin="echo one\necho two\necho three\n")
        assert r.returncode == 0, r.stderr
        uuids = r.stdout.split()
        assert len(uuids) == 3 and len(set(uuids)) == 3
        # wait accepts multiple uuids (reference: test_wait_for_multiple)
        r = cli(daemon, "wait", *uuids, "--timeout", "30")
        assert r.returncode == 0, r.stderr

    def test_empty_stdin_is_an_error(self, daemon):
        r = cli(daemon, "submit", stdin="")
        assert r.returncode == 1
        assert "no command" in r.stderr


class TestRawSubmit:
    def test_raw_object_and_list(self, daemon):
        spec = {"command": "true", "cpus": 1, "mem": 64, "name": "rawjob"}
        r = cli(daemon, "submit", "--raw", stdin=json.dumps(spec))
        assert r.returncode == 0, r.stderr
        [u1] = r.stdout.split()
        r = cli(daemon, "submit", "--raw",
                stdin=json.dumps([spec, dict(spec, name="rawjob2")]))
        assert r.returncode == 0, r.stderr
        assert len(r.stdout.split()) == 2
        r = cli(daemon, "show", u1)
        assert r.returncode == 0
        shown = json.loads(r.stdout)
        assert shown[0]["name"] == "rawjob"

    def test_raw_invalid_json(self, daemon):
        r = cli(daemon, "submit", "--raw", stdin="{not json")
        assert r.returncode == 1
        assert "malformed" in r.stderr

    def test_raw_refuses_command_argument(self, daemon):
        r = cli(daemon, "submit", "--raw", "echo", "hi", stdin="{}")
        assert r.returncode == 1
        assert "cannot be combined" in r.stderr


class TestPiping:
    def test_jobs_one_per_line_pipes_into_show_and_kill(self, daemon):
        user = "piper"
        subs = [cli(daemon, "submit", "--cpus", "1", "--mem", "64",
                    "--env", "COOK_FAKE_DURATION_MS=999999",
                    "sleep", "999", user=user) for _ in range(2)]
        uuids = {r.stdout.strip() for r in subs}
        assert all(r.returncode == 0 for r in subs)
        r = cli(daemon, "jobs", "-1", "--state", "waiting+running",
                user=user)
        assert r.returncode == 0, r.stderr
        listed = set(r.stdout.split())
        assert uuids <= listed
        # pipe the uuid list into show (no positional args -> stdin)
        r = cli(daemon, "show", stdin=r.stdout, user=user)
        assert r.returncode == 0, r.stderr
        shown = {j["uuid"] for j in json.loads(r.stdout)}
        assert uuids <= shown
        # and into kill
        r = cli(daemon, "kill", stdin="\n".join(uuids), user=user)
        assert r.returncode == 0, r.stderr

    def test_show_empty_stdin_errors(self, daemon):
        r = cli(daemon, "show", stdin="")
        assert r.returncode == 1
        assert "at least one uuid" in r.stderr


class TestEntityRefs:
    def _submit(self, daemon):
        r = cli(daemon, "submit", "--cpus", "1", "--mem", "64", "true")
        assert r.returncode == 0, r.stderr
        return r.stdout.strip()

    def test_jobs_path_ref(self, daemon):
        url, _ = daemon
        u = self._submit(daemon)
        r = cli(daemon, "show", f"{url}/jobs/{u}")
        assert r.returncode == 0, r.stderr
        assert json.loads(r.stdout)[0]["uuid"] == u

    def test_query_string_ref_and_case(self, daemon):
        url, _ = daemon
        u = self._submit(daemon)
        ref = f"{url}/rawscheduler?job={u}".replace("http://", "HTTP://")
        r = cli(daemon, "show", ref)
        assert r.returncode == 0, r.stderr
        assert json.loads(r.stdout)[0]["uuid"] == u

    def test_ref_cluster_is_queried_without_cook_url(self, daemon):
        url, home = daemon
        u = self._submit(daemon)
        # COOK_URL deliberately points at a dead port; the ref's own
        # cluster URL must carry the query
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
                   COOK_URL="http://127.0.0.1:1", COOK_USER="alice",
                   HOME=home)
        r = subprocess.run(
            [sys.executable, "-m", "cook_tpu.cli.main", "show",
             f"{url}/jobs/{u}"],
            capture_output=True, text=True, cwd=REPO, env=env, timeout=60)
        assert r.returncode == 0, r.stderr
        assert json.loads(r.stdout)[0]["uuid"] == u

    def test_duplicate_uuids_refused(self, daemon):
        u = self._submit(daemon)
        for cmd in ("show", "wait", "kill"):
            r = cli(daemon, cmd, u, u)
            assert r.returncode == 1, (cmd, r.stdout)
            assert "duplicate" in r.stderr.lower()

    def test_malformed_ref_refused(self, daemon):
        r = cli(daemon, "show", "http://")
        assert r.returncode == 1
        assert "malformed" in r.stderr or "error" in r.stderr


class TestKillErrors:
    def test_kill_bogus_uuid(self, daemon):
        r = cli(daemon, "kill", "00000000-0000-0000-0000-00000000dead")
        assert r.returncode == 1
        assert "error" in r.stderr


class TestDoubleDash:
    def test_double_dash_ends_options(self, daemon):
        r = cli(daemon, "submit", "--cpus", "1", "--mem", "64", "--",
                "echo", "--not-a-flag")
        assert r.returncode == 0, r.stderr
        uuid = r.stdout.strip()
        r = cli(daemon, "show", uuid)
        assert json.loads(r.stdout)[0]["command"] == "echo --not-a-flag"


class TestFederatedFanout:
    """kill/wait route each uuid to the cluster that OWNS it (reference:
    querying.py per-cluster routing; distinct from the dedupe-only show
    path)."""

    def test_kill_and_wait_across_two_clusters(self, daemon,
                                               tmp_path_factory):
        url_a, _home = daemon
        tmp = tmp_path_factory.mktemp("cli-b")
        conf = {
            "host": "127.0.0.1", "port": 0,
            "data_dir": str(tmp / "data"),
            "election_dir": str(tmp),
            "admins": ["admin"],
            "clusters": [{"factory": "cook_tpu.cluster.fake.factory",
                          "kwargs": {"name": "beta", "n_hosts": 2,
                                     "cpus": 4.0, "mem": 4096.0,
                                     "default_task_duration_ms": 300,
                                     "auto_advance": True}}],
            "scheduler": {"rank_backend": "cpu", "cycle_mode": "split",
                          "match_interval_seconds": 0.1,
                          "rank_interval_seconds": 0.1},
        }
        pb = spawn(conf, tmp, "b")
        try:
            url_b = wait_serving(pb)
            assert wait_leader(url_b)

            def fed(*args, stdin=None):
                env = dict(os.environ, JAX_PLATFORMS="cpu",
                           PYTHONPATH=REPO, COOK_URL=f"{url_a},{url_b}",
                           COOK_USER="alice", HOME=str(tmp))
                return subprocess.run(
                    [sys.executable, "-m", "cook_tpu.cli.main", *args],
                    input=stdin, capture_output=True, text=True, cwd=REPO,
                    env=env, timeout=60)

            # one job on each cluster (submit goes to the FIRST url, so
            # target B explicitly for the second)
            ua = fed("--url", url_a, "submit", "--cpus", "1", "--mem",
                     "64", "--env", "COOK_FAKE_DURATION_MS=999999",
                     "sleep", "999").stdout.strip()
            ub = fed("--url", url_b, "submit", "--cpus", "1", "--mem",
                     "64", "--env", "COOK_FAKE_DURATION_MS=999999",
                     "sleep", "999").stdout.strip()
            assert ua and ub and ua != ub
            # federated kill must reach BOTH owners
            r = fed("kill", ua, ub)
            assert r.returncode == 0, r.stderr
            # wait resolves each from its own cluster (kill -> completed)
            r = fed("wait", ua, ub, "--timeout", "30")
            assert r.returncode in (0, 1), r.stderr  # killed != success
            shown = {j["uuid"] for j in json.loads(r.stdout)}
            assert shown == {ua, ub}
            # a uuid no cluster knows is an error
            r = fed("kill", "00000000-0000-0000-0000-0000000000ff")
            assert r.returncode == 1
            assert "no cluster knows" in r.stderr
        finally:
            if pb.poll() is None:
                pb.kill()
            pb.wait(timeout=10)


class TestConfigCommand:
    """cs config dotted-key get/set + submit command-prefix (reference:
    test_config_command_basics/advanced, test_base_config_file,
    test_submit_with_command_prefix)."""

    def test_set_get_roundtrip_and_types(self, daemon):
        r = cli(daemon, "config", "defaults.submit.command-prefix",
                "echo pre; ")
        assert r.returncode == 0, r.stderr
        r = cli(daemon, "config", "defaults.submit.command-prefix")
        assert r.returncode == 0
        assert json.loads(r.stdout) == "echo pre; "
        # JSON typing: numbers and booleans parse
        cli(daemon, "config", "defaults.submit.mem", "256")
        r = cli(daemon, "config", "defaults.submit.mem")
        assert json.loads(r.stdout) == 256
        # unknown key read errors
        r = cli(daemon, "config", "no.such.key")
        assert r.returncode == 1
        assert "not found" in r.stderr
        # unrelated keys survive merging
        r = cli(daemon, "config")
        cfg = json.loads(r.stdout)
        assert cfg["defaults"]["submit"]["mem"] == 256

    def test_command_prefix_applies_to_submissions(self, daemon):
        url, home = daemon
        cli(daemon, "config", "defaults.submit.command-prefix", "true && ")
        try:
            r = cli(daemon, "submit", "--cpus", "1", "--mem", "64",
                    "echo", "hi")
            assert r.returncode == 0, r.stderr
            uuid = r.stdout.strip()
            r = cli(daemon, "show", uuid)
            assert json.loads(r.stdout)[0]["command"] == "true && echo hi"
            # the flag overrides the config value
            r = cli(daemon, "submit", "--command-prefix", "", "--cpus",
                    "1", "--mem", "64", "echo", "bare")
            uuid2 = r.stdout.strip()
            r = cli(daemon, "show", uuid2)
            assert json.loads(r.stdout)[0]["command"] == "echo bare"
        finally:
            cli(daemon, "config", "defaults.submit.command-prefix", '""')

    def test_corrupt_config_refused_not_clobbered(self, daemon):
        _url, home = daemon
        cs_path = os.path.join(home, ".cs.json")
        original = None
        if os.path.exists(cs_path):
            original = open(cs_path).read()
        try:
            with open(cs_path, "w") as f:
                f.write('{"clusters": [,]}')  # corrupt
            r = cli(daemon, "config", "defaults.submit.mem", "64")
            assert r.returncode == 1
            assert "not valid JSON" in r.stderr
            assert open(cs_path).read() == '{"clusters": [,]}'  # untouched
        finally:
            if original is None:
                os.remove(cs_path)
            else:
                with open(cs_path, "w") as f:
                    f.write(original)

    def test_non_dict_intermediate_refused(self, daemon):
        cli(daemon, "config", "--set-url", "http://example:1")
        r = cli(daemon, "config", "clusters.default", "oops")
        assert r.returncode == 1
        assert "not a table" in r.stderr
        # the clusters list survived
        r = cli(daemon, "config", "clusters")
        assert json.loads(r.stdout)[0]["url"] == "http://example:1"

    def test_raw_refuses_command_prefix(self, daemon):
        r = cli(daemon, "submit", "--raw", "--command-prefix", "t ",
                stdin="{}")
        assert r.returncode == 1
        assert "does not apply" in r.stderr


class TestUsageCommand:
    def test_usage_pool_filter_and_breakdown(self, daemon):
        r = cli(daemon, "submit", "--cpus", "1", "--mem", "64",
                "--env", "COOK_FAKE_DURATION_MS=999999",
                "sleep", "999", user="usg")
        uuid = r.stdout.strip()
        assert r.returncode == 0, r.stderr
        # wait for it to run so usage is non-zero
        deadline = time.time() + 20
        running = False
        while time.time() < deadline:
            r = cli(daemon, "show", uuid, user="usg")
            if '"state": "running"' in r.stdout:
                running = True
                break
            time.sleep(0.3)
        try:
            assert running, "job never reached running"
            r = cli(daemon, "usage", "--pool", "default",
                    "--group-breakdown", user="usg")
            assert r.returncode == 0, r.stderr
            rep = json.loads(r.stdout)
            assert rep["total_usage"]["jobs"] == 1
            assert "ungrouped" in rep
            r = cli(daemon, "usage", "--pool", "ghost", user="usg")
            assert r.returncode == 0, r.stderr
            assert json.loads(r.stdout)["total_usage"]["jobs"] == 0
        finally:
            cli(daemon, "kill", uuid, user="usg")


class TestRetryCommand:
    """cs retry over PUT /retry: multiple jobs, groups, increment,
    failed-only (reference: subcommands/retry.py + UpdateRetriesRequest)."""

    def test_retry_multiple_and_flags(self, daemon):
        subs = [cli(daemon, "submit", "--cpus", "1", "--mem", "64",
                    "--max-retries", "1",
                    "--env", "COOK_FAKE_EXIT_CODE=1", "exit", "1")
                for _ in range(2)]
        uuids = [r.stdout.strip() for r in subs]
        assert all(r.returncode == 0 for r in subs)
        for u in uuids:
            deadline = time.time() + 20
            reached = False
            while time.time() < deadline:
                if '"state": "failed"' in cli(daemon, "show", u).stdout:
                    reached = True
                    break
                time.sleep(0.3)
            assert reached, f"{u} never failed"
        r = cli(daemon, "retry", *uuids, "--retries", "3")
        assert r.returncode == 0, r.stderr
        for u in uuids:
            shown = json.loads(cli(daemon, "show", u).stdout)[0]
            assert shown["max_retries"] == 3
            # resurrection: the job leaves the failed state.  With a
            # 0.1s match interval and 300ms fake tasks it may have
            # already burned the fresh budget and re-failed before this
            # subprocess observes it — extra instances prove the
            # resurrection happened either way.
            assert shown["state"] != "failed" \
                or len(shown.get("instances", [])) > 1, shown
        # increment raises BY n
        r = cli(daemon, "retry", uuids[0], "--increment", "2")
        assert r.returncode == 0, r.stderr
        shown = json.loads(cli(daemon, "show", uuids[0]).stdout)[0]
        assert shown["max_retries"] == 5
        # validation: both/neither of retries/increment refused
        assert cli(daemon, "retry", uuids[0]).returncode == 1
        assert cli(daemon, "retry", uuids[0], "--retries", "4",
                   "--increment", "1").returncode == 1
        assert cli(daemon, "retry", "--retries", "4",
                   stdin="").returncode == 1  # no uuids and no groups


class TestAdminUsage:
    def test_all_users_report_via_cli(self, daemon):
        r = cli(daemon, "submit", "--cpus", "1", "--mem", "64",
                "--env", "COOK_FAKE_DURATION_MS=999999",
                "sleep", "999", user="au1")
        uuid = r.stdout.strip()
        deadline = time.time() + 20
        while time.time() < deadline:
            if '"state": "running"' in cli(daemon, "show", uuid,
                                           user="au1").stdout:
                break
            time.sleep(0.3)
        try:
            r = cli(daemon, "admin", "usage", user="admin")
            assert r.returncode == 0, r.stderr
            rep = json.loads(r.stdout)
            assert "au1" in rep["users"]
            # non-admin refused
            r = cli(daemon, "admin", "usage", user="au1")
            assert r.returncode == 1
        finally:
            cli(daemon, "kill", uuid, user="au1")


class TestGangSubmit:
    def test_gang_size_fans_out_one_group(self, daemon):
        r = cli(daemon, "submit", "--gang-size", "2", "--cpus", "1",
                "--mem", "64", "true")
        assert r.returncode == 0, r.stderr
        uuids = r.stdout.split()
        assert len(uuids) == 2
        # 60s: the whole gang must clear the barrier, and a loaded CI
        # box has pushed the 30s budget over the line before
        r = cli(daemon, "wait", *uuids, "--timeout", "60", timeout=90)
        assert r.returncode == 0, r.stdout + r.stderr
        # cs show surfaces the gang block (members, barrier state)
        r = cli(daemon, "show", uuids[0])
        assert r.returncode == 0, r.stderr
        shown = json.loads(r.stdout)[0]
        assert shown["gang"]["size"] == 2
        assert shown["groups"] == [shown["gang"]["group"]]

    def test_gang_flags_require_size(self, daemon):
        r = cli(daemon, "submit", "--gang-topology", "slice-id", "true")
        assert r.returncode == 1
        assert "--gang-size" in r.stderr

    def test_gang_flags_refused_with_raw(self, daemon):
        r = cli(daemon, "submit", "--raw", "--gang-size", "2",
                stdin=json.dumps({"command": "true"}))
        assert r.returncode == 1
        assert "gang" in r.stderr

    def test_raw_full_body_submits_a_gang(self, daemon):
        # --raw accepts a full {"jobs", "groups"} body — the raw-mode
        # route to gang submission the gang-flags error points at
        g = "33333333-0000-0000-0000-000000000002"
        body = {"jobs": [{"command": "true", "group": g,
                          "cpus": 1, "mem": 64} for _ in range(2)],
                "groups": [{"uuid": g, "gang": {"size": 2}}]}
        r = cli(daemon, "submit", "--raw", stdin=json.dumps(body))
        assert r.returncode == 0, r.stderr
        uuids = r.stdout.split()
        assert len(uuids) == 2
        r = cli(daemon, "show", uuids[0])
        assert r.returncode == 0, r.stderr
        shown = json.loads(r.stdout)[0]
        assert shown["gang"]["size"] == 2
        assert shown["gang"]["group"] == g

    def test_malformed_gang_spec_is_a_clear_400(self, daemon):
        # the API rejects a bad gang spec; the CLI surfaces the message
        spec = {"jobs": [{"command": "true", "group":
                          "33333333-0000-0000-0000-000000000001"}],
                "groups": [{"uuid":
                            "33333333-0000-0000-0000-000000000001",
                            "gang": {"size": 0}}]}
        url, home = daemon
        import urllib.request, urllib.error
        req = urllib.request.Request(
            url + "/jobs", method="POST",
            data=json.dumps(spec).encode(),
            headers={"Content-Type": "application/json",
                     "X-Cook-User": "alice"})
        try:
            urllib.request.urlopen(req, timeout=10)
            assert False, "bad gang spec accepted"
        except urllib.error.HTTPError as e:
            assert e.code == 400
            assert b"gang.size" in e.read()
