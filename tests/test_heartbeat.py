"""Heartbeat timeout killer (reference: mesos/heartbeat.clj:66-147)."""

from cook_tpu.cluster import FakeCluster, FakeHost
from cook_tpu.config import Config
from cook_tpu.sched import Scheduler
from cook_tpu.sched.heartbeat import HeartbeatTracker
from cook_tpu.state import (
    InstanceStatus,
    Job,
    JobState,
    Reasons,
    Resources,
    Store,
    new_uuid,
)


class TestTracker:
    def test_watch_beat_expire(self):
        hb = HeartbeatTracker(timeout_ms=1000)
        hb.watch("t1", now=0)
        hb.watch("t2", now=0)
        assert hb.expired(now=500) == []
        hb.beat("t1", now=900)
        assert hb.expired(now=1500) == ["t2"]
        hb.forget("t2")
        assert hb.expired(now=1500) == []
        assert hb.last_beat("t1") == 900

    def test_beat_before_watch_is_ignored(self):
        # stale liveness after forget() must not re-track (leak + spurious
        # kill); watch() is the sole insert point
        hb = HeartbeatTracker(timeout_ms=1000)
        hb.beat("t1", now=500)
        assert hb.tracked_count() == 0
        hb.watch("t1", now=0)
        hb.forget("t1")
        hb.beat("t1", now=600)
        assert hb.tracked_count() == 0


def mk_env(heartbeat_enabled=True, timeout_ms=1000):
    store = Store()
    cluster = FakeCluster("fake-1", [FakeHost(
        hostname="h0", capacity=Resources(cpus=8.0, mem=8192.0))])
    config = Config()
    config.default_matcher.backend = "cpu"
    config.heartbeat_enabled = heartbeat_enabled
    config.heartbeat_timeout_ms = timeout_ms
    sched = Scheduler(store, config, [cluster], rank_backend="cpu")
    return store, cluster, sched


class TestSchedulerIntegration:
    def test_silent_task_killed_mea_culpa(self):
        store, cluster, sched = mk_env()
        job = Job(uuid=new_uuid(), user="a", command="x", pool="default",
                  resources=Resources(cpus=1.0, mem=64.0), max_retries=5)
        store.create_jobs([job])
        sched.step_rank()
        [tid] = sched.step_match()["default"].launched_task_ids
        assert sched.heartbeats.tracked_count() == 1
        base = sched.heartbeats.last_beat(tid)
        # silent past the timeout -> killed as HEARTBEAT_LOST
        killed = sched.step_reapers(current_ms=base + 5000)
        assert killed == [tid]
        inst = store.instance(tid)
        assert inst.status is InstanceStatus.FAILED
        assert inst.reason_code == Reasons.HEARTBEAT_LOST.code
        # mea-culpa: retry budget untouched, job back to waiting
        assert store.job(job.uuid).state is JobState.WAITING
        assert sched.heartbeats.tracked_count() == 0

    def test_beating_task_survives(self):
        store, cluster, sched = mk_env()
        job = Job(uuid=new_uuid(), user="a", command="x", pool="default",
                  resources=Resources(cpus=1.0, mem=64.0))
        store.create_jobs([job])
        sched.step_rank()
        [tid] = sched.step_match()["default"].launched_task_ids
        base = sched.heartbeats.last_beat(tid)
        sched.heartbeats.beat(tid, base + 4500)
        assert sched.step_reapers(current_ms=base + 5000) == []
        assert store.instance(tid).status is not InstanceStatus.FAILED

    def test_disabled_by_default(self):
        store, cluster, sched = mk_env(heartbeat_enabled=False)
        job = Job(uuid=new_uuid(), user="a", command="x", pool="default",
                  resources=Resources(cpus=1.0, mem=64.0))
        store.create_jobs([job])
        sched.step_rank()
        [tid] = sched.step_match()["default"].launched_task_ids
        base = sched.heartbeats.last_beat(tid)
        assert sched.step_reapers(current_ms=base + 10 ** 9) == []

    def test_restart_watches_preexisting_running_instances(self):
        store = Store()
        job = Job(uuid=new_uuid(), user="a", command="x", pool="default",
                  resources=Resources(cpus=1.0, mem=64.0))
        store.create_jobs([job])
        store.launch_instance(job.uuid, "t-pre", hostname="h0",
                              compute_cluster="fake-1")
        store.update_instance_status("t-pre", InstanceStatus.RUNNING)
        # a fresh scheduler on a reopened store adopts the live instance
        config = Config()
        config.default_matcher.backend = "cpu"
        config.heartbeat_enabled = True
        sched = Scheduler(store, config, [], rank_backend="cpu")
        assert sched.heartbeats.tracked_count() == 1
        assert sched.heartbeats.last_beat("t-pre") is not None

    def test_terminal_status_forgets(self):
        store, cluster, sched = mk_env()
        job = Job(uuid=new_uuid(), user="a", command="x", pool="default",
                  resources=Resources(cpus=1.0, mem=64.0))
        store.create_jobs([job])
        sched.step_rank()
        [tid] = sched.step_match()["default"].launched_task_ids
        cluster.complete_task(tid)
        assert sched.heartbeats.tracked_count() == 0
