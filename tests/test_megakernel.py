"""Pallas fused-cycle megakernel (ISSUE 14; ops/pallas_cycle.py,
ops/quant.py, sched/fused.py megakernel dispatch path).

The contract under test:

* KERNEL PARITY: the single-launch megakernel's outputs are bit-identical
  to the fused XLA driver (parallel/sharded.make_pool_cycle compact) on
  random compact inputs — same module functions, one launch;
* DRIVER PARITY MATRIX: launch decisions byte-identical across
  megakernel / fused XLA / split drivers, sync and depth-2 pipelined,
  over rigid AND elastic (gang_min < gang_max) gangs, compact and
  quantized wire, resident and rebuild modes;
* QUANTIZED WIRE: expand(quantize(x)) == x wherever a narrow form was
  negotiated; non-representable domains fall back WIDE explicitly
  (cook_quant_wide_fallback_total) — quantization is lossless-or-wide,
  never approximate;
* FUSED GANG STAGE: the in-kernel gang_min-gated segment reduction
  matches reference_impl.gang_reduce, and the driver consumes the fused
  verdicts only while the candidate view is intact;
* ROBUSTNESS: a megakernel dispatch failure degrades to the fused XLA
  cycle (cook_kernel_fallback_total{kernel=pallas.megacycle}) with
  decisions unchanged — the cycle never dies;
* TELEMETRY: CycleRecord.kernel_launches / .path land on /debug/cycles
  (megakernel cycles read path="megakernel", 1 launch).
"""

import numpy as np
import pytest

from cook_tpu.cluster import FakeCluster, FakeHost
from cook_tpu.config import Config, MatcherConfig
from cook_tpu.ops import pallas_cycle, quant
from cook_tpu.sched import Scheduler
from cook_tpu.state import Group, Job, Pool, Resources, Store
from cook_tpu.utils.flight import recorder as flight_recorder
from cook_tpu.utils.metrics import registry


def counter_value(name, labels):
    """Current value of one labeled counter series (0.0 when absent)."""
    for lbl, v in registry.series(name):
        if all(lbl.get(k) == want for k, want in labels.items()):
            return v
    return 0.0


# ---------------------------------------------------------------------------
# world builders (fixed uuids: two builds produce identical worlds)
# ---------------------------------------------------------------------------

def make_cfg(backend="tpu-megakernel", depth=0, resident=True,
             quantized=True, cycle_mode="fused"):
    cfg = Config()
    cfg.cycle_mode = cycle_mode
    cfg.default_matcher.backend = backend
    cfg.pipeline.depth = depth
    cfg.resident_pack = resident
    cfg.quantized_wire = quantized
    return cfg


def build_world(cfg, n_jobs=16, n_hosts=5, seed=3, cpus=16.0,
                gang_size=0, gang_min=0, gang_max=0):
    rng = np.random.default_rng(seed)
    store = Store()
    store.put_pool(Pool(name="default"))
    hosts = [FakeHost(hostname=f"h{i}",
                      capacity=Resources(cpus=cpus, mem=16384.0))
             for i in range(n_hosts)]
    sched = Scheduler(store, cfg, [FakeCluster("fake-1", hosts)],
                      rank_backend="tpu")
    jobs = []
    for i in range(n_jobs):
        j = Job(uuid=f"00000000-0000-0000-0000-{i:012d}",
                user=f"user{i % 3}", command="true", pool="default",
                priority=int(rng.integers(0, 100)),
                resources=Resources(cpus=float(rng.integers(1, 4)),
                                    mem=float(rng.integers(128, 1024))),
                submit_time_ms=1000 + i)
        jobs.append(j)
        store.create_jobs([j])
    if gang_size:
        members = [Job(uuid=f"00000000-0000-0000-0001-{i:012d}",
                       user="ganguser", command="true", group="g1",
                       resources=Resources(cpus=2.0, mem=256.0),
                       submit_time_ms=900)
                   for i in range(gang_size)]
        store.create_jobs(members, groups=[Group(
            uuid="g1", gang=True, gang_size=gang_size,
            gang_min=gang_min, gang_max=gang_max,
            jobs=[m.uuid for m in members])])
        jobs.extend(members)
    return store, sched, jobs


def decisions(store, jobs):
    out = {}
    for j in jobs:
        job = store.job(j.uuid)
        hosts = [store.instance(t).hostname for t in job.instances
                 if store.instance(t) is not None]
        out[j.uuid] = (job.state.value, tuple(sorted(hosts)))
    return out


def churn(store, wave, n=4, seed=11):
    rng = np.random.default_rng(seed + wave)
    fresh = [Job(uuid=f"00000000-0000-0000-{wave + 2:04d}-{i:012d}",
                 user=f"user{i % 3}", command="true", pool="default",
                 resources=Resources(cpus=float(rng.integers(1, 4)),
                                     mem=float(rng.integers(128, 512))),
                 submit_time_ms=5000 + wave * 100 + i)
             for i in range(n)]
    store.create_jobs(fresh)
    return fresh


def drive(cfg, cycles=4, split=False, **kw):
    store, sched, jobs = build_world(cfg, **kw)
    for w in range(cycles):
        if split:
            sched.step_rank()
            sched.step_match()
        else:
            sched.step_cycle()
        jobs.extend(churn(store, w))
    if split:
        sched.step_rank()
        sched.step_match()
    else:
        sched.step_cycle()
    return decisions(store, jobs)


# ---------------------------------------------------------------------------
# kernel-level parity
# ---------------------------------------------------------------------------

def _random_compact_inputs(seed=0, P=2, T=64, H=16, U=8, E=8, N=128):
    import jax.numpy as jnp
    from cook_tpu.ops.delta import (FLAG_ENQUEUE_OK, FLAG_LAUNCH_OK,
                                    FLAG_PENDING, FLAG_USER_FIRST,
                                    FLAG_VALID)
    from cook_tpu.parallel.sharded import CompactPoolCycleInputs
    rng = np.random.default_rng(seed)
    rows = np.stack([rng.permutation(np.arange(T))
                     for _ in range(P)]).astype(np.int32)
    pend = rng.random((P, T)) < 0.7
    uid = np.sort(rng.integers(0, U, (P, T)), axis=1)
    is_first = np.zeros((P, T), dtype=bool)
    is_first[:, 0] = True
    is_first[:, 1:] = uid[:, 1:] != uid[:, :-1]
    flags = (pend.astype(np.uint8) * FLAG_PENDING + FLAG_VALID
             + is_first.astype(np.uint8) * FLAG_USER_FIRST
             + (rng.random((P, T)) < 0.95).astype(np.uint8)
             * FLAG_ENQUEUE_OK
             + (rng.random((P, T)) < 0.9).astype(np.uint8)
             * FLAG_LAUNCH_OK)
    res_base = np.zeros((N, 4), dtype=np.float32)
    res_base[:, 0] = rng.integers(1, 4, N)
    res_base[:, 1] = rng.integers(1, 16, N) * 128.0
    res_base[:, 2] = (rng.random(N) < 0.1) * 1.0
    res_base[:, 3] = 1.0
    host_gpu = rng.random((P, H)) < 0.1
    host_blocked = rng.random((P, H)) < 0.1
    exc_rows = np.full((P, E), -1, dtype=np.int32)
    exc_rows[0, 0] = 3
    avail = rng.integers(0, 64, (P, H, 4)).astype(np.float32)
    inp = CompactPoolCycleInputs(
        rows=jnp.asarray(rows), flags=jnp.asarray(flags),
        res_base=jnp.asarray(res_base),
        disk_base=jnp.asarray(
            rng.integers(0, 4, N).astype(np.float32) * 10.0),
        tokens_u=jnp.full((P, U), np.inf, dtype=jnp.float32),
        shares_u=jnp.full((P, U, 3), 100.0, dtype=jnp.float32),
        quota_u=jnp.full((P, U, 4), np.inf, dtype=jnp.float32),
        num_considerable=jnp.full((P,), 32, dtype=jnp.int32),
        pool_quota=jnp.full((P, 4), np.inf, dtype=jnp.float32),
        group_quota=jnp.full((P, 4), np.inf, dtype=jnp.float32),
        group_id=jnp.zeros((P,), dtype=jnp.int32),
        host_gpu=jnp.asarray(host_gpu),
        host_blocked=jnp.asarray(host_blocked),
        exc_rows=jnp.asarray(exc_rows),
        exc_mask=jnp.asarray(rng.random((P, E, H)) < 0.5),
        avail=jnp.asarray(avail),
        capacity=jnp.asarray(
            avail + rng.integers(0, 8, (P, H, 4)).astype(np.float32)))
    return inp


def _wire_from(inp, gang=None, quantized=False):
    import jax.numpy as jnp
    P, T = inp.rows.shape
    H = inp.avail.shape[1]
    host_bits = np.stack(
        [quant.pack_bits(np.asarray(inp.host_gpu)),
         quant.pack_bits(np.asarray(inp.host_blocked))], axis=1)
    if gang is None:
        gang = pallas_cycle.empty_gang_wire(P, T, H)
    codecs = (quant.ROWS_WIDE, 0.0, 0.0)
    rows, avail, cap = inp.rows, inp.avail, inp.capacity
    if quantized:
        qr = quant.quantize_rows(np.asarray(inp.rows))
        qa = quant.quantize_fixed(np.asarray(inp.avail), "avail")
        qc = quant.quantize_fixed(np.asarray(inp.capacity), "capacity")
        codecs = (qr.codec, qa.scale, qc.scale)
        rows, avail, cap = (jnp.asarray(qr.data), jnp.asarray(qa.data),
                            jnp.asarray(qc.data))
    wire = pallas_cycle.MegaCycleWire(
        rows=rows, flags=inp.flags, res_base=inp.res_base,
        disk_base=inp.disk_base, tokens_u=inp.tokens_u,
        shares_u=inp.shares_u, quota_u=inp.quota_u,
        num_considerable=inp.num_considerable,
        pool_quota=inp.pool_quota, group_quota=inp.group_quota,
        group_id=inp.group_id, host_bits=jnp.asarray(host_bits),
        exc_rows=inp.exc_rows, exc_mask=inp.exc_mask,
        avail=avail, capacity=cap,
        gang_id=jnp.asarray(gang[0]), gang_size=jnp.asarray(gang[1]),
        gang_attr=jnp.asarray(gang[2]), host_topo=jnp.asarray(gang[3]))
    return wire, codecs


class TestKernelParity:
    def _fused(self, inp, cap=32):
        import jax
        from jax.sharding import Mesh
        from cook_tpu.parallel.mesh import POOL_AXIS
        from cook_tpu.parallel.sharded import make_pool_cycle
        mesh = Mesh(np.array(jax.devices()[:1]), (POOL_AXIS,))
        return make_pool_cycle(mesh, considerable_cap=cap,
                               structured=True, compact=True)(inp)

    @pytest.mark.parametrize("seed", [0, 7])
    def test_megakernel_bit_identical_to_fused_xla(self, seed):
        inp = _random_compact_inputs(seed=seed)
        res = self._fused(inp)
        wire, codecs = _wire_from(inp)
        mega = pallas_cycle.megacycle(wire, considerable_cap=32,
                                      interpret=True)
        for name in ("queue_rows", "n_queue", "cand_row", "cand_assign",
                     "cand_qpos"):
            a, b = np.asarray(getattr(res, name)), \
                np.asarray(getattr(mega, name))
            assert (a == b).all(), name

    def test_quantized_wire_decision_identical(self):
        inp = _random_compact_inputs(seed=1)
        wire, _ = _wire_from(inp)
        wire_q, codecs = _wire_from(inp, quantized=True)
        # the negotiation actually picked narrow forms on this workload
        assert codecs[0] != quant.ROWS_WIDE
        assert codecs[1] != 0.0 and codecs[2] != 0.0
        a = pallas_cycle.megacycle(wire, considerable_cap=32,
                                   interpret=True)
        b = pallas_cycle.megacycle(wire_q, considerable_cap=32,
                                   rows_codec=codecs[0],
                                   avail_scale=codecs[1],
                                   cap_scale=codecs[2], interpret=True)
        for name in a._fields:
            assert (np.asarray(getattr(a, name))
                    == np.asarray(getattr(b, name))).all(), name

    def test_fused_gang_stage_matches_reference(self):
        from cook_tpu.ops import reference_impl
        inp = _random_compact_inputs(seed=2)
        P, T = inp.rows.shape
        H = inp.avail.shape[1]
        gang_id = np.full((P, T), -1, dtype=np.int32)
        gang_id[0, 5:9] = 0          # gang of 4 (sorted positions 5..8)
        gang_id[1, 2:4] = 1          # second pool, gang segment 1
        G = 4
        gang_size = np.full((P, G), 2 ** 30, dtype=np.int32)
        gang_size[0, 0] = 4
        gang_size[1, 1] = 2
        gang_attr = np.zeros((P, G), dtype=np.int32)
        host_topo = np.full((P, 1, H), -1, dtype=np.int32)
        host_topo[:, 0] = 0
        wire, _ = _wire_from(inp, gang=(gang_id, gang_size, gang_attr,
                                        host_topo))
        mega = pallas_cycle.megacycle(wire, considerable_cap=32,
                                      interpret=True)
        cr = np.asarray(mega.cand_row)
        ca = np.asarray(mega.cand_assign)
        for p in range(P):
            gid_c = np.where(cr[p] >= 0,
                             gang_id[p][np.maximum(cr[p], 0)], -1)
            out, dropped = reference_impl.gang_reduce(
                ca[p], gid_c.astype(np.int32), gang_size[p],
                gang_attr[p], host_topo[p])
            assert (np.asarray(mega.cand_gang)[p] == out).all()
            assert (np.asarray(mega.cand_dropped)[p]
                    == dropped.astype(np.int32)).all()


# ---------------------------------------------------------------------------
# driver parity matrix
# ---------------------------------------------------------------------------

@pytest.mark.gang
class TestDriverParityMatrix:
    """Megakernel vs fused XLA vs split drivers, sync + depth-2
    pipelined, rigid + elastic gangs: launch decisions byte-identical."""

    @pytest.mark.parametrize("depth", [0, 2])
    @pytest.mark.parametrize("gang", ["none", "rigid", "elastic"])
    def test_megakernel_vs_fused(self, depth, gang):
        kw = {}
        if gang == "rigid":
            kw = dict(gang_size=3)
        elif gang == "elastic":
            # min 2 of 4 on 5 hosts: places at >= min, grows later
            kw = dict(gang_size=4, gang_min=2, gang_max=4, cpus=8.0)
        base = drive(make_cfg(backend="auto", depth=depth), **kw)
        mega = drive(make_cfg(depth=depth), **kw)
        assert base == mega, {k: (base[k], mega[k])
                              for k in base if base[k] != mega[k]}

    def test_megakernel_vs_split(self):
        base = drive(make_cfg(backend="cpu", cycle_mode="split"),
                     split=True, gang_size=3)
        mega = drive(make_cfg(), gang_size=3)
        assert base == mega

    @pytest.mark.parametrize("resident", [True, False])
    @pytest.mark.parametrize("quantized", [True, False])
    def test_wire_modes_decision_identical(self, resident, quantized):
        base = drive(make_cfg(backend="auto"), gang_size=3)
        mega = drive(make_cfg(resident=resident, quantized=quantized),
                     gang_size=3)
        assert base == mega

    def test_elastic_gang_places_at_min_under_megakernel(self):
        # capacity for only 2 members at once: a rigid 4-gang would wait
        # whole; the elastic min-2 gang must come up partial
        cfg = make_cfg()
        store, sched, jobs = build_world(
            cfg, n_jobs=0, n_hosts=2, cpus=4.0,
            gang_size=4, gang_min=2, gang_max=4)
        for _ in range(3):
            sched.step_cycle()
        live = [j for j in jobs
                if store.job(j.uuid).state.value == "running"]
        assert 2 <= len(live) <= 4, [store.job(j.uuid).state
                                     for j in jobs]


# ---------------------------------------------------------------------------
# quantized-wire round-trip properties
# ---------------------------------------------------------------------------

class TestQuantCodecs:
    def test_rows_roundtrip_near_identity(self):
        rng = np.random.default_rng(0)
        rows = np.arange(4096, dtype=np.int64)
        swaps = rng.integers(0, 4095, 64)
        rows[swaps], rows[swaps + 1] = rows[swaps + 1], rows[swaps].copy()
        q = quant.quantize_rows(rows)
        assert q.codec == quant.ROWS_I8
        assert (quant.expand_rows(q) == rows).all()

    def test_rows_widths_and_overflow_fallback(self):
        n0 = counter_value("cook_quant_wide_fallback",
                                    {"field": "rows"})
        rows = np.arange(4096) + 1000          # delta 1000: i16
        q = quant.quantize_rows(rows)
        assert q.codec == quant.ROWS_I16
        assert (quant.expand_rows(q) == rows).all()
        rows = np.arange(4096) + 100_000       # out of i16: wide
        q = quant.quantize_rows(rows)
        assert q.codec == quant.ROWS_WIDE
        assert (quant.expand_rows(q) == rows).all()
        assert counter_value("cook_quant_wide_fallback",
                                      {"field": "rows"}) == n0 + 1

    def test_rows_device_decode_matches_host(self):
        rows = np.arange(512) + 17
        q = quant.quantize_rows(rows)
        dev = np.asarray(quant.expand_rows_device(q.codec, q.data, 512))
        assert (dev == quant.expand_rows(q)).all()

    def test_fixed_roundtrip_per_column_scales(self):
        rng = np.random.default_rng(1)
        x = np.stack([rng.integers(0, 64, 256) * 0.5,       # halves
                      rng.integers(0, 16384, 256) * 1.0,    # ints
                      rng.integers(0, 8, 256) * 1.0,
                      rng.integers(0, 1000, 256) * 1024.0],  # big, /64
                     axis=1).astype(np.float32)
        q = quant.quantize_fixed(x, "avail")
        assert q.scale != 0.0 and q.data.dtype == np.uint16
        assert (quant.expand_fixed(q) == x).all()
        dev = np.asarray(quant.expand_fixed_device(q.scale, q.data))
        assert (dev == x).all()

    def test_fixed_nonrepresentable_falls_back_wide(self):
        x = np.full((8, 4), 0.3, dtype=np.float32)  # not dyadic
        q = quant.quantize_fixed(x, "avail")
        assert q.scale == 0.0
        assert (quant.expand_fixed(q) == x).all()

    def test_bitpack_roundtrip(self):
        rng = np.random.default_rng(2)
        for n in (1, 7, 8, 9, 100):
            x = rng.random((3, n)) < 0.5
            packed = quant.pack_bits(x)
            assert (quant.unpack_bits(packed, n) == x).all()
            dev = np.asarray(quant.unpack_bits_device(packed, n))
            assert (dev == x).all()

    def test_delta_scatter_quantized_matches_wide(self):
        import jax.numpy as jnp
        from cook_tpu.ops.delta import PackDeltaApplier
        rng = np.random.default_rng(3)
        P, T = 2, 512
        rows0 = np.zeros((P, T), dtype=np.int32)
        flags0 = np.zeros((P, T), dtype=np.uint8)
        idx = np.sort(rng.choice(P * T, 64, replace=False)).astype(
            np.int32)
        vals = ((idx % T) + rng.integers(-100, 100, 64)).astype(np.int32)
        fvals = rng.integers(0, 32, 64).astype(np.uint8)
        ap = PackDeltaApplier(donate=False)
        rw, fw = ap.apply(jnp.asarray(rows0), jnp.asarray(flags0),
                          idx, vals, fvals, quantize=False)
        rq, fq = ap.apply(jnp.asarray(rows0), jnp.asarray(flags0),
                          idx, vals, fvals, quantize=True)
        assert (np.asarray(rw) == np.asarray(rq)).all()
        assert (np.asarray(fw) == np.asarray(fq)).all()
        # and the staged narrow batch was genuinely smaller
        st_w = ap.stage((P, T), idx, vals, fvals, quantize=False)
        st_q = ap.stage((P, T), idx, vals, fvals, quantize=True)
        assert st_q.codec != quant.ROWS_WIDE
        assert st_q.nbytes < st_w.nbytes


# ---------------------------------------------------------------------------
# config / telemetry / robustness
# ---------------------------------------------------------------------------

class TestReviewRegressions:
    """Fix-pinning tests from the PR 14 review round."""

    def test_rebuild_mode_rows_actually_negotiate_narrow(self):
        """The rows codec must engage over the BUCKET-PADDED production
        wire, not just the bench's unpadded identity rows: zero padding
        used to read as delta -t and force wide on every pool not
        exactly filling its bucket (identity padding fixes it)."""
        n0 = counter_value("cook_quant_wide_fallback", {"field": "rows"})
        base = drive(make_cfg(backend="auto"), cycles=2)
        got = drive(make_cfg(resident=False, quantized=True), cycles=2)
        assert got == base
        assert counter_value("cook_quant_wide_fallback",
                             {"field": "rows"}) == n0

    def test_sticky_fixed_scales_reused(self):
        x = (np.arange(32, dtype=np.float32).reshape(8, 4)) * 0.5
        q1 = quant.quantize_fixed(x, "avail")
        # a coarser-but-still-exact preferred scale must be KEPT (the
        # scale tuple is a static jit key; flapping means retraces)
        coarse = tuple(s * 2 for s in q1.scale)
        q2 = quant.quantize_fixed(x * 2, "avail", prefer=coarse)
        assert q2.scale == coarse
        assert (quant.expand_fixed(q2) == x * 2).all()
        # a preferred scale that no longer round-trips renegotiates
        q3 = quant.quantize_fixed(np.full((2, 4), 0.125,
                                          dtype=np.float32),
                                  "avail", prefer=(1.0, 1.0, 1.0, 1.0))
        assert q3.scale != (1.0, 1.0, 1.0, 1.0)
        assert (quant.expand_fixed(q3) == 0.125).all()

    def _two_pool_world(self, cfg):
        """default pool pinned per cfg + an 'other' pool on auto, each
        with a small gang that cannot fully place (all-or-nothing must
        hold on BOTH paths of a mixed group)."""
        store = Store()
        store.put_pool(Pool(name="default"))
        store.put_pool(Pool(name="other"))
        hosts = [FakeHost(hostname=f"h{i}",
                          capacity=Resources(cpus=4.0, mem=4096.0))
                 for i in range(2)]
        hosts_o = [FakeHost(hostname=f"o{i}", pool="other",
                            capacity=Resources(cpus=4.0, mem=4096.0))
                   for i in range(2)]
        sched = Scheduler(
            store, cfg,
            [FakeCluster("fake-1", hosts),
             FakeCluster("fake-2", hosts_o)],
            rank_backend="tpu")
        # a 3-member gang of 4-cpu jobs on 2x4cpu hosts: can never
        # place whole — any member launching is a partial-gang bug
        members = [Job(uuid=f"00000000-0000-0000-0009-{i:012d}",
                       user="gang", command="true", group="gx",
                       pool="other",
                       resources=Resources(cpus=4.0, mem=512.0),
                       submit_time_ms=900)
                   for i in range(3)]
        store.create_jobs(members, groups=[Group(
            uuid="gx", gang=True, gang_size=3,
            jobs=[m.uuid for m in members])])
        singles = [Job(uuid=f"00000000-0000-0000-0008-{i:012d}",
                       user=f"u{i}", command="true", pool="default",
                       resources=Resources(cpus=1.0, mem=128.0),
                       submit_time_ms=1000 + i) for i in range(3)]
        store.create_jobs(singles)
        return store, sched, members, singles

    def test_explicit_pin_takes_mixed_group_and_gang_guard_holds(self):
        """An explicit tpu-megakernel pin routes the whole dispatch
        group through the megakernel even when a co-grouped pool is on
        'auto' (CPU); the auto pool stages NO gang wire, so its gang
        verdicts must come from the host reduction — a partial gang in
        that pool must still launch NOTHING."""
        cfg = make_cfg()  # default matcher pinned tpu-megakernel
        cfg.pool_matchers = [("other", MatcherConfig(backend="auto"))]
        store, sched, members, singles = self._two_pool_world(cfg)
        for _ in range(3):
            sched.step_cycle()
        rec = flight_recorder.recent(5)
        assert any(r["path"] == "megakernel" for r in rec), \
            [r["path"] for r in rec]
        for m in members:
            assert store.job(m.uuid).instances == [], \
                (m.uuid, store.job(m.uuid).state)
        for s in singles:
            assert store.job(s.uuid).state.value in ("running",
                                                     "completed")


class TestWarmup:
    def test_warmup_compiles_megakernel_executables(self):
        """Boot warmup must cover the megakernel when it is the live
        path: the first production cycle then reuses a compiled
        executable instead of tracing in-cycle (residual: the first
        negotiated fixed-point scale tuple, by design)."""
        cfg = make_cfg()
        cfg.pipeline.warmup_tasks = 64
        cfg.pipeline.warmup_hosts = 8
        before = set(pallas_cycle._FNS)
        store, sched, jobs = build_world(cfg)
        runs = sched.warmup_kernels()
        assert runs > 0
        warmed = set(pallas_cycle._FNS) - before
        assert warmed, "warmup built no megakernel executables"


class TestBackendConfig:
    def test_megakernel_backend_validates(self):
        assert MatcherConfig(backend="tpu-megakernel").backend == \
            "tpu-megakernel"
        with pytest.raises(ValueError):
            MatcherConfig(backend="tpu-megakernel-typo")

    def test_auction_pallas_deprecation_logged_and_counted(self, caplog):
        import logging
        n0 = counter_value(
            "cook_config_deprecated",
            {"knob": "matcher.backend", "value": "tpu-auction-pallas"})
        with caplog.at_level(logging.WARNING):
            mc = MatcherConfig(backend="tpu-auction-pallas")
        assert mc.backend == "tpu-auction"
        assert any("DEPRECATED" in r.message for r in caplog.records)
        assert counter_value(
            "cook_config_deprecated",
            {"knob": "matcher.backend",
             "value": "tpu-auction-pallas"}) == n0 + 1

    def test_split_path_resolves_megakernel_to_greedy(self):
        from cook_tpu.sched.matcher import Matcher
        mc = MatcherConfig(backend="tpu-megakernel")
        assert Matcher.resolve_backend(mc, 10) == "tpu-greedy"


class TestTelemetryAndFallback:
    def test_cycle_record_path_and_launch_count(self):
        store, sched, jobs = build_world(make_cfg())
        sched.step_cycle()
        rec = flight_recorder.recent(3)[-1]
        assert rec["path"] == "megakernel"
        assert rec["kernel_launches"] == 1, rec["kernel_launches"]
        store, sched, jobs = build_world(make_cfg(backend="auto"))
        sched.step_cycle()
        rec = flight_recorder.recent(3)[-1]
        assert rec["path"] == "fused"

    def test_dispatch_failure_degrades_to_fused_xla(self, monkeypatch):
        from cook_tpu.ops import pallas_cycle as pc
        base = drive(make_cfg(backend="auto"), cycles=1)
        n0 = counter_value("cook_kernel_fallback",
                                    {"kernel": "pallas.megacycle"})

        def boom(*a, **kw):
            raise RuntimeError("mosaic lowering exploded")
        monkeypatch.setattr(pc, "megacycle", boom)
        got = drive(make_cfg(), cycles=1)
        assert got == base
        assert counter_value(
            "cook_kernel_fallback",
            {"kernel": "pallas.megacycle"}) > n0
        rec = flight_recorder.recent(3)[-1]
        assert rec["path"] == "fused"


# ---------------------------------------------------------------------------
# lint pass: module-level jnp constants in pallas modules
# ---------------------------------------------------------------------------

@pytest.mark.analysis
class TestPallasModuleConstantPass:
    def _lint(self, tmp_path, source, name):
        import textwrap
        from cook_tpu.analysis.engine import run_lint
        pkg = tmp_path / "pkg"
        target = pkg / name
        target.parent.mkdir(parents=True, exist_ok=True)
        target.write_text(textwrap.dedent(source))
        empty = tmp_path / "empty_baseline.json"
        empty.write_text('{"suppressions": []}')
        return run_lint(package_root=pkg, docs_root=None, baseline=empty)

    def test_module_level_jnp_constant_fires(self, tmp_path):
        r = self._lint(tmp_path, """
            import jax.numpy as jnp
            NEG = jnp.float32(-1e30)
            def kernel(ref):
                return ref[...] + NEG
        """, "ops/pallas_thing.py")
        assert any(f.check == "pallas-module-constant"
                   for f in r.findings), r.findings

    def test_python_literal_and_inner_jnp_clean(self, tmp_path):
        r = self._lint(tmp_path, """
            import jax.numpy as jnp
            BIG = 2**31 - 1
            def kernel(ref):
                neg = jnp.float32(-1e30)
                return ref[...] + neg + BIG
        """, "ops/pallas_thing.py")
        assert not any(f.check == "pallas-module-constant"
                       for f in r.findings), r.findings

    def test_non_pallas_module_exempt(self, tmp_path):
        r = self._lint(tmp_path, """
            import jax.numpy as jnp
            NEG = jnp.float32(-1e30)
        """, "ops/dru_like.py")
        assert not any(f.check == "pallas-module-constant"
                       for f in r.findings)
