"""State-core tests: schema, state machines, transactional store.

Models the reference's unit-test tier (SURVEY.md section 4: in-memory Datomic +
entity factories testutil.clj:217-478) with plain Store fixtures.
"""

import pytest

from cook_tpu.state import (
    AbortTransaction,
    Group,
    Instance,
    InstanceStatus,
    Job,
    JobState,
    Reasons,
    Resources,
    Store,
    machines,
    new_uuid,
)


def make_job(user="alice", pool="default", cpus=1.0, mem=100.0, gpus=0.0,
             priority=50, max_retries=1, **kw) -> Job:
    return Job(uuid=new_uuid(), user=user, command="echo hi", pool=pool,
               resources=Resources(cpus=cpus, mem=mem, gpus=gpus),
               priority=priority, max_retries=max_retries, **kw)


class TestInstanceStateMachine:
    def test_legal_transitions(self):
        ok = machines.instance_transition_allowed
        assert ok(InstanceStatus.UNKNOWN, InstanceStatus.RUNNING)
        assert ok(InstanceStatus.UNKNOWN, InstanceStatus.FAILED)
        assert ok(InstanceStatus.RUNNING, InstanceStatus.SUCCESS)
        assert ok(InstanceStatus.RUNNING, InstanceStatus.FAILED)
        assert not ok(InstanceStatus.SUCCESS, InstanceStatus.RUNNING)
        assert not ok(InstanceStatus.FAILED, InstanceStatus.RUNNING)
        assert not ok(InstanceStatus.SUCCESS, InstanceStatus.FAILED)
        # self-transition is a tolerated no-op
        assert ok(InstanceStatus.RUNNING, InstanceStatus.RUNNING)


class TestLaunchAndComplete:
    def test_launch_then_success(self):
        store = Store()
        [uuid] = store.create_jobs([make_job()])
        inst = store.launch_instance(uuid, "task-1", "host-a")
        assert inst.status is InstanceStatus.UNKNOWN
        assert store.job(uuid).state is JobState.RUNNING

        assert store.update_instance_status("task-1", InstanceStatus.RUNNING)
        assert store.update_instance_status("task-1", InstanceStatus.SUCCESS)
        job = store.job(uuid)
        assert job.state is JobState.COMPLETED

    def test_failed_instance_requeues_until_retries_exhausted(self):
        store = Store()
        [uuid] = store.create_jobs([make_job(max_retries=2)])
        store.launch_instance(uuid, "t1", "h1")
        store.update_instance_status("t1", InstanceStatus.FAILED,
                                     reason_code=Reasons.NON_ZERO_EXIT.code)
        assert store.job(uuid).state is JobState.WAITING  # retry available
        store.launch_instance(uuid, "t2", "h2")
        store.update_instance_status("t2", InstanceStatus.FAILED,
                                     reason_code=Reasons.NON_ZERO_EXIT.code)
        assert store.job(uuid).state is JobState.COMPLETED  # attempts consumed

    def test_mea_culpa_failure_does_not_consume_retry(self):
        store = Store()
        [uuid] = store.create_jobs([make_job(max_retries=1)])
        for i in range(3):
            store.launch_instance(uuid, f"t{i}", f"h{i}")
            store.update_instance_status(
                f"t{i}", InstanceStatus.FAILED,
                reason_code=Reasons.PREEMPTED_BY_REBALANCER.code, preempted=True)
            assert store.job(uuid).state is JobState.WAITING
        # a real failure then consumes the single retry
        store.launch_instance(uuid, "t-final", "hx")
        store.update_instance_status("t-final", InstanceStatus.FAILED,
                                     reason_code=Reasons.NON_ZERO_EXIT.code)
        assert store.job(uuid).state is JobState.COMPLETED

    def test_mea_culpa_failure_limit(self):
        # CONTAINER_LAUNCH_FAILED has failure_limit=3: the 4th occurrence
        # consumes a real retry (reference: reason failure limits +
        # persist-mea-culpa-failure-limit! scheduler.clj:2326-2342).
        store = Store()
        [uuid] = store.create_jobs([make_job(max_retries=1)])
        for i in range(3):
            store.launch_instance(uuid, f"t{i}", "h")
            store.update_instance_status(f"t{i}", InstanceStatus.FAILED,
                                         reason_code=Reasons.CONTAINER_LAUNCH_FAILED.code)
            assert store.job(uuid).state is JobState.WAITING
        store.launch_instance(uuid, "t3", "h")
        store.update_instance_status("t3", InstanceStatus.FAILED,
                                     reason_code=Reasons.CONTAINER_LAUNCH_FAILED.code)
        assert store.job(uuid).state is JobState.COMPLETED

    def test_disable_mea_culpa(self):
        store = Store()
        [uuid] = store.create_jobs([make_job(max_retries=1, disable_mea_culpa_retries=True)])
        store.launch_instance(uuid, "t0", "h")
        store.update_instance_status("t0", InstanceStatus.FAILED,
                                     reason_code=Reasons.PREEMPTED_BY_REBALANCER.code)
        assert store.job(uuid).state is JobState.COMPLETED


class TestLaunchGuard:
    def test_allowed_to_start_blocks_double_launch(self):
        store = Store()
        [uuid] = store.create_jobs([make_job()])
        store.launch_instance(uuid, "t1", "h1")
        with pytest.raises(AbortTransaction) as exc:
            store.launch_instance(uuid, "t2", "h2")
        assert "job-state-running" in str(exc.value)

    def test_allowed_to_start_blocks_completed_job(self):
        store = Store()
        [uuid] = store.create_jobs([make_job()])
        store.kill_job(uuid)
        with pytest.raises(AbortTransaction):
            store.launch_instance(uuid, "t1", "h1")

    def test_abort_rolls_back_everything(self):
        store = Store()
        [uuid] = store.create_jobs([make_job()])
        store.launch_instance(uuid, "t1", "h1")
        try:
            store.launch_instance(uuid, "t2", "h2")
        except AbortTransaction:
            pass
        assert store.instance("t2") is None
        assert len(store.job(uuid).instances) == 1


class TestKillAndTxFeed:
    def test_kill_emits_completed_event(self):
        store = Store()
        events = []
        store.subscribe(lambda tx_id, evs: events.extend(evs))
        [uuid] = store.create_jobs([make_job()])
        store.launch_instance(uuid, "t1", "h1")
        store.kill_job(uuid)
        job = store.job(uuid)
        assert job.state is JobState.COMPLETED
        kinds = [e.kind for e in events]
        assert "job-created" in kinds and "instance-created" in kinds
        completed = [e for e in events if e.kind == "job-state" and e.data["new"] == "completed"]
        assert completed and completed[0].data["reason"] == "user-killed"

    def test_redelivered_terminal_status_is_pure_noop(self):
        # k8s watch replays / mesos re-sends must not touch terminal fields
        store = Store()
        [uuid] = store.create_jobs([make_job()])
        store.launch_instance(uuid, "t1", "h1")
        store.update_instance_status("t1", InstanceStatus.RUNNING)
        store.update_instance_status("t1", InstanceStatus.FAILED,
                                     reason_code=Reasons.NON_ZERO_EXIT.code,
                                     exit_code=3)
        first = store.instance("t1")
        assert store.update_instance_status(
            "t1", InstanceStatus.FAILED,
            reason_code=Reasons.PREEMPTED_BY_REBALANCER.code, exit_code=9,
            preempted=True)
        again = store.instance("t1")
        assert again.end_time_ms == first.end_time_ms
        assert again.reason_code == Reasons.NON_ZERO_EXIT.code
        assert again.exit_code == 3
        assert not again.preempted

    def test_progress_sequence_monotone(self):
        store = Store()
        [uuid] = store.create_jobs([make_job()])
        store.launch_instance(uuid, "t1", "h1")
        assert store.update_instance_progress("t1", 50, sequence=5)
        assert not store.update_instance_progress("t1", 30, sequence=3)
        assert store.instance("t1").progress == 50

    def test_txn_read_mutation_does_not_leak(self):
        # mutating an entity obtained via a txn *read* then aborting must
        # leave the store untouched (all-or-nothing guarantee)
        store = Store()
        [uuid] = store.create_jobs([make_job()])

        def evil(txn):
            job = txn.job(uuid)  # read, not job_w
            job.priority = 99
            txn.abort("nope")

        with pytest.raises(AbortTransaction):
            store.transact(evil)
        assert store.job(uuid).priority == 50

    def test_subscriber_transacting_from_callback(self):
        # a subscriber reacting to job completion by transacting (the
        # monitor-tx-report-queue pattern) must not deadlock and must see
        # events in commit order
        store = Store()
        seen = []

        def on_events(tx_id, events):
            seen.append(tx_id)
            for e in events:
                if e.kind == "job-state" and e.data["new"] == "completed":
                    store.kill_job(e.data["uuid"])  # idempotent re-kill

        store.subscribe(on_events)
        [uuid] = store.create_jobs([make_job()])
        store.launch_instance(uuid, "t1", "h1")
        store.update_instance_status("t1", InstanceStatus.SUCCESS)
        assert seen == sorted(seen)

    def test_stale_status_update_dropped(self):
        store = Store()
        [uuid] = store.create_jobs([make_job()])
        store.launch_instance(uuid, "t1", "h1")
        store.update_instance_status("t1", InstanceStatus.SUCCESS)
        # late RUNNING update must not resurrect the instance
        assert not store.update_instance_status("t1", InstanceStatus.RUNNING)
        assert store.instance("t1").status is InstanceStatus.SUCCESS
        assert store.job(uuid).state is JobState.COMPLETED


class TestCommitLatch:
    def test_uncommitted_jobs_invisible_until_latch_commits(self):
        store = Store()
        jobs = [make_job(), make_job()]
        store.create_jobs(jobs, latch="latch-1")
        assert store.pending_jobs() == []
        store.commit_latch("latch-1")
        assert {j.uuid for j in store.pending_jobs()} == {j.uuid for j in jobs}

    def test_uncommitted_job_cannot_start(self):
        store = Store()
        [uuid] = store.create_jobs([make_job()], latch="latch-2")
        with pytest.raises(AbortTransaction) as exc:
            store.launch_instance(uuid, "t1", "h1")
        assert "uncommitted" in str(exc.value)


class TestRetry:
    def test_retry_resurrects_completed_job(self):
        store = Store()
        [uuid] = store.create_jobs([make_job(max_retries=1)])
        store.launch_instance(uuid, "t1", "h1")
        store.update_instance_status("t1", InstanceStatus.FAILED,
                                     reason_code=Reasons.NON_ZERO_EXIT.code)
        assert store.job(uuid).state is JobState.COMPLETED
        store.retry_job(uuid, 3)
        assert store.job(uuid).state is JobState.WAITING

    def test_retry_does_not_resurrect_successful_job(self):
        store = Store()
        [uuid] = store.create_jobs([make_job()])
        store.launch_instance(uuid, "t1", "h1")
        store.update_instance_status("t1", InstanceStatus.SUCCESS)
        store.retry_job(uuid, 5)
        assert store.job(uuid).state is JobState.COMPLETED


class TestSharesQuotas:
    def test_share_default_user_fallback(self):
        store = Store()
        store.set_share("default", "default", {"cpus": 10.0, "mem": 1000.0})
        store.set_share("alice", "default", {"cpus": 20.0})
        s = store.get_share("alice", "default")
        assert s["cpus"] == 20.0
        assert s["mem"] == 1000.0  # falls back to default user
        s = store.get_share("bob", "default")
        assert s["cpus"] == 10.0
        # unset dims fall back to a MAX_VALUE stand-in
        assert store.get_share("bob", "default")["gpus"] > 1e300

    def test_quota_count_dimension(self):
        store = Store()
        store.set_quota("alice", "default", {"cpus": 4.0}, count=2)
        q = store.get_quota("alice", "default")
        assert q["count"] == 2
        assert q["mem"] == float("inf")


class TestConcurrency:
    def test_latched_creates_with_transacting_subscriber_under_threads(self):
        # regression: create_jobs used to hold the store lock across event
        # drain, deadlocking against a concurrent drainer
        import threading
        store = Store()

        def reactive(tx_id, events):
            for e in events:
                if e.kind == "job-committed":
                    store.kill_job(e.data["uuid"])  # transact from callback

        store.subscribe(reactive)
        errs = []

        def submitter(k):
            try:
                for i in range(20):
                    latch = f"latch-{k}-{i}"
                    store.create_jobs([make_job(user=f"u{k}")], latch=latch)
                    store.commit_latch(latch)
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=submitter, args=(k,))
                   for k in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not any(t.is_alive() for t in threads), "deadlock"
        assert not errs
        # every job was committed then killed by the subscriber
        assert all(j.state is JobState.COMPLETED
                   for j in store.jobs_where(lambda j: True))


class TestSnapshotRestore:
    def test_round_trip(self):
        store = Store()
        [uuid] = store.create_jobs([make_job(gpus=2.0)])
        store.launch_instance(uuid, "t1", "h1")
        store.update_instance_status("t1", InstanceStatus.RUNNING)
        store.set_share("alice", "default", {"cpus": 5.0})
        store.set_quota("alice", "default", {"mem": 100.0}, count=7)
        blob = store.snapshot()
        restored = Store.restore(blob)
        job = restored.job(uuid)
        assert job.state is JobState.RUNNING
        assert job.resources.gpus == 2.0
        assert restored.instance("t1").status is InstanceStatus.RUNNING
        assert restored.get_share("alice", "default")["cpus"] == 5.0
        assert restored.get_quota("alice", "default")["count"] == 7
        # restored store is live: finish the instance
        restored.update_instance_status("t1", InstanceStatus.SUCCESS)
        assert restored.job(uuid).state is JobState.COMPLETED


class TestDurableStore:
    def test_crash_and_reopen_replays_journal(self, tmp_path):
        d = str(tmp_path / "state")
        store = Store.open(d)
        [uuid] = store.create_jobs([make_job()])
        store.launch_instance(uuid, "t1", "host-a")
        store.update_instance_status("t1", InstanceStatus.RUNNING)
        store.set_share("alice", "default", {"cpus": 5.0})
        tx_before = store._tx_id
        # simulate a crash: no close(), no checkpoint — just reopen
        reopened = Store.open(d)
        assert reopened.job(uuid).state is JobState.RUNNING
        assert reopened.instance("t1").status is InstanceStatus.RUNNING
        assert reopened.get_share("alice", "default")["cpus"] == 5.0
        assert reopened._tx_id == tx_before
        # the reopened store is live and keeps journaling
        reopened.update_instance_status("t1", InstanceStatus.SUCCESS)
        third = Store.open(d)
        assert third.job(uuid).state is JobState.COMPLETED

    def test_denied_launch_does_not_grow_journal(self, tmp_path):
        """A guard-denied launch must journal NOTHING (regression: taking
        write intent before the guard re-journaled the unchanged job on
        every denial, growing the journal unboundedly for a job that keeps
        getting matched while no longer startable)."""
        import os
        d = str(tmp_path / "state")
        store = Store.open(d)
        [uuid] = store.create_jobs([make_job()])
        store.kill_job(uuid)
        size_before = os.path.getsize(store._journal_path)
        for i in range(5):
            insts, fails = store.launch_instances([
                dict(job_uuid=uuid, task_id=f"t{i}", hostname="h")])
            assert insts == [] and len(fails) == 1
        assert os.path.getsize(store._journal_path) == size_before

    def test_checkpoint_compacts_journal(self, tmp_path):
        d = str(tmp_path / "state")
        store = Store.open(d)
        uuids = store.create_jobs([make_job() for _ in range(5)])
        journal = tmp_path / "state" / "journal.jsonl"
        assert journal.stat().st_size > 0
        store.checkpoint()
        # compacted: every ENTITY record is gone — what remains is at
        # most the bounded audit re-seed record ({"a": [...]}) that keeps
        # per-job timelines alive across compaction (utils/audit.py)
        from cook_tpu.state.integrity import scan_journal
        recs, _good, _size = scan_journal(str(journal))
        assert all(set(r) <= {"a", "ep"} for r in recs), recs
        assert (tmp_path / "state" / "snapshot.json").exists()
        # post-checkpoint writes land in the fresh journal
        store.kill_job(uuids[0])
        reopened = Store.open(d)
        assert reopened.job(uuids[0]).state is JobState.COMPLETED
        assert reopened.job(uuids[1]).state is JobState.WAITING

    def test_torn_tail_write_is_ignored(self, tmp_path):
        d = str(tmp_path / "state")
        store = Store.open(d)
        [uuid] = store.create_jobs([make_job()])
        store.close()
        journal = tmp_path / "state" / "journal.jsonl"
        with open(journal, "a") as f:
            f.write('{"tx": 99, "w": {"jobs/zzz": {"uu')  # torn record
        reopened = Store.open(d)
        assert reopened.job(uuid) is not None
        assert reopened.job("zzz") is None

    def test_uncommitted_latch_survives_restart_invisible(self, tmp_path):
        d = str(tmp_path / "state")
        store = Store.open(d)
        job = make_job()
        store.create_jobs([job], latch="latch-1")
        assert store.pending_jobs("default") == []
        reopened = Store.open(d)
        # still registered and still invisible
        assert reopened.pending_jobs("default") == []
        reopened.commit_latch("latch-1")
        assert [j.uuid for j in reopened.pending_jobs("default")] == [job.uuid]
        final = Store.open(d)
        assert [j.uuid for j in final.pending_jobs("default")] == [job.uuid]

    def test_quota_inf_roundtrips_through_journal(self, tmp_path):
        d = str(tmp_path / "state")
        store = Store.open(d)
        store.set_quota("bob", "default", {"cpus": 4.0})  # count defaults inf
        reopened = Store.open(d)
        assert reopened.get_quota("bob", "default")["count"] == float("inf")
        assert reopened.get_quota("bob", "default")["cpus"] == 4.0

    def test_retract_share_durable(self, tmp_path):
        d = str(tmp_path / "state")
        store = Store.open(d)
        store.set_share("alice", "default", {"cpus": 2.0})
        store.retract_share("alice", "default")
        reopened = Store.open(d)
        # falls back to the infinite default
        assert reopened.get_share("alice", "default")["cpus"] == float("inf")

    def test_writes_after_torn_tail_recovery_survive_next_reopen(self, tmp_path):
        d = str(tmp_path / "state")
        store = Store.open(d)
        [u1] = store.create_jobs([make_job()])
        store.close()
        journal = tmp_path / "state" / "journal.jsonl"
        with open(journal, "a") as f:
            f.write('{"tx": 99, "w"')  # torn record, no newline
        # recovery truncates the torn bytes; new writes append cleanly
        recovered = Store.open(d)
        [u2] = recovered.create_jobs([make_job()])
        recovered.close()
        final = Store.open(d)
        assert final.job(u1) is not None
        assert final.job(u2) is not None, "post-recovery write was lost"

    def test_failed_append_aborts_tx_and_excises_fragment(self, tmp_path):
        """A journal append that dies mid-write must abort the transaction,
        cut the torn fragment back out, and leave the journal appendable."""
        d = str(tmp_path / "state")
        store = Store.open(d)
        [u1] = store.create_jobs([make_job()])

        real_file = store._journal_file

        class TornWriter:
            """Writes half the record, then dies (simulated ENOSPC)."""
            def __init__(self, f):
                self.f = f
            def tell(self):
                return self.f.tell()
            def write(self, s):
                self.f.write(s[: len(s) // 2])
                raise OSError(28, "No space left on device")
            def __getattr__(self, name):
                return getattr(self.f, name)

        store._journal_file = TornWriter(real_file)
        with pytest.raises(OSError):
            store.create_jobs([make_job()])
        store._journal_file = real_file
        # aborted tx is not visible in memory
        assert len(store.jobs_where(lambda j: True)) == 1
        # journal recovered: later transactions append after the excised
        # fragment and a reopen sees exactly the committed state
        [u3] = store.create_jobs([make_job()])
        reopened = Store.open(d)
        assert {j.uuid for j in reopened.jobs_where(lambda j: True)} == {u1, u3}

    def test_unrecoverable_append_failure_poisons_store(self, tmp_path):
        d = str(tmp_path / "state")
        store = Store.open(d)
        store.create_jobs([make_job()])

        class BrokenWriter:
            def tell(self):
                return 0
            def write(self, s):
                raise OSError(5, "I/O error")
            def seek(self, *a):
                raise OSError(5, "I/O error")
            def truncate(self, *a):
                raise OSError(5, "I/O error")
            def close(self):
                pass

        store._journal_file = BrokenWriter()
        with pytest.raises(OSError):
            store.create_jobs([make_job()])
        # journal is poisoned: durable writes now refuse instead of
        # silently diverging from what a replay would reconstruct
        with pytest.raises(RuntimeError, match="poisoned"):
            store.create_jobs([make_job()])


class TestPeekContract:
    """peek()/peek_instances_of return LIVE store entities guarded by a
    __debug__-mode fingerprint spot-check (ADVICE r5): a guard that
    mutates what it peeked fails the transaction loudly instead of
    silently corrupting committed state outside the undo log."""

    def test_mutating_a_peeked_entity_fails_the_txn(self):
        store = Store()
        [uuid] = store.create_jobs([make_job()])

        def rogue_guard(txn):
            job = txn.peek("jobs", uuid)
            job.priority = 99  # violates the read-only promise

        with pytest.raises(AssertionError, match="peeked entity"):
            store.transact(rogue_guard)
        # the store entity itself keeps the rogue write (peek is
        # no-clone by design); the assertion exists to catch the bug in
        # tests before it ships, not to roll it back
        assert store.job(uuid) is not None

    def test_peek_then_write_accessor_is_legal(self):
        store = Store()
        [uuid] = store.create_jobs([make_job()])

        def guard_then_write(txn):
            peeked = txn.peek("jobs", uuid)
            assert peeked.priority == 50
            job = txn.job_w(uuid)  # the sanctioned mutation path
            job.priority = 75

        store.transact(guard_then_write)
        assert store.job(uuid).priority == 75

    def test_peek_of_own_write_is_not_fingerprinted(self):
        store = Store()

        def create_and_mutate(txn):
            job = make_job()
            txn.put("jobs", job.uuid, job)
            peeked = txn.peek("jobs", job.uuid)  # resolves to OUR write
            peeked.priority = 60  # legal: txn-local entity
            return job.uuid

        uuid = store.transact(create_and_mutate)
        assert store.job(uuid).priority == 60
