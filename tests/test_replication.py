"""Socket journal replication (native/repl.cpp + state/replication.py).

The reference's durable state is an out-of-process networked store
(datomic.clj:79), so failover works from any host.  These tests prove the
cook_tpu equivalent: a follower mirrors the leader's journal over TCP into
its OWN directory (no shared filesystem), sync replication means
"committed implies on the mirror", and a promoted follower carries every
committed transaction with stale-epoch records fenced out.
"""

import json
import os
import time

import pytest

from cook_tpu.state import ReplicationTimeout, Store
from cook_tpu.state.replication import (
    ReplicationFollower,
    ReplicationServer,
    replication_available,
)
from cook_tpu.state.schema import Job, Resources

pytestmark = pytest.mark.skipif(not replication_available(),
                                reason="C++ toolchain unavailable")


def make_job(i, user="alice"):
    return Job(uuid=f"00000000-0000-0000-0000-{i:012d}", user=user,
               command=f"echo {i}", resources=Resources(cpus=1, mem=64))


def journal_size(d):
    try:
        return os.path.getsize(os.path.join(d, "journal.jsonl"))
    except FileNotFoundError:
        return 0


def wait_for(pred, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.005)
    return pred()


def wait_synced(srv, n=1, timeout=10.0):
    """The sync-commit guarantee starts once a follower is SYNCED (has
    reached the journal head), not merely connected — a catching-up
    follower neither acks nor blocks commits."""
    return wait_for(lambda: srv.synced_follower_count >= n, timeout)


class TestMirror:
    def test_sync_commit_reaches_follower_bytes_identical(self, tmp_path):
        dir_a, dir_b = str(tmp_path / "a"), str(tmp_path / "b")
        store = Store.open(dir_a, epoch=1, shared=False)
        with ReplicationServer(dir_a) as srv:
            store.attach_replication(srv, sync=True)
            with ReplicationFollower("127.0.0.1", srv.port, dir_b) as f:
                assert wait_synced(srv)
                store.create_jobs([make_job(i) for i in range(50)])
                # sync mode: by the time create_jobs RETURNED, the bytes
                # were fsynced on the follower — no wait needed
                assert journal_size(dir_b) == journal_size(dir_a)
                a = open(os.path.join(dir_a, "journal.jsonl"), "rb").read()
                b = open(os.path.join(dir_b, "journal.jsonl"), "rb").read()
                assert a == b
        replica = Store.replay_only(dir_b)
        assert len(replica.jobs_where(lambda j: True)) == 50

    def test_late_joiner_catches_up(self, tmp_path):
        dir_a, dir_b = str(tmp_path / "a"), str(tmp_path / "b")
        store = Store.open(dir_a, epoch=1, shared=False)
        store.create_jobs([make_job(i) for i in range(200)])
        size = journal_size(dir_a)
        with ReplicationServer(dir_a) as srv:
            store.attach_replication(srv, sync=True)
            with ReplicationFollower("127.0.0.1", srv.port, dir_b) as f:
                assert f.wait_offset(size)
        replica = Store.replay_only(dir_b)
        assert len(replica.jobs_where(lambda j: True)) == 200

    def test_checkpoint_resyncs_follower_snapshot(self, tmp_path):
        dir_a, dir_b = str(tmp_path / "a"), str(tmp_path / "b")
        store = Store.open(dir_a, epoch=1, shared=False)
        with ReplicationServer(dir_a) as srv:
            store.attach_replication(srv, sync=True)
            with ReplicationFollower("127.0.0.1", srv.port, dir_b) as f:
                assert wait_synced(srv)
                store.create_jobs([make_job(i) for i in range(30)])
                store.checkpoint()  # journal truncates; snapshot moves
                store.create_jobs([make_job(i) for i in range(30, 40)])
                # follower must RESET to the new snapshot, then mirror the
                # post-checkpoint journal tail
                assert wait_for(
                    lambda: journal_size(dir_b) == journal_size(dir_a)
                    and os.path.exists(
                        os.path.join(dir_b, "snapshot.json")))
        replica = Store.replay_only(dir_b)
        assert len(replica.jobs_where(lambda j: True)) == 40

    def test_follower_reconnect_resumes_incrementally(self, tmp_path):
        dir_a, dir_b = str(tmp_path / "a"), str(tmp_path / "b")
        store = Store.open(dir_a, epoch=1, shared=False)
        with ReplicationServer(dir_a) as srv:
            store.attach_replication(srv, sync=True)
            with ReplicationFollower("127.0.0.1", srv.port, dir_b):
                store.create_jobs([make_job(i) for i in range(20)])
            # follower gone; leader keeps committing (no min_followers)
            store.create_jobs([make_job(i) for i in range(20, 35)])
            with ReplicationFollower("127.0.0.1", srv.port, dir_b) as f:
                assert f.wait_offset(journal_size(dir_a))
        assert len(Store.replay_only(dir_b).jobs_where(lambda j: True)) == 35

    def test_min_followers_refuses_lone_commit(self, tmp_path):
        dir_a = str(tmp_path / "a")
        store = Store.open(dir_a, epoch=1, shared=False)
        with ReplicationServer(dir_a) as srv:
            store.attach_replication(srv, sync=True, min_followers=1)
            with pytest.raises(ReplicationTimeout):
                store.create_jobs([make_job(0)])
            # the refused record was excised: replay sees nothing
            assert len(Store.replay_only(dir_a).jobs_where(lambda j: True)) == 0
            # a follower arrives -> commits flow again
            dir_b = str(tmp_path / "b")
            with ReplicationFollower("127.0.0.1", srv.port, dir_b) as f:
                assert wait_synced(srv)
                store.create_jobs([make_job(1)])
                assert len(Store.replay_only(dir_b).jobs_where(lambda j: True)) == 1


class TestCatchUpInterruptions:
    def test_follower_killed_mid_catchup_reconnects_and_converges(
            self, tmp_path):
        """A large backlog streamed in 1 MiB chunks; the follower is
        stopped partway, restarts, HELLOs with its trimmed offset, and
        must converge byte-identically (incremental, same base)."""
        dir_a, dir_b = str(tmp_path / "a"), str(tmp_path / "b")
        store = Store.open(dir_a, epoch=1, shared=False)
        store.create_jobs([make_job(i) for i in range(3000)])
        total = journal_size(dir_a)
        with ReplicationServer(dir_a) as srv:
            store.attach_replication(srv, sync=True)
            with ReplicationFollower("127.0.0.1", srv.port,
                                     dir_b) as f:
                # stop somewhere in the middle of the catch-up (the
                # context manager guarantees cleanup if the wait raises;
                # the explicit stop below is the intentional mid-kill)
                wait_for(lambda: f.offset >= total // 3, timeout=10)
                f.stop()
            partial = journal_size(dir_b)
            # a fast machine may finish the catch-up before the stop
            # lands; the reconnect below then exercises HELLO-at-head
            # instead of mid-stream resume — both are valid paths
            assert 0 < partial <= total, (partial, total)
            with ReplicationFollower("127.0.0.1", srv.port, dir_b) as f2:
                assert f2.wait_offset(total)
        a = open(os.path.join(dir_a, "journal.jsonl"), "rb").read()
        b = open(os.path.join(dir_b, "journal.jsonl"), "rb").read()
        assert a == b
        assert len(Store.replay_only(dir_b)
                   .jobs_where(lambda j: True)) == 3000

    def test_checkpoint_during_catchup_resyncs_to_new_base(self,
                                                           tmp_path):
        """The leader compacts WHILE a follower is still streaming the
        old journal: the serving loop detects the moved base mid-stream
        and full-resyncs; the mirror must end on the new snapshot +
        post-checkpoint tail."""
        dir_a, dir_b = str(tmp_path / "a"), str(tmp_path / "b")
        store = Store.open(dir_a, epoch=1, shared=False)
        store.create_jobs([make_job(i) for i in range(2500)])
        with ReplicationServer(dir_a) as srv:
            store.attach_replication(srv, sync=True)
            with ReplicationFollower("127.0.0.1", srv.port, dir_b) as f:
                # checkpoint as soon as the stream is underway
                wait_for(lambda: f.offset > 0, timeout=10)
                store.checkpoint()
                store.create_jobs([make_job(i)
                                   for i in range(2500, 2600)])
                assert wait_for(
                    lambda: journal_size(dir_b) == journal_size(dir_a)
                    and os.path.exists(
                        os.path.join(dir_b, "snapshot.json")))
        assert len(Store.replay_only(dir_b)
                   .jobs_where(lambda j: True)) == 2600


class TestPromotion:
    def test_promotion_gate_refuses_unsynced_mirror(self, tmp_path):
        """A standby mid-catch-up (token written, head never reached)
        must not become the authority — and a synced follower's dir
        carries the marker that allows it."""
        from cook_tpu.state.replication import assert_promotable
        d = tmp_path / "m"
        d.mkdir()
        assert_promotable(str(d))  # never followed: cluster genesis
        # a fresh standby killed mid-initial-snapshot has only the
        # "following" marker (no token yet) — still not genesis
        (d / "repl_following").write_text("1")
        with pytest.raises(RuntimeError, match="never reached"):
            assert_promotable(str(d))
        (d / "repl_token").write_text("tok")
        with pytest.raises(RuntimeError, match="never reached"):
            assert_promotable(str(d))  # began following, not synced
        (d / "repl_synced").write_text("1")
        assert_promotable(str(d))  # synced: promotable

        # end-to-end: a follower that reaches the head gets the marker,
        # and a RESET (leader checkpoint) strips it until resynced
        dir_a, dir_b = str(tmp_path / "a"), str(tmp_path / "b")
        store = Store.open(dir_a, epoch=1, shared=False)
        with ReplicationServer(dir_a) as srv:
            store.attach_replication(srv, sync=True)
            with ReplicationFollower("127.0.0.1", srv.port, dir_b):
                assert wait_synced(srv)
                assert wait_for(lambda: os.path.exists(
                    os.path.join(dir_b, "repl_synced")))
        assert_promotable(dir_b)

    def test_promoted_follower_has_every_committed_txn(self, tmp_path):
        dir_a, dir_b = str(tmp_path / "a"), str(tmp_path / "b")
        store = Store.open(dir_a, epoch=1, shared=False)
        with ReplicationServer(dir_a) as srv:
            store.attach_replication(srv, sync=True)
            with ReplicationFollower("127.0.0.1", srv.port, dir_b) as f:
                # sync acks are vacuous until the standby has SYNCED (a
                # lone leader stays available) — the no-loss guarantee
                # starts here, as in a real deployment with a live standby
                assert wait_synced(srv)
                store.create_jobs([make_job(i) for i in range(25)])
        # leader "dies" (server stopped, no clean handoff); promote B at
        # the next election epoch in ITS OWN directory
        promoted = Store.open(dir_b, epoch=2, shared=False)
        assert len(promoted.jobs_where(lambda j: True)) == 25
        promoted.create_jobs([make_job(99)])
        assert len(promoted.jobs_where(lambda j: True)) == 26

    def test_stale_epoch_records_fenced_after_promotion(self, tmp_path):
        dir_a, dir_b = str(tmp_path / "a"), str(tmp_path / "b")
        store = Store.open(dir_a, epoch=1, shared=False)
        with ReplicationServer(dir_a) as srv:
            store.attach_replication(srv, sync=True)
            with ReplicationFollower("127.0.0.1", srv.port, dir_b) as f:
                assert wait_synced(srv)
                store.create_jobs([make_job(0)])
        promoted = Store.open(dir_b, epoch=2, shared=False)
        promoted.create_jobs([make_job(1)])
        # a deposed ep-1 leader's late record lands after the ep-2
        # barrier (e.g. an in-flight chunk flushed by a dying process):
        # replay must skip it — it was never committed cluster-wide
        stale = {"tx": 999, "ep": 1, "w": {
            "jobs/deadbeef-0000-0000-0000-000000000000":
                json.loads(json.dumps(
                    {"uuid": "deadbeef-0000-0000-0000-000000000000",
                     "user": "mallory", "command": "evil",
                     "resources": {"cpus": 1.0, "mem": 64.0,
                                   "gpus": 0.0, "disk": 0.0}}))}}
        with open(os.path.join(dir_b, "journal.jsonl"), "a") as f:
            f.write(json.dumps(stale) + "\n")
        replayed = Store.replay_only(dir_b)
        uuids = {j.uuid for j in replayed.jobs_where(lambda j: True)}
        assert "deadbeef-0000-0000-0000-000000000000" not in uuids
        assert len(uuids) == 2

    def test_truncate_then_same_length_reappend_forces_reset(self,
                                                             tmp_path):
        """A position-only consistency check would silently accept a
        diverged mirror after the leader excises an aborted record and a
        later commit of the SAME byte length lands at the same offset.
        The store bumps journal_gen on every truncation; the server folds
        it into the mirror-base token, so the reconnecting follower
        full-resyncs and ends byte-identical."""
        dir_a, dir_b = str(tmp_path / "a"), str(tmp_path / "b")
        store = Store.open(dir_a, epoch=1, shared=False)
        with ReplicationServer(dir_a) as srv:
            store.attach_replication(srv, sync=True)
            with ReplicationFollower("127.0.0.1", srv.port, dir_b) as f:
                assert wait_synced(srv)
                store.create_jobs([make_job(0)])
                store.create_jobs([make_job(1)])  # the record to excise
            size_with_b1 = journal_size(dir_a)
            # leader-side excision of the last record (what a
            # ReplicationTimeout abort does), then a same-length commit
            jpath = os.path.join(dir_a, "journal.jsonl")
            lines = open(jpath, "rb").read().splitlines(keepends=True)
            with open(jpath, "r+b") as fh:
                fh.truncate(size_with_b1 - len(lines[-1]))
            store._bump_journal_gen()
            # reopen so the store's file position matches the truncation
            store = Store.open(dir_a, epoch=1, shared=False)
            store.create_jobs([make_job(2)])  # same uuid length -> same size
            assert journal_size(dir_a) >= size_with_b1
            with ReplicationFollower("127.0.0.1", srv.port, dir_b) as f:
                assert wait_for(
                    lambda: open(os.path.join(dir_b, "journal.jsonl"),
                                 "rb").read()
                    == open(jpath, "rb").read())
        replayed = Store.replay_only(dir_b)
        uuids = {j.uuid for j in replayed.jobs_where(lambda j: True)}
        assert "00000000-0000-0000-0000-000000000002" in uuids
        assert "00000000-0000-0000-0000-000000000001" not in uuids

    def test_diverged_follower_tail_heals_by_reset(self, tmp_path):
        # follower acked bytes the leader then excised (ack raced a
        # ReplicationTimeout truncation): on reconnect the leader sees
        # offset > journal size and full-resyncs
        dir_a, dir_b = str(tmp_path / "a"), str(tmp_path / "b")
        store = Store.open(dir_a, epoch=1, shared=False)
        store.create_jobs([make_job(i) for i in range(5)])
        with ReplicationServer(dir_a) as srv:
            store.attach_replication(srv, sync=True)
            with ReplicationFollower("127.0.0.1", srv.port, dir_b) as f:
                assert f.wait_offset(journal_size(dir_a))
            # fake divergence: append junk the leader never had
            with open(os.path.join(dir_b, "journal.jsonl"), "a") as fh:
                fh.write(json.dumps({"tx": 12345, "ep": 1}) + "\n")
            assert journal_size(dir_b) > journal_size(dir_a)
            with ReplicationFollower("127.0.0.1", srv.port, dir_b) as f:
                assert wait_for(
                    lambda: journal_size(dir_b) == journal_size(dir_a))
        assert len(Store.replay_only(dir_b).jobs_where(lambda j: True)) == 5
