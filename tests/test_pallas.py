"""Pallas preference-kernel parity vs the plain-XLA formulation.

The blockwise top-K kernel (ops/pallas_match.py) must reproduce
``lax.top_k`` over the full score matrix bit-exactly, including
lowest-host-index tie-breaking, across padding boundaries, and feed the
auction matcher to the same assignments (ops/match.py)."""

import jax.numpy as jnp
import numpy as np
import pytest

from cook_tpu.ops import match, pallas_match


def _rand_problem(rng, J, H, R=4, tie_heavy=False):
    if tie_heavy:  # quantized resources -> many identical fitness scores
        job_res = rng.integers(1, 4, (J, R)).astype(np.float32)
        capacity = np.full((H, R), 8.0, dtype=np.float32)
        avail = rng.integers(0, 9, (H, R)).astype(np.float32)
    else:
        job_res = rng.uniform(0.1, 4.0, (J, R)).astype(np.float32)
        capacity = rng.uniform(8.0, 64.0, (H, R)).astype(np.float32)
        avail = (capacity * rng.uniform(0.0, 1.0, (H, R))).astype(np.float32)
    cmask = rng.random((J, H)) < 0.8
    valid = rng.random(J) < 0.9
    return (jnp.asarray(job_res), jnp.asarray(cmask), jnp.asarray(valid),
            jnp.asarray(avail), jnp.asarray(capacity))


def _reference_topk(job_res, cmask, valid, avail, capacity, k):
    feas = (jnp.all(avail[None, :, :] >= job_res[:, None, :], axis=2)
            & cmask & valid[:, None])
    used = capacity - avail
    cap = jnp.maximum(capacity, 1e-9)
    fit = (used[None, :, 0] + job_res[:, 0:1]) / cap[None, :, 0] \
        + (used[None, :, 1] + job_res[:, 1:2]) / cap[None, :, 1]
    score = jnp.where(feas, fit * 0.5, -jnp.inf)
    import jax
    return jax.lax.top_k(score, min(k, score.shape[1]))


@pytest.mark.parametrize("J,H,k", [
    (16, 8, 4),        # smaller than one tile, k > feasible hosts for some
    (128, 128, 16),    # exactly one tile
    (200, 300, 16),    # ragged: padding rows and a padded host tile
    (300, 520, 8),     # multiple host tiles -> running merge across tiles
])
def test_topk_prefs_matches_lax_topk(J, H, k):
    rng = np.random.default_rng(J * 1000 + H)
    args = _rand_problem(rng, J, H)
    ref_fit, ref_host = _reference_topk(*args, k)
    fit, host = pallas_match.topk_prefs(*args, k=k, interpret=True)
    np.testing.assert_array_equal(np.asarray(fit), np.asarray(ref_fit))
    # host indices only meaningful where the score is finite
    finite = np.asarray(ref_fit) > -np.inf
    np.testing.assert_array_equal(np.asarray(host)[finite],
                                  np.asarray(ref_host)[finite])


def test_topk_prefs_tie_breaking_lowest_host():
    rng = np.random.default_rng(7)
    args = _rand_problem(rng, 150, 260, tie_heavy=True)
    ref_fit, ref_host = _reference_topk(*args, 16)
    fit, host = pallas_match.topk_prefs(*args, k=16, interpret=True)
    finite = np.asarray(ref_fit) > -np.inf
    np.testing.assert_array_equal(np.asarray(fit), np.asarray(ref_fit))
    np.testing.assert_array_equal(np.asarray(host)[finite],
                                  np.asarray(ref_host)[finite])


@pytest.mark.parametrize("J,H,E,k", [
    (128, 128, 4, 8),     # one tile
    (300, 520, 7, 16),    # ragged + multiple host tiles
    (200, 130, 0, 8),     # no exceptions at all
])
def test_topk_prefs_structured_matches_dense(J, H, E, k):
    """The structured-mask kernel (per-host vectors + exception rows
    composed in VMEM) must equal the dense kernel on the equivalent dense
    mask — gpu isolation, blocks, exceptions, validity, padding."""
    rng = np.random.default_rng(J + H * 7 + E)
    job_res = rng.uniform(0.1, 4.0, (J, 4)).astype(np.float32)
    job_res[:, 2] = (rng.random(J) < 0.2).astype(np.float32)  # gpu demand
    capacity = rng.uniform(8.0, 64.0, (H, 4)).astype(np.float32)
    capacity[:, 2] = (rng.random(H) < 0.3) * 4.0              # gpu hosts
    avail = (capacity * rng.uniform(0.0, 1.0, (H, 4))).astype(np.float32)
    host_gpu = capacity[:, 2] > 0
    host_blocked = rng.random(H) < 0.15
    valid = rng.random(J) < 0.9
    exc_id = np.full(J, -1, np.int32)
    exc_mask = np.zeros((max(E, 1), H), dtype=bool)
    if E:
        rows = rng.choice(J, size=E, replace=False)
        exc_id[rows] = np.arange(E, dtype=np.int32)
        exc_mask = rng.random((E, H)) < 0.5
    dense = np.where(job_res[:, 2:3] > 0, host_gpu[None, :],
                     ~host_gpu[None, :]) & ~host_blocked[None, :]
    for kk in range(E):
        dense[np.flatnonzero(exc_id == kk)[0]] = exc_mask[kk]

    ref_fit, ref_host = pallas_match.topk_prefs(
        jnp.asarray(job_res), jnp.asarray(dense), jnp.asarray(valid),
        jnp.asarray(avail), jnp.asarray(capacity), k=k, interpret=True)
    fit, host = pallas_match.topk_prefs_structured(
        jnp.asarray(job_res), jnp.asarray(valid), jnp.asarray(host_gpu),
        jnp.asarray(host_blocked), jnp.asarray(exc_id),
        jnp.asarray(exc_mask), jnp.asarray(avail), jnp.asarray(capacity),
        k=k, interpret=True)
    np.testing.assert_array_equal(np.asarray(fit), np.asarray(ref_fit))
    finite = np.asarray(ref_fit) > -np.inf
    np.testing.assert_array_equal(np.asarray(host)[finite],
                                  np.asarray(ref_host)[finite])


