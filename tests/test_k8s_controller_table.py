"""Exhaustive (cook-expected x pod-synthesized) transition-table test.

Every cell of the controller's state table is asserted (VERDICT r1 #4;
reference: the 30-state table at
scheduler/src/cook/kubernetes/controller.clj:482-711 plus its
deleting-state arms): 5 expected states x 7 pod states = 35 cells, each
checked for the callbacks fired, the final tracked state, and whether the
pod was deleted from kubernetes.
"""

import pytest

from cook_tpu.cluster.k8s.controller import (
    OLD_DELETION_MS,
    CookExpected as E,
    PodController,
    PodState as A,
    synthesize_pod_state,
)
from cook_tpu.cluster.k8s.fake_api import FakeKubernetesApi, FakePod
from cook_tpu.state.schema import Reasons

POD = "pod-1"


class Recorder:
    def __init__(self):
        self.calls = []

    def started(self, name):
        self.calls.append("started")

    def completed(self, name, exit_code, reason):
        self.calls.append(("completed", reason))

    def killed(self, name, reason):
        self.calls.append(("killed", reason))

    def preempted(self, name):
        self.calls.append("preempted")


def setup_cell(expected, actual, *, sticky=True, old_deletion=False,
               with_launch_pod=True, clock_ms=0):
    api = FakeKubernetesApi()
    api.sticky_deletion = sticky
    rec = Recorder()
    ctl = PodController(
        api, on_pod_started=rec.started, on_pod_completed=rec.completed,
        on_pod_killed=rec.killed, on_pod_preempted=rec.preempted,
        clock=lambda: clock_ms)
    pod = None
    if actual is not A.MISSING:
        phase = {A.WAITING: "Pending", A.RUNNING: "Running",
                 A.SUCCEEDED: "Succeeded", A.FAILED: "Failed",
                 A.UNKNOWN: "Unknown", A.DELETING: "Running"}[actual]
        pod = FakePod(name=POD, phase=phase, node_name="n1",
                      labels={"cook/job": "j1"},
                      exit_code=(0 if actual is A.SUCCEEDED else
                                 1 if actual is A.FAILED else None))
        if actual is A.DELETING:
            pod.deleted = True
            pod.deletion_ms = -OLD_DELETION_MS - 1 if old_deletion else 0
        api._pods[POD] = pod  # place directly: no watch noise
        assert synthesize_pod_state(pod) is actual
    if expected is not E.MISSING:
        ctl.set_expected(POD, expected)
        if with_launch_pod:
            ctl.expected[POD].launch_pod = pod or FakePod(name=POD)
    return api, ctl, rec


# (expected, actual) -> (callbacks, entry_gone, pod_gone)
# entry_gone: controller forgot the pod; pod_gone: removed from kubernetes.
K_USER = ("killed", Reasons.KILLED_BY_USER.code)
K_LOST = ("killed", Reasons.NODE_LOST.code)
C_OK = ("completed", None)
C_FAIL = ("completed", Reasons.NON_ZERO_EXIT.code)
C_MEA = ("completed", Reasons.UNKNOWN_MEA_CULPA.code)

TABLE = {
    (E.STARTING, A.WAITING):   ([], False, False),
    (E.STARTING, A.MISSING):   ([], False, True),
    (E.STARTING, A.RUNNING):   (["started"], False, False),
    (E.STARTING, A.SUCCEEDED): (["started", C_OK], True, True),
    (E.STARTING, A.FAILED):    ([C_FAIL], True, True),
    (E.STARTING, A.UNKNOWN):   ([C_MEA], True, True),
    (E.STARTING, A.DELETING):  ([K_LOST], True, False),

    (E.RUNNING, A.RUNNING):    ([], False, False),
    (E.RUNNING, A.WAITING):    (["preempted"], True, True),
    (E.RUNNING, A.SUCCEEDED):  ([C_OK], True, True),
    (E.RUNNING, A.FAILED):     ([C_FAIL], True, True),
    (E.RUNNING, A.UNKNOWN):    ([C_MEA], True, True),
    (E.RUNNING, A.MISSING):    ([K_LOST], True, True),
    (E.RUNNING, A.DELETING):   ([K_LOST], True, False),

    (E.KILLED, A.WAITING):     ([K_USER], True, True),
    (E.KILLED, A.RUNNING):     ([K_USER], True, True),
    (E.KILLED, A.SUCCEEDED):   ([C_OK], True, True),
    (E.KILLED, A.FAILED):      ([K_USER], True, True),
    (E.KILLED, A.UNKNOWN):     ([C_MEA], True, True),
    (E.KILLED, A.DELETING):    ([K_USER], True, False),
    (E.KILLED, A.MISSING):     ([K_USER], True, True),

    (E.COMPLETED, A.SUCCEEDED): ([], True, True),
    (E.COMPLETED, A.FAILED):    ([], True, True),
    (E.COMPLETED, A.UNKNOWN):   ([], True, True),
    # weird-kill cells: the pod is deleted but the entry stays until the
    # watch's DELETED event re-processes (asserted in
    # test_weird_kill_converges_on_delete_event)
    (E.COMPLETED, A.RUNNING):   ([], False, True),
    (E.COMPLETED, A.WAITING):   ([], False, True),
    (E.COMPLETED, A.DELETING):  ([], True, False),
    (E.COMPLETED, A.MISSING):   ([], True, True),

    (E.MISSING, A.MISSING):    ([], True, True),
    (E.MISSING, A.SUCCEEDED):  ([], True, True),
    (E.MISSING, A.FAILED):     ([], True, True),
    (E.MISSING, A.UNKNOWN):    ([], True, True),
    (E.MISSING, A.RUNNING):    ([], True, True),
    (E.MISSING, A.WAITING):    ([], True, True),
    (E.MISSING, A.DELETING):   ([], True, False),
}


class TestFullTransitionTable:
    @pytest.mark.parametrize("expected,actual",
                             sorted(TABLE, key=lambda c: (c[0].value,
                                                          c[1].value)))
    def test_cell(self, expected, actual):
        callbacks, entry_gone, pod_gone = TABLE[(expected, actual)]
        # non-sticky deletion so "delete" removes the pod immediately;
        # DELETING cells are staged with sticky deletion
        api, ctl, rec = setup_cell(expected, actual,
                                   sticky=(actual is A.DELETING))
        ctl.process(POD)
        assert rec.calls == callbacks, (expected, actual, rec.calls)
        assert (POD not in ctl.expected) == entry_gone, (expected, actual)
        assert (api.pod(POD) is None) == pod_gone, (expected, actual)

    def test_all_cells_covered(self):
        assert len(TABLE) == len(E) * len(A) == 35

    def test_missing_deleting_old_timestamp_hard_kills(self):
        """(MISSING, DELETING) past the deadline escalates to a grace-0
        hard kill (reference: kill-pod-hard)."""
        api, ctl, rec = setup_cell(E.MISSING, A.DELETING, sticky=True,
                                   old_deletion=True, clock_ms=0)
        ctl.process(POD)
        assert api.pod(POD) is None  # grace-0 bypasses sticky deletion
        assert rec.calls == []

    def test_killed_missing_opportunistic_kill(self):
        """(KILLED, MISSING) uses the saved launch pod to issue the kill
        even though the watch never showed the pod (controller.clj
        :launch-pod race)."""
        api, ctl, rec = setup_cell(E.KILLED, A.MISSING, with_launch_pod=True)
        ctl.process(POD)
        assert rec.calls == [K_USER]

    @pytest.mark.parametrize("actual", [A.RUNNING, A.WAITING])
    def test_weird_kill_converges_on_delete_event(self, actual):
        """(COMPLETED, live) deletes the pod; the watch DELETED event then
        drives (COMPLETED, MISSING) -> forgotten."""
        api, ctl, rec = setup_cell(E.COMPLETED, actual, sticky=False)
        ctl.process(POD)
        assert api.pod(POD) is None
        ctl.pod_deleted(POD)  # what the watch layer does on DELETED
        assert POD not in ctl.expected
        assert rec.calls == []

    def test_starting_waiting_is_stable_under_rescan(self):
        api, ctl, rec = setup_cell(E.STARTING, A.WAITING)
        for _ in range(3):
            ctl.process(POD)
        assert rec.calls == []
        assert ctl.expected[POD].state is E.STARTING
