"""User/pool gauge sweeper tests (reference behaviors:
set-stats-counters! monitor.clj:35-207)."""

from cook_tpu.sched.monitor import Monitor
from cook_tpu.state import InstanceStatus, Job, Pool, Resources, Store
from cook_tpu.utils.metrics import MetricsRegistry


def make_store() -> Store:
    store = Store()
    store.put_pool(Pool(name="default"))
    return store


def make_job(uuid, user, cpus=1.0, mem=100.0):
    return Job(uuid=uuid, user=user, command="x",
               resources=Resources(cpus=cpus, mem=mem))


def run_job(store, uuid, host="h0"):
    store.launch_instance(uuid, f"task-{uuid}", host)
    store.update_instance_status(f"task-{uuid}", InstanceStatus.RUNNING)


class TestMonitorSweep:
    def test_user_classification(self):
        store = make_store()
        # alice: running 4 cpus, share 2 -> not starved (over share), waiting
        store.create_jobs([make_job("a1", "alice", cpus=4),
                           make_job("a2", "alice", cpus=1)])
        run_job(store, "a1")
        store.set_share("alice", "default", {"cpus": 2.0, "mem": 1e9})
        # bob: waiting only, share large -> starved
        store.create_jobs([make_job("b1", "bob", cpus=1)])
        store.set_share("bob", "default", {"cpus": 10.0, "mem": 1e9})
        # carol: running only -> satisfied
        store.create_jobs([make_job("c1", "carol", cpus=1)])
        run_job(store, "c1")
        registry = MetricsRegistry()
        counts = Monitor(store, registry).sweep()["default"]
        assert counts["total"] == 3
        assert counts["starved"] == 1          # bob
        assert counts["hungry"] == 1           # alice (waiting, not starved)
        assert counts["satisfied"] == 1        # carol
        assert counts["waiting_under_quota"] == 2  # alice + bob (inf quota)

    def test_starvation_amount_capped_by_share_gap(self):
        store = make_store()
        store.create_jobs([make_job("r1", "dave", cpus=2),
                           make_job("w1", "dave", cpus=8)])
        run_job(store, "r1")
        store.set_share("dave", "default", {"cpus": 5.0, "mem": 1e9})
        from cook_tpu.sched.monitor import compute_starved_stats
        running = {"dave": {"cpus": 2.0, "mem": 100.0, "jobs": 1.0}}
        waiting = {"dave": {"cpus": 8.0, "mem": 100.0, "jobs": 1.0}}
        starved = compute_starved_stats(store, "default", running, waiting)
        # starvation = min(waiting 8, share 5 - running 2) = 3
        assert starved["dave"]["cpus"] == 3.0

    def test_waiting_under_quota_respects_count(self):
        store = make_store()
        store.create_jobs([make_job("q1", "erin"), make_job("q2", "erin")])
        run_job(store, "q1")
        # count quota 1, already running 1 -> NOT under quota
        store.set_quota("erin", "default", {"cpus": 100.0, "mem": 1e9},
                        count=1)
        registry = MetricsRegistry()
        counts = Monitor(store, registry).sweep()["default"]
        assert counts["waiting_under_quota"] == 0

    def test_gauges_published_and_stale_zeroed(self):
        store = make_store()
        store.create_jobs([make_job("g1", "frank")])
        registry = MetricsRegistry()
        monitor = Monitor(store, registry)
        monitor.sweep()
        text = registry.expose()
        assert 'cook_user_resource' in text
        assert 'user="frank"' in text and 'user="all"' in text
        assert 'cook_user_state_count' in text
        # frank's job completes; his waiting series must drop to zero
        store.kill_job("g1")
        monitor.sweep()
        snap = registry.snapshot()
        gauges = snap.get("gauges", snap)
        found = [
            (k, v) for k, v in _flatten(gauges)
            if "cook_user_resource" in str(k) and "frank" in str(k)
            and "waiting" in str(k) and "cpus" in str(k)]
        assert found and all(v == 0.0 for _k, v in found)


def _flatten(obj, prefix=()):
    if isinstance(obj, dict):
        for k, v in obj.items():
            yield from _flatten(v, prefix + (k,))
    else:
        yield prefix, obj


class TestStorageSweep:
    """The monitor's storage-integrity sweep (docs/ROBUSTNESS.md
    "WAL v2"): one incremental scrub step per journal shard at the
    configured cadence, verified frontier published as
    cook_storage_scrub_offset_bytes."""

    def _journaled(self, tmp_path):
        from cook_tpu.state.store import Store as DurableStore
        store = DurableStore.open(str(tmp_path / "s"))
        store.put_pool(Pool(name="default"))
        store.create_jobs([make_job("s1", "alice")])
        run_job(store, "s1")
        return store

    def test_sweep_advances_the_scrub_frontier(self, tmp_path):
        from cook_tpu.config import Config
        cfg = Config()
        cfg.storage.scrub_interval_seconds = 0.0
        store = self._journaled(tmp_path)
        registry = MetricsRegistry()
        monitor = Monitor(store, registry, config=cfg)
        monitor.sweep()
        assert "cook_storage_scrub_offset_bytes" in registry.expose()
        assert store.storage_stats()["scrub_verified_offset"] \
            == store.storage_stats()["journal_bytes"]
        store.close()

    def test_cadence_gate_and_disable_switch(self, tmp_path):
        from cook_tpu.config import Config
        store = self._journaled(tmp_path)
        # a long interval: the first sweep scrubs, the second is gated
        cfg = Config()
        cfg.storage.scrub_interval_seconds = 3600.0
        monitor = Monitor(store, MetricsRegistry(), config=cfg)
        monitor.sweep()
        first = store.storage_stats()["scrub_verified_offset"]
        store.create_jobs([make_job("s2", "alice")])
        monitor.sweep()  # within the interval: no second step
        assert store.storage_stats()["scrub_verified_offset"] == first
        # disabled: the sweep never scrubs at all
        off = Config()
        off.storage.scrub_enabled = False
        off.storage.scrub_interval_seconds = 0.0
        monitor2 = Monitor(store, MetricsRegistry(), config=off)
        monitor2.sweep()
        assert store.storage_stats()["scrub_verified_offset"] == first
        store.close()
