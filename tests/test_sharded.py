"""Multi-controller scale-out (ISSUE 19; sched/shard.py,
parallel/mesh.py shard alignment, state/partition.py summary exchange
peers, sim/chaos.py process-kill leg; docs/DEPLOY.md "sharded
controllers").

The contract under test:

* ALIGNMENT: PartitionMap pool groups and the mesh pool-sharding layout
  are the SAME partition — `validate_shard_alignment` derives each
  shard's pool block, and any operator-declared layout that disagrees
  (or doesn't divide) is a clear config error at daemon boot;
* SHARD TELEMETRY: a shard worker's CycleRecords carry its shard id,
  `/debug/cycles` rolls sharded records into a per-shard `by_shard`
  summary, and every shard's span ring stitches into ONE Perfetto
  export as distinct process tracks;
* CROSS-PROCESS PARITY: a fixed-seed world driven through 1-process and
  N-process topologies produces bit-identical launched sets — the
  per-pool decision path makes sharding by pool decision-preserving;
* BOUNDED GLOBAL STATE: cross-shard per-user totals ride the
  UserSummaryExchange peer feed with the staleness bound ASSERTED —
  a dead peer makes the bound trip, it never silently serves stale;
* FAILOVER: a REAL SIGKILL of one partition's shard worker process
  promotes its synced standby via the candidate ranking while sibling
  shard processes keep committing — zero committed-write loss
  (`sim --chaos-failover --partitions N`).
"""

import json
import time
import urllib.request

import pytest

from cook_tpu.parallel.mesh import (ShardAlignmentError, shard_of_partition,
                                    validate_shard_alignment)
from cook_tpu.state.partition import (PartitionMap, SummaryStalenessError,
                                      UserSummaryExchange)

pytestmark = pytest.mark.sharded

WORLD = {"n_jobs": 24, "n_users": 3, "hosts_per_pool": 3, "seed": 3}
#: the no-jax worker config: split cycle + cpu rank boots in well under
#: a second per process, and the decision path is the same per-pool
#: rank/match the parity contract covers
CPU_CFG = {"backend": "cpu", "rank_backend": "cpu", "cycle_mode": "split"}
POOLS = ["pool0", "pool1", "pool2", "pool3"]


# ---------------------------------------------------------------------------
# alignment: partition groups == mesh shard layout, or a boot error
# ---------------------------------------------------------------------------

class TestShardAlignment:
    def test_contiguous_blocks(self):
        assert [shard_of_partition(p, 8, 2) for p in range(8)] == \
            [0, 0, 0, 0, 1, 1, 1, 1]
        assert [shard_of_partition(p, 4, 4) for p in range(4)] == \
            [0, 1, 2, 3]

    def test_derived_layout_and_declared_agreement(self):
        pmap = PartitionMap(count=4, pools={f"pool{i}": i
                                            for i in range(4)})
        layout = validate_shard_alignment(pmap, 2)
        assert layout == {0: ["pool0", "pool1"], 1: ["pool2", "pool3"]}
        # declaring the SAME layout explicitly is accepted
        assert validate_shard_alignment(
            pmap, 2, {"pool0": 0, "pool1": 0, "pool2": 1, "pool3": 1})

    def test_mismatched_declaration_is_config_error(self):
        pmap = PartitionMap(count=4, pools={f"pool{i}": i
                                            for i in range(4)})
        with pytest.raises(ShardAlignmentError) as ei:
            validate_shard_alignment(pmap, 2, {"pool1": 1})
        msg = str(ei.value)
        assert "pool1" in msg and "shard" in msg

    def test_indivisible_partition_count_refused(self):
        pmap = PartitionMap(count=3, pools={f"pool{i}": i
                                            for i in range(3)})
        with pytest.raises(ShardAlignmentError):
            validate_shard_alignment(pmap, 2)

    def test_declared_shard_out_of_range(self):
        pmap = PartitionMap(count=4, pools={f"pool{i}": i
                                            for i in range(4)})
        with pytest.raises(ShardAlignmentError):
            validate_shard_alignment(pmap, 2, {"pool0": 2})

    def test_partition_config_validates_shards(self):
        from cook_tpu.config import PartitionConfig
        PartitionConfig(count=4, pools={"a": 0}, shards=2,
                        shard_pools={"a": 0})
        with pytest.raises(ValueError):
            PartitionConfig(count=3, pools={"a": 0}, shards=2)
        with pytest.raises(ValueError):
            PartitionConfig(count=4, pools={"a": 0}, shards=2,
                            shard_pools={"a": 5})
        with pytest.raises(ValueError):
            # shard_pools without shards has nothing to validate against
            PartitionConfig(count=4, pools={"a": 0},
                            shard_pools={"a": 0})

    def test_daemon_boot_rejects_misaligned_layout(self):
        """The satellite-1 cross-check: a daemon conf whose declared
        shard_pools disagree with the PartitionMap's derived owner must
        die with the alignment error AT BOOT, before any plane starts."""
        from cook_tpu.daemon import CookDaemon
        conf = {"port": 0,
                "scheduler": {"partitions": {
                    "count": 4,
                    "pools": {f"pool{i}": i for i in range(4)},
                    "shards": 2,
                    # pool3 lives on partition 3 -> shard 1; declaring 0
                    # splits the write plane from the mesh shard
                    "shard_pools": {"pool3": 0}}}}
        daemon = CookDaemon(conf)
        with pytest.raises(ShardAlignmentError) as ei:
            daemon.start()
        assert "pool3" in str(ei.value)

    def test_daemon_boot_accepts_aligned_layout(self):
        from cook_tpu.daemon import CookDaemon
        conf = {"port": 0,
                "scheduler": {"partitions": {
                    "count": 4,
                    "pools": {f"pool{i}": i for i in range(4)},
                    "shards": 2,
                    "shard_pools": {"pool0": 0, "pool3": 1}}}}
        daemon = CookDaemon(conf)
        try:
            daemon.start()
        finally:
            daemon.shutdown()


# ---------------------------------------------------------------------------
# shard telemetry: CycleRecord.shard + by_shard roll-up + /debug/cycles
# ---------------------------------------------------------------------------

class TestShardTelemetry:
    def test_cycle_record_carries_shard(self):
        from cook_tpu.utils import flight
        flight.set_shard(3)
        try:
            rec = flight.CycleRecord(1, "fused")
            assert rec.shard == 3
            assert rec.to_doc()["shard"] == 3
        finally:
            flight.set_shard(None)
        assert flight.CycleRecord(2, "fused").shard is None

    def test_summary_by_shard_rollup(self):
        from cook_tpu.utils.flight import FlightRecorder, set_shard
        rec = FlightRecorder()
        try:
            for shard in (0, 0, 1):
                set_shard(shard)
                with rec.cycle("fused"):
                    pass
        finally:
            set_shard(None)
        by_shard = rec.summary()["by_shard"]
        assert set(by_shard) == {"0", "1"}
        assert by_shard["0"]["cycles"] == 2
        assert by_shard["1"]["cycles"] == 1
        assert by_shard["1"]["cycle_ms_p50"] >= 0.0
        assert by_shard["1"]["cycle_ms_p99"] >= by_shard["1"]["cycle_ms_p50"]

    def test_unsharded_summary_has_no_by_shard(self):
        from cook_tpu.utils.flight import FlightRecorder
        rec = FlightRecorder()
        with rec.cycle("fused"):
            pass
        assert "by_shard" not in rec.summary()

    def test_debug_cycles_endpoint_rolls_up(self):
        from cook_tpu.rest.api import ApiServer, CookApi
        from cook_tpu.state import Store
        from cook_tpu.utils import flight
        flight.set_shard(2)
        try:
            with flight.recorder.cycle("fused"):
                pass
            server = ApiServer(CookApi(Store()))
            server.start()
            try:
                body = json.load(urllib.request.urlopen(
                    server.url + "/debug/cycles?limit=5"))
            finally:
                server.stop()
        finally:
            flight.set_shard(None)
        assert "2" in body["by_shard"]
        assert body["cycles"][-1]["shard"] == 2


# ---------------------------------------------------------------------------
# summary exchange: peer feed + asserted staleness bound (no processes)
# ---------------------------------------------------------------------------

class TestPeerSummaryExchange:
    _uid = 0

    def _store(self, user_jobs):
        from cook_tpu.state import Job, Pool, Resources, Store
        store = Store()
        store.put_pool(Pool(name="default"))
        for user, n in user_jobs.items():
            for _ in range(n):
                TestPeerSummaryExchange._uid += 1
                store.create_jobs([Job(
                    uuid=f"00000000-0000-4000-8000-"
                         f"{TestPeerSummaryExchange._uid:012d}",
                    user=user, command="true",
                    resources=Resources(cpus=1, mem=64))])
        return store

    def test_peer_tables_merge_into_totals(self):
        store = self._store({"alice": 2})
        peer_table = {"alice": {"pending": 3.0, "running": 1.0}}
        ex = UserSummaryExchange([store], max_age_s=5.0,
                                 peer_fetch=lambda: [(peer_table, 0.0)])
        totals = ex.user_totals("alice")
        assert totals["pending"] == 5.0
        assert totals["running"] == 1.0
        assert ex.stats()["peer_tables"] == 1

    def test_peer_age_backdates_freshness(self):
        store = self._store({"alice": 1})
        ex = UserSummaryExchange([store], max_age_s=0.5,
                                 peer_fetch=lambda: [({}, 10.0)],
                                 assert_bound=True)
        with pytest.raises(SummaryStalenessError):
            ex.user_totals("alice")

    def test_bound_not_asserted_by_default(self):
        store = self._store({"alice": 1})
        ex = UserSummaryExchange([store], max_age_s=0.5,
                                 peer_fetch=lambda: [({}, 10.0)])
        assert ex.user_totals("alice")["pending"] == 1.0
        assert ex.staleness_s() >= 10.0

    def test_fresh_peers_keep_bound(self):
        store = self._store({"alice": 1})
        ex = UserSummaryExchange([store], max_age_s=0.5,
                                 peer_fetch=lambda: [({}, 0.0)],
                                 assert_bound=True)
        assert ex.user_totals("alice")["pending"] == 1.0


# ---------------------------------------------------------------------------
# cross-process topologies (real shard worker processes)
# ---------------------------------------------------------------------------

def _drive(sup, cycles=3):
    sup.broadcast({"cmd": "cycle", "n": cycles}, timeout_s=120)
    return sup.collect_decisions()


class TestShardedTopology:
    # One shared 2-process topology for the whole class: worker boots
    # dominate these tests' wall time, and every probe except the parity
    # baseline reads the same topology.  The dead-peer test kills shard 1
    # and therefore MUST stay last in definition order.
    @pytest.fixture(scope="class")
    def topo(self, tmp_path_factory):
        from cook_tpu.sched.shard import sched_topology
        sup = sched_topology(2, POOLS, WORLD, cfg=CPU_CFG,
                             summary_max_age_s=0.4,
                             root=str(tmp_path_factory.mktemp("topo2")))
        yield sup
        sup.stop()

    def test_workers_own_disjoint_pool_blocks(self, topo):
        from cook_tpu.sched.shard import shard_pools
        assert shard_pools(POOLS, 0, 2) == ["pool0", "pool1"]
        assert shard_pools(POOLS, 1, 2) == ["pool2", "pool3"]
        assert topo.procs[0].addr["pools"] == ["pool0", "pool1"]
        assert topo.procs[1].addr["pools"] == ["pool2", "pool3"]

    def test_parity_one_vs_two_processes(self, topo, tmp_path):
        """The tentpole parity contract: the SAME fixed-seed world
        through a single process and through 2 shard processes launches
        the bit-identical job set (states + sorted hostnames), extending
        the test_megakernel parity matrix across process boundaries."""
        from cook_tpu.sched.shard import sched_topology
        sup1 = sched_topology(1, POOLS, WORLD, cfg=CPU_CFG,
                              root=str(tmp_path / "topo1"))
        try:
            got1 = _drive(sup1)
        finally:
            sup1.stop()
        got2 = _drive(topo)
        assert len(got1) == WORLD["n_jobs"]
        assert any(h for _s, h in got1.values()), "nothing launched"
        assert got2 == got1

    def test_flight_and_trace_stitch_across_shards(self, topo):
        _drive(topo, cycles=2)
        flight = topo.collect_flight()
        assert set(flight) == {0, 1}
        for shard, summary in flight.items():
            assert set(summary["by_shard"]) == {str(shard)}
            assert summary["by_shard"][str(shard)]["cycles"] >= 2
        trace = topo.collect_trace("test-stitch")
        pids = {ev["pid"] for ev in trace["traceEvents"]}
        names = {ev["args"]["name"]
                 for ev in trace["traceEvents"]
                 if ev.get("ph") == "M"
                 and ev.get("name") == "process_name"}
        assert len(pids) == 2
        assert {"shard-0", "shard-1"} <= names
        members = trace["otherData"]["members"]
        assert all(m["ok"] and m["spans"] > 0 for m in members)

    def test_cross_shard_user_totals_and_dead_peer_staleness(self, topo):
        local = [topo.rpc(i, {"cmd": "summary"})["users"]
                 for i in (0, 1)]
        want = sum(local[i].get("user0", {}).get("pending", 0.0)
                   + local[i].get("user0", {}).get("running", 0.0)
                   for i in (0, 1))
        resp = topo.rpc(0, {"cmd": "user_totals", "user": "user0"})
        got = (resp["totals"]["pending"]
               + resp["totals"]["running"])
        assert got == pytest.approx(want)
        assert resp["staleness_s"] <= 0.4
        # kill the peer: shard 0's asserted bound must TRIP once the
        # cached table ages past max_age_s — never silently stale
        topo.kill(1)
        deadline = time.monotonic() + 10.0
        stale = None
        while time.monotonic() < deadline:
            resp = topo.rpc(0, {"cmd": "user_totals", "user": "user0"})
            if "stale" in resp:
                stale = resp["stale"]
                break
            time.sleep(0.1)
        assert stale is not None, "staleness bound never tripped"
        assert "max_age" in stale or "stale" in stale.lower()


# ---------------------------------------------------------------------------
# process-kill failover (the chaos leg, tier-1 smoke + slow soak)
# ---------------------------------------------------------------------------

@pytest.mark.chaos
class TestProcessKillFailover:
    def test_sigkill_failover_smoke(self, tmp_path):
        """Tier-1 smoke of `sim --chaos-failover --partitions 2` with a
        REAL SIGKILL: victim's standby promotes via candidate ranking,
        siblings never stall, zero committed-write loss."""
        from cook_tpu.sim.chaos import (PartitionChaosConfig,
                                        run_partition_chaos_procs)
        res = run_partition_chaos_procs(PartitionChaosConfig(
            partitions=2, jobs_before=2, writers=2,
            sibling_stream_s=0.8, data_root=str(tmp_path)))
        assert res.ok, res.violations
        assert res.process_kill is True
        assert res.promoted_epoch == 2
        assert res.victim_indeterminate >= 1
        assert res.sibling_errors == 0
        assert res.sibling_commits_during_promotion >= 1
        assert res.summary()["process_kill"] is True

    @pytest.mark.slow
    def test_sigkill_failover_soak_four_partitions(self, tmp_path):
        from cook_tpu.sim.chaos import (PartitionChaosConfig,
                                        run_partition_chaos_procs)
        res = run_partition_chaos_procs(PartitionChaosConfig(
            partitions=4, victim=1, data_root=str(tmp_path)))
        assert res.ok, res.violations
        assert res.committed >= 4 * res.partitions
