"""Policy-layer tests: rate limiting, queue limits, plugins."""

import time

import pytest

from cook_tpu.cluster import FakeCluster, FakeHost
from cook_tpu.config import Config
from cook_tpu.policy import (
    JobLaunchFilter,
    JobSubmissionModifier,
    JobSubmissionValidator,
    PluginRegistry,
    PluginResult,
    QueueLimits,
    RateLimits,
    TokenBucketRateLimiter,
    pool_user_key,
)
from cook_tpu.sched import Scheduler
from cook_tpu.state import InstanceStatus, Job, JobState, Resources, Store, new_uuid


def make_job(user="alice", pool="default", **kw):
    kw.setdefault("resources", Resources(cpus=1, mem=100))
    return Job(uuid=new_uuid(), user=user, pool=pool, command="x", **kw)


class TestTokenBucket:
    def test_spend_and_replenish(self):
        now = [0.0]
        rl = TokenBucketRateLimiter(tokens_per_minute=60, bucket_size=5,
                                    clock=lambda: now[0])
        assert rl.get_token_count("u") == 5
        for _ in range(5):
            rl.spend("u")
        assert rl.get_token_count("u") == 0
        assert not rl.within_limit("u")
        now[0] += 2.0  # 2 seconds -> 2 tokens
        assert rl.get_token_count("u") == pytest.approx(2.0)
        assert rl.within_limit("u")

    def test_debt_and_time_until_out(self):
        now = [0.0]
        rl = TokenBucketRateLimiter(tokens_per_minute=60, bucket_size=2,
                                    clock=lambda: now[0])
        rl.spend("u", 5)  # 3 tokens of debt
        assert rl.time_until_out_of_debt_s("u") == pytest.approx(3.0)

    def test_bucket_caps_at_size(self):
        now = [0.0]
        rl = TokenBucketRateLimiter(tokens_per_minute=60, bucket_size=3,
                                    clock=lambda: now[0])
        now[0] += 1000
        assert rl.get_token_count("u") == 3

    def test_enforce_off(self):
        rl = TokenBucketRateLimiter(1, 1, enforce=False)
        rl.spend("u", 100)
        assert rl.within_limit("u")


class TestLaunchRateLimitIntegration:
    def test_launch_rate_limits_users_per_cycle(self):
        now = [0.0]
        store = Store()
        cluster = FakeCluster("c", [FakeHost(f"h{i}", Resources(cpus=8, mem=8192))
                                    for i in range(4)])
        rl = RateLimits(job_launch=TokenBucketRateLimiter(
            tokens_per_minute=0.0001, bucket_size=2, clock=lambda: now[0]))
        cfg = Config()
        cfg.default_matcher.backend = "cpu"
        sched = Scheduler(store, cfg, [cluster], rank_backend="cpu",
                          rate_limits=rl)
        store.create_jobs([make_job() for _ in range(6)])
        sched.step_rank()
        res = sched.step_match()["default"]
        assert len(res.launched_task_ids) == 2  # bucket size caps the cycle
        sched.step_rank()
        res = sched.step_match()["default"]
        assert len(res.launched_task_ids) == 0  # tokens spent, none earned

    def test_cluster_launch_rate_limit(self):
        store = Store()
        cluster = FakeCluster("c", [FakeHost(f"h{i}", Resources(cpus=8, mem=8192))
                                    for i in range(4)])
        rl = RateLimits(cluster_launch=TokenBucketRateLimiter(
            tokens_per_minute=0.0001, bucket_size=3))
        cfg = Config()
        cfg.default_matcher.backend = "cpu"
        sched = Scheduler(store, cfg, [cluster], rank_backend="cpu",
                          rate_limits=rl)
        store.create_jobs([make_job(user=f"u{i}") for i in range(6)])
        sched.step_rank()
        res = sched.step_match()["default"]
        assert len(res.launched_task_ids) == 3


class TestDirectModeRateLimit:
    def test_direct_pool_spends_launch_tokens(self):
        from cook_tpu.state import Pool, SchedulerKind
        store = Store()
        hosts = [FakeHost(f"h{i}", Resources(cpus=8, mem=8192), pool="direct")
                 for i in range(4)]
        cluster = FakeCluster("c", hosts)
        rl = RateLimits(job_launch=TokenBucketRateLimiter(
            tokens_per_minute=0.0001, bucket_size=2))
        store.put_pool(Pool(name="direct", scheduler=SchedulerKind.DIRECT))
        cfg = Config()
        cfg.default_matcher.backend = "cpu"
        sched = Scheduler(store, cfg, [cluster], rank_backend="cpu",
                          rate_limits=rl)
        store.create_jobs([make_job(pool="direct") for _ in range(6)])
        sched.step_rank()
        res = sched.step_match("direct")["direct"]
        assert len(res.launched_task_ids) == 2
        sched.step_rank()
        res = sched.step_match("direct")["direct"]
        assert len(res.launched_task_ids) == 0  # tokens spent


class TestQueueLimits:
    def test_per_user_cap(self):
        store = Store()
        ql = QueueLimits(store, per_user_limit=2)
        store.create_jobs([make_job(), make_job()])
        assert ql.check_submission("default", "alice", 1) is not None
        assert ql.check_submission("default", "bob", 2) is None

    def test_per_pool_cap(self):
        store = Store()
        ql = QueueLimits(store, per_pool_limit=3)
        store.create_jobs([make_job(user=f"u{i}") for i in range(3)])
        assert ql.check_submission("default", "x", 1) is not None
        assert ql.check_submission("other", "x", 3) is None

    def test_counts_track_state_transitions(self):
        store = Store()
        ql = QueueLimits(store, per_user_limit=10)
        [uuid] = store.create_jobs([make_job()])
        assert ql.counts()["pools"]["default"] == 1
        store.launch_instance(uuid, "t1", "h1")
        assert ql.counts()["pools"]["default"] == 0
        store.update_instance_status("t1", InstanceStatus.FAILED, reason_code=7)
        assert ql.counts()["pools"]["default"] == 1  # mea-culpa requeue

    def test_user_override(self):
        store = Store()
        ql = QueueLimits(store, per_user_limit=100,
                         user_overrides={"greedy": 1})
        store.create_jobs([make_job(user="greedy")])
        assert ql.check_submission("default", "greedy", 1) is not None


class RejectBigJobs(JobSubmissionValidator):
    def validate(self, job):
        if job.resources.cpus > 8:
            return PluginResult.rejected("too big")
        return PluginResult.accepted()


class AddLabel(JobSubmissionModifier):
    def modify(self, job):
        job.labels["injected"] = "yes"
        return job


class DeferAll(JobLaunchFilter):
    calls = 0

    def check(self, job):
        DeferAll.calls += 1
        return PluginResult.deferred("not yet", ttl_s=1000)


class TestPlugins:
    def test_submission_validator(self):
        reg = PluginRegistry(validators=[RejectBigJobs()])
        assert reg.validate_submission(
            make_job(resources=Resources(cpus=16, mem=10))) == "too big"
        assert reg.validate_submission(make_job()) is None

    def test_submission_modifier(self):
        reg = PluginRegistry(modifiers=[AddLabel()])
        job = reg.modify_submission(make_job())
        assert job.labels["injected"] == "yes"

    def test_launch_filter_defers_and_caches(self):
        DeferAll.calls = 0
        store = Store()
        cluster = FakeCluster("c", [FakeHost("h0", Resources(cpus=8, mem=8192))])
        reg = PluginRegistry(launch_filters=[DeferAll()])
        cfg = Config()
        cfg.default_matcher.backend = "cpu"
        sched = Scheduler(store, cfg, [cluster], rank_backend="cpu",
                          plugins=reg)
        store.create_jobs([make_job()])
        sched.step_rank()
        assert sched.step_match()["default"].launched_task_ids == []
        sched.step_rank()
        sched.step_match()
        assert DeferAll.calls == 1  # second cycle hit the verdict cache

    def test_completion_handler_fires(self):
        seen = []

        class Handler:
            def on_completion(self, job, instance):
                seen.append((job.uuid, instance.task_id, instance.status))

        store = Store()
        cluster = FakeCluster("c", [FakeHost("h0", Resources(cpus=8, mem=8192))])
        reg = PluginRegistry(completion_handlers=[Handler()])
        cfg = Config()
        cfg.default_matcher.backend = "cpu"
        sched = Scheduler(store, cfg, [cluster], rank_backend="cpu",
                          plugins=reg)
        [uuid] = store.create_jobs([make_job()])
        sched.step_rank()
        [tid] = sched.step_match()["default"].launched_task_ids
        cluster.complete_task(tid)
        assert seen and seen[0][0] == uuid

    def test_registry_from_config(self):
        reg = PluginRegistry.from_config({
            "validators": ["tests.test_policy.RejectBigJobs"],
            "modifiers": ["tests.test_policy.AddLabel"],
        })
        assert len(reg.validators) == 1
        assert len(reg.modifiers) == 1


class TestPoolMover:
    def test_moves_portion_of_user_jobs(self):
        from cook_tpu.policy.plugins import PoolMoverPlugin
        from cook_tpu.state.schema import Job, Resources, new_uuid

        mover = PoolMoverPlugin({"alpha": {
            "destination": "beta", "users": {"alice": 0.5, "bob": 0.0}}})
        moved = unmoved = 0
        for _ in range(400):
            job = Job(uuid=new_uuid(), user="alice", command="x",
                      pool="alpha", resources=Resources(cpus=1, mem=1))
            job = mover.modify(job)
            if job.pool == "beta":
                moved += 1
            else:
                unmoved += 1
        # ~50% portion; generous bounds
        assert 100 < moved < 300, (moved, unmoved)
        # portion 0 user never moves; other pools untouched
        for user, pool in (("bob", "alpha"), ("alice", "gamma")):
            job = Job(uuid=new_uuid(), user=user, command="x", pool=pool,
                      resources=Resources(cpus=1, mem=1))
            assert mover.modify(job).pool == pool

    def test_deterministic_per_uuid(self):
        from cook_tpu.policy.plugins import PoolMoverPlugin
        from cook_tpu.state.schema import Job, Resources

        mover = PoolMoverPlugin({"alpha": {
            "destination": "beta", "users": {"alice": 0.5}}})
        job1 = Job(uuid="11111111-1111-1111-1111-111111111111", user="alice",
                   command="x", pool="alpha", resources=Resources(cpus=1, mem=1))
        job2 = Job(uuid="11111111-1111-1111-1111-111111111111", user="alice",
                   command="x", pool="alpha", resources=Resources(cpus=1, mem=1))
        assert mover.modify(job1).pool == mover.modify(job2).pool

    def test_from_config_with_kwargs(self):
        from cook_tpu.policy.plugins import PluginRegistry, PoolMoverPlugin
        reg = PluginRegistry.from_config({"modifiers": [
            {"factory": "cook_tpu.policy.plugins.PoolMoverPlugin",
             "kwargs": {"moves": {"alpha": {"destination": "beta",
                                            "users": {"alice": 1.0}}}}}]})
        [mover] = reg.modifiers
        assert isinstance(mover, PoolMoverPlugin)
        assert mover.moves["alpha"]["destination"] == "beta"

    def test_missing_destination_rejected_at_config_time(self):
        import pytest
        from cook_tpu.policy.plugins import PoolMoverPlugin
        with pytest.raises(ValueError, match="destination"):
            PoolMoverPlugin({"alpha": {"users": {"alice": 1.0}}})
