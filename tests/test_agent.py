"""On-node agent tests: executor lifecycle, progress tracking, kill
escalation, sandbox file server (reference test tier: executor/tests/)."""

import json
import time
import urllib.request

import pytest

from cook_tpu.agent import (
    ProgressWatcher,
    SandboxFileServer,
    TaskExecutor,
    rest_progress_publisher,
)


class TestProgressWatcher:
    def test_extracts_percent_and_message(self):
        seen = []
        w = ProgressWatcher(publish=lambda s, p, m: seen.append((s, p, m)))
        w.observe_line("progress: 25 loading data\n")
        w.observe_line("no progress here\n")
        w.observe_line("progress: 80% training\n")
        assert seen == [(1, 25, "loading data"), (2, 80, "training")]

    def test_clamps_out_of_range(self):
        w = ProgressWatcher()
        w.observe_line("progress: 150 overshoot")
        assert w.last_percent == 100

    def test_custom_regex(self):
        w = ProgressWatcher(regex=r"\[(\d+)/100\]")
        w.observe_line("step [42/100] done")
        assert w.last_percent == 42


class TestTaskExecutor:
    def test_runs_and_captures_output(self, tmp_path):
        ex = TaskExecutor("echo out-line; echo err-line >&2; exit 0",
                          sandbox=str(tmp_path / "sb"))
        ex.start()
        assert ex.wait(timeout_s=10) == 0
        assert (tmp_path / "sb" / "stdout").read_text() == "out-line\n"
        assert (tmp_path / "sb" / "stderr").read_text() == "err-line\n"
        assert (tmp_path / "sb" / "exit_code").read_text() == "0"

    def test_nonzero_exit(self, tmp_path):
        ex = TaskExecutor("exit 7", sandbox=str(tmp_path / "sb"))
        ex.start()
        assert ex.wait(timeout_s=10) == 7

    def test_progress_from_stdout(self, tmp_path):
        seen = []
        ex = TaskExecutor(
            "echo 'progress: 10 start'; echo 'progress: 90 almost'",
            sandbox=str(tmp_path / "sb"),
            progress_publish=lambda s, p, m: seen.append((p, m)))
        ex.start()
        ex.wait(timeout_s=10)
        assert (10, "start") in seen and (90, "almost") in seen

    def test_kill_escalation_sigterm_trapped(self, tmp_path):
        # the command traps SIGTERM; the executor must escalate to SIGKILL
        ex = TaskExecutor(
            "trap '' TERM; while true; do sleep 0.1; done",
            sandbox=str(tmp_path / "sb"), kill_grace_period_s=0.5)
        ex.start()
        time.sleep(0.3)
        assert ex.running
        t0 = time.time()
        code = ex.kill()
        assert not ex.running
        assert code != 0
        assert time.time() - t0 < 10

    def test_kill_takes_down_process_tree(self, tmp_path):
        # children in the same process group die with the parent
        ex = TaskExecutor("sleep 300 & sleep 300 & wait",
                          sandbox=str(tmp_path / "sb"),
                          kill_grace_period_s=0.5)
        ex.start()
        time.sleep(0.3)
        import os
        pgid = os.getpgid(ex.process.pid)
        ex.kill()
        # no live survivors in the group (zombies may linger until reaped)
        import subprocess
        deadline = time.time() + 5
        live = "unchecked"
        while time.time() < deadline:
            out = subprocess.run(["ps", "-o", "pid=,stat=", "-g", str(pgid)],
                                 capture_output=True, text=True)
            live = [line for line in out.stdout.splitlines()
                    if line.strip() and "Z" not in line.split()[1]]
            if not live:
                break
            time.sleep(0.1)
        assert not live, f"survivors: {live}"

    def test_progress_posted_to_rest_api(self, tmp_path):
        from cook_tpu.cluster import FakeCluster, FakeHost
        from cook_tpu.config import Config
        from cook_tpu.rest import ApiServer, CookApi
        from cook_tpu.sched import Scheduler
        from cook_tpu.state import Job, Resources, Store, new_uuid

        store = Store()
        cluster = FakeCluster("c", [FakeHost("h0", Resources(cpus=8, mem=8192))])
        cfg = Config()
        cfg.default_matcher.backend = "cpu"
        sched = Scheduler(store, cfg, [cluster], rank_backend="cpu")
        server = ApiServer(CookApi(store, scheduler=sched))
        server.start()
        try:
            [uuid] = store.create_jobs([Job(
                uuid=new_uuid(), user="u", command="x",
                resources=Resources(cpus=1, mem=10))])
            sched.step_rank()
            [tid] = sched.step_match()["default"].launched_task_ids
            ex = TaskExecutor(
                "echo 'progress: 55 crunching'",
                sandbox=str(tmp_path / "sb"),
                progress_publish=rest_progress_publisher(server.url, tid))
            ex.start()
            ex.wait(timeout_s=10)
            deadline = time.time() + 5
            while time.time() < deadline \
                    and store.instance(tid).progress != 55:
                time.sleep(0.05)
            inst = store.instance(tid)
            assert inst.progress == 55
            assert inst.progress_message == "crunching"
        finally:
            server.stop()


class TestSandboxFileServer:
    @pytest.fixture()
    def sandbox(self, tmp_path):
        (tmp_path / "stdout").write_text("hello sandbox\n")
        (tmp_path / "sub").mkdir()
        (tmp_path / "sub" / "data.txt").write_text("nested")
        (tmp_path / "secret-outside.txt").write_text("x")  # still inside tmp
        server = SandboxFileServer(str(tmp_path))
        server.start()
        yield tmp_path, server
        server.stop()

    def _get(self, url):
        with urllib.request.urlopen(url) as resp:
            return resp.status, resp.read()

    def test_read_with_offset(self, sandbox):
        _root, server = sandbox
        status, body = self._get(
            f"{server.url}/files/read?path=stdout&offset=6&length=7")
        assert status == 200
        assert json.loads(body)["data"] == "sandbox"

    def test_download(self, sandbox):
        _root, server = sandbox
        status, body = self._get(f"{server.url}/files/download?path=sub/data.txt")
        assert status == 200 and body == b"nested"

    def test_browse(self, sandbox):
        _root, server = sandbox
        status, body = self._get(f"{server.url}/files/browse?path=")
        entries = json.loads(body)
        names = {e["path"] for e in entries}
        assert "stdout" in names and "sub" in names
        assert all("size" in e and "mode" in e for e in entries)

    def test_path_traversal_rejected(self, sandbox):
        _root, server = sandbox
        import urllib.error
        with pytest.raises(urllib.error.HTTPError) as e:
            self._get(f"{server.url}/files/read?path=../../etc/passwd")
        assert e.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as e:
            self._get(f"{server.url}/files/read?path=%2Fetc%2Fpasswd")
        assert e.value.code == 404


class TestProgressFile:
    def test_explicit_progress_file_watched(self, tmp_path):
        """Per-job progress file (reference: :job/progress-output-file,
        progress.py watches the EXECUTOR_PROGRESS_OUTPUT_FILE location)."""
        from cook_tpu.agent.executor import TaskExecutor

        updates = []
        ex = TaskExecutor(
            'echo "progress: 25 quarter" > prog.txt; sleep 0.4; '
            'echo "progress: 75 three-quarters" >> prog.txt; sleep 0.3',
            sandbox=str(tmp_path / "sb"),
            progress_file="prog.txt",
            progress_publish=lambda seq, pct, msg: updates.append((pct, msg)))
        ex.start()
        assert ex.wait(timeout_s=10) == 0
        deadline = time.time() + 3
        while time.time() < deadline and len(updates) < 2:
            time.sleep(0.05)
        assert (25, "quarter") in updates
        assert (75, "three-quarters") in updates
