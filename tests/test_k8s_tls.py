"""Real TLS handshakes on the k8s wire (VERDICT r4 #4).

The reference's apiserver client is TLS everywhere
(scheduler/project.clj:152-156 pins an okhttp TLS stack;
kubernetes/api.clj:372-475 builds it from kubeconfig / service-account
material).  These tests put an ssl-wrapped MockApiServer behind
RealKubernetesApi and execute every cert path for real: CA verification
(file and inline base64 data), wrong-CA rejection, mTLS client
certificates required at the handshake, insecure-skip-tls-verify,
bearer-token 401s, token rotation over TLS, and the full
cluster-launches-a-pod flow over https.
"""

import base64
import json
import ssl
import time
import urllib.error

import pytest
import yaml

from cook_tpu.cluster.k8s.fake_api import FakeNode
from cook_tpu.cluster.k8s.mock_apiserver import MockApiServer
from cook_tpu.cluster.k8s.real_api import RealKubernetesApi
from cook_tpu.cluster.k8s.testcerts import generate_pki


@pytest.fixture(scope="module")
def pki(tmp_path_factory):
    return generate_pki(str(tmp_path_factory.mktemp("pki")))


def wait_for(pred, timeout=15.0, msg=""):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out: {msg}")


def write_kubeconfig(path, server, ca=None, ca_data=None, token=None,
                     client_cert=None, client_key=None, cert_data=None,
                     key_data=None, skip_verify=False):
    cluster = {"server": server}
    if ca:
        cluster["certificate-authority"] = ca
    if ca_data:
        cluster["certificate-authority-data"] = ca_data
    if skip_verify:
        cluster["insecure-skip-tls-verify"] = True
    user = {}
    if token:
        user["token"] = token
    if client_cert:
        user["client-certificate"] = client_cert
        user["client-key"] = client_key
    if cert_data:
        user["client-certificate-data"] = cert_data
        user["client-key-data"] = key_data
    cfg = {"apiVersion": "v1", "kind": "Config",
           "current-context": "test",
           "contexts": [{"name": "test",
                         "context": {"cluster": "c1", "user": "u1"}}],
           "clusters": [{"name": "c1", "cluster": cluster}],
           "users": [{"name": "u1", "user": user}]}
    path.write_text(yaml.safe_dump(cfg))
    return str(path)


def b64file(path):
    with open(path, "rb") as f:
        return base64.b64encode(f.read()).decode()


class TestServerVerification:
    def test_kubeconfig_ca_file_roundtrip(self, pki, tmp_path):
        mock = MockApiServer(tls_cert=pki.server_cert,
                             tls_key=pki.server_key).start()
        try:
            mock.fake.add_node(FakeNode(name="n1", cpus=4.0, mem=4096.0))
            kc = write_kubeconfig(tmp_path / "kc.yaml", mock.base_url,
                                  ca=pki.ca_cert)
            api = RealKubernetesApi(kubeconfig=kc, watch_timeout_s=5.0)
            nodes = api.nodes()
            assert [n.name for n in nodes] == ["n1"]
            assert mock.base_url.startswith("https://")
        finally:
            mock.close()

    def test_kubeconfig_inline_ca_data(self, pki, tmp_path):
        # base64 *-data fields exercise the materialize() temp-file path
        mock = MockApiServer(tls_cert=pki.server_cert,
                             tls_key=pki.server_key).start()
        try:
            mock.fake.add_node(FakeNode(name="n2", cpus=1.0, mem=512.0))
            kc = write_kubeconfig(tmp_path / "kc.yaml", mock.base_url,
                                  ca_data=b64file(pki.ca_cert))
            api = RealKubernetesApi(kubeconfig=kc, watch_timeout_s=5.0)
            assert [n.name for n in api.nodes()] == ["n2"]
        finally:
            mock.close()

    def test_wrong_ca_rejected(self, pki, tmp_path):
        mock = MockApiServer(tls_cert=pki.server_cert,
                             tls_key=pki.server_key).start()
        try:
            kc = write_kubeconfig(tmp_path / "kc.yaml", mock.base_url,
                                  ca=pki.wrong_ca_cert)
            api = RealKubernetesApi(kubeconfig=kc, watch_timeout_s=5.0)
            with pytest.raises((ssl.SSLError, urllib.error.URLError)):
                api.nodes()
        finally:
            mock.close()

    def test_insecure_skip_tls_verify(self, pki, tmp_path):
        # no CA at all, skip-verify set: the handshake must proceed
        mock = MockApiServer(tls_cert=pki.server_cert,
                             tls_key=pki.server_key).start()
        try:
            mock.fake.add_node(FakeNode(name="n3", cpus=1.0, mem=512.0))
            kc = write_kubeconfig(tmp_path / "kc.yaml", mock.base_url,
                                  skip_verify=True)
            api = RealKubernetesApi(kubeconfig=kc, watch_timeout_s=5.0)
            assert [n.name for n in api.nodes()] == ["n3"]
        finally:
            mock.close()

    def test_base_url_verify_tls_false(self, pki):
        # the base_url + verify_tls=False constructor path (no kubeconfig)
        mock = MockApiServer(tls_cert=pki.server_cert,
                             tls_key=pki.server_key).start()
        try:
            mock.fake.add_node(FakeNode(name="n4", cpus=1.0, mem=512.0))
            api = RealKubernetesApi(base_url=mock.base_url,
                                    verify_tls=False, watch_timeout_s=5.0)
            assert [n.name for n in api.nodes()] == ["n4"]
        finally:
            mock.close()


class TestClientIdentity:
    def test_mtls_client_certificate_accepted(self, pki, tmp_path):
        mock = MockApiServer(tls_cert=pki.server_cert,
                             tls_key=pki.server_key,
                             client_ca=pki.ca_cert).start()
        try:
            mock.fake.add_node(FakeNode(name="m1", cpus=1.0, mem=512.0))
            kc = write_kubeconfig(tmp_path / "kc.yaml", mock.base_url,
                                  ca=pki.ca_cert,
                                  client_cert=pki.client_cert,
                                  client_key=pki.client_key)
            api = RealKubernetesApi(kubeconfig=kc, watch_timeout_s=5.0)
            assert [n.name for n in api.nodes()] == ["m1"]
        finally:
            mock.close()

    def test_mtls_inline_cert_data(self, pki, tmp_path):
        mock = MockApiServer(tls_cert=pki.server_cert,
                             tls_key=pki.server_key,
                             client_ca=pki.ca_cert).start()
        try:
            mock.fake.add_node(FakeNode(name="m2", cpus=1.0, mem=512.0))
            kc = write_kubeconfig(tmp_path / "kc.yaml", mock.base_url,
                                  ca_data=b64file(pki.ca_cert),
                                  cert_data=b64file(pki.client_cert),
                                  key_data=b64file(pki.client_key))
            api = RealKubernetesApi(kubeconfig=kc, watch_timeout_s=5.0)
            assert [n.name for n in api.nodes()] == ["m2"]
        finally:
            mock.close()

    def test_missing_client_certificate_rejected_at_handshake(self, pki,
                                                              tmp_path):
        mock = MockApiServer(tls_cert=pki.server_cert,
                             tls_key=pki.server_key,
                             client_ca=pki.ca_cert).start()
        try:
            kc = write_kubeconfig(tmp_path / "kc.yaml", mock.base_url,
                                  ca=pki.ca_cert)  # CA only, no identity
            api = RealKubernetesApi(kubeconfig=kc, watch_timeout_s=5.0)
            with pytest.raises((ssl.SSLError, urllib.error.URLError,
                                ConnectionError, OSError)):
                api.nodes()
        finally:
            mock.close()


class TestBearerAuth:
    def test_token_enforced_over_tls(self, pki, tmp_path):
        mock = MockApiServer(tls_cert=pki.server_cert,
                             tls_key=pki.server_key,
                             bearer_token="sekrit").start()
        try:
            mock.fake.add_node(FakeNode(name="b1", cpus=1.0, mem=512.0))
            good = write_kubeconfig(tmp_path / "good.yaml", mock.base_url,
                                    ca=pki.ca_cert, token="sekrit")
            api = RealKubernetesApi(kubeconfig=good, watch_timeout_s=5.0)
            assert [n.name for n in api.nodes()] == ["b1"]
            bad = write_kubeconfig(tmp_path / "bad.yaml", mock.base_url,
                                   ca=pki.ca_cert, token="wrong")
            api2 = RealKubernetesApi(kubeconfig=bad, watch_timeout_s=5.0)
            from cook_tpu.cluster.k8s.real_api import ApiError
            with pytest.raises(ApiError) as e:
                api2.nodes()
            assert "401" in str(e.value)
        finally:
            mock.close()

    def test_in_cluster_service_account_over_tls(self, pki, tmp_path,
                                                 monkeypatch):
        """The in-cluster constructor branch: projected service-account
        dir (token + ca.crt) + KUBERNETES_SERVICE_* env — through a real
        handshake, with the rotating-token path armed."""
        from urllib.parse import urlparse
        mock = MockApiServer(tls_cert=pki.server_cert,
                             tls_key=pki.server_key,
                             bearer_token="sa-tok").start()
        try:
            mock.fake.add_node(FakeNode(name="s1", cpus=1.0, mem=512.0))
            sa = tmp_path / "sa"
            sa.mkdir()
            (sa / "token").write_text("sa-tok")
            import shutil
            shutil.copy(pki.ca_cert, sa / "ca.crt")
            u = urlparse(mock.base_url)
            monkeypatch.setenv("COOK_K8S_SA_DIR", str(sa))
            monkeypatch.setenv("KUBERNETES_SERVICE_HOST", u.hostname)
            monkeypatch.setenv("KUBERNETES_SERVICE_PORT", str(u.port))
            api = RealKubernetesApi(watch_timeout_s=5.0)
            assert api._token_path == str(sa / "token")
            assert [n.name for n in api.nodes()] == ["s1"]
            # the projected token rotates; the client re-reads it
            (sa / "token").write_text("sa-tok-2")
            mock.bearer_token = "sa-tok-2"
            api._token_checked = 0.0
            assert [n.name for n in api.nodes()] == ["s1"]
        finally:
            mock.close()

    def test_token_rotation_over_tls(self, pki, tmp_path):
        """Bound service-account tokens rotate (the kubelet refreshes the
        projected file); the client must pick up the fresh token and keep
        authenticating through REAL handshakes."""
        mock = MockApiServer(tls_cert=pki.server_cert,
                            tls_key=pki.server_key,
                            bearer_token="tok-1").start()
        try:
            mock.fake.add_node(FakeNode(name="r1", cpus=1.0, mem=512.0))
            kc = write_kubeconfig(tmp_path / "kc.yaml", mock.base_url,
                                  ca=pki.ca_cert, token="tok-1")
            api = RealKubernetesApi(kubeconfig=kc, watch_timeout_s=5.0)
            assert [n.name for n in api.nodes()] == ["r1"]
            token_file = tmp_path / "token"
            token_file.write_text("tok-2")
            api._token_path = str(token_file)
            mock.bearer_token = "tok-2"  # server-side rotation
            api._token_checked = 0.0     # force the re-read
            assert [n.name for n in api.nodes()] == ["r1"]
        finally:
            mock.close()


class TestFullBackendOverTls:
    def test_cluster_launches_pod_over_https(self, pki, tmp_path):
        """The complete store -> cluster -> POST pod -> watch -> status
        flow, over a verified mTLS connection."""
        from cook_tpu.cluster.base import LaunchSpec
        from cook_tpu.cluster.k8s.compute_cluster import KubernetesCluster
        from cook_tpu.state import InstanceStatus, Job, Resources, Store

        mock = MockApiServer(tls_cert=pki.server_cert,
                             tls_key=pki.server_key,
                             client_ca=pki.ca_cert,
                             bearer_token="sekrit").start()
        try:
            mock.fake.add_node(FakeNode(name="n1", cpus=8.0, mem=8192.0))
            kc = write_kubeconfig(tmp_path / "kc.yaml", mock.base_url,
                                  ca=pki.ca_cert, token="sekrit",
                                  client_cert=pki.client_cert,
                                  client_key=pki.client_key)
            api = RealKubernetesApi(kubeconfig=kc, watch_timeout_s=5.0)
            updates = []
            store = Store()
            store.create_jobs([Job(uuid="j1", user="alice",
                                   command="echo hi",
                                   resources=Resources(cpus=1.0,
                                                       mem=256.0))])
            cluster = KubernetesCluster("k8s-tls", api, store=store)
            cluster.initialize(lambda tid, status, reason, **kw:
                               updates.append((tid, status)))
            wait_for(lambda: len(cluster.pending_offers("default")) == 1,
                     msg="offer from watched node over TLS")
            cluster.launch_tasks("default", [LaunchSpec(
                task_id="t1", job_uuid="j1", hostname="", slave_id="",
                resources=Resources(cpus=1.0, mem=256.0),
                env={"COOK_COMMAND": "echo hi"})])
            wait_for(lambda: mock.fake.pod("t1") is not None,
                     msg="pod created over https")
            mock.fake.step()
            mock.fake.step()
            wait_for(lambda: any(s is InstanceStatus.RUNNING
                                 for _, s in updates),
                     msg="RUNNING update over TLS watch")
            mock.fake.finish_pod("t1", exit_code=0)
            wait_for(lambda: any(s is InstanceStatus.SUCCESS
                                 for _, s in updates),
                     msg="SUCCESS update over TLS watch")
            cluster.shutdown()
        finally:
            mock.close()
