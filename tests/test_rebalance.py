"""Rebalancer tests: kernel parity vs golden + end-to-end preemption cycle."""

import numpy as np
import pytest

import jax.numpy as jnp

from cook_tpu.cluster import FakeCluster, FakeHost
from cook_tpu.config import Config, RebalancerConfig
from cook_tpu.ops.padding import bucket, pad_to
from cook_tpu.ops.rebalance import RebalanceInputs, preemption_kernel
from cook_tpu.ops.reference_impl import preemption_decision
from cook_tpu.sched import Scheduler
from cook_tpu.state import (
    InstanceStatus,
    Job,
    JobState,
    Reasons,
    Resources,
    Store,
    new_uuid,
)

F32 = np.float32


def run_kernel(task_dru, task_res, task_host, eligible, spare, host_ok, demand):
    order = sorted(range(len(task_dru)),
                   key=lambda i: (task_host[i], -task_dru[i], i))
    task_dru = np.asarray(task_dru, dtype=F32)[order]
    task_res = np.asarray(task_res, dtype=F32)[order]
    task_host = np.asarray(task_host, dtype=np.int32)[order]
    eligible = np.asarray(eligible, dtype=bool)[order]
    host_start = np.ones(len(order), dtype=bool)
    host_start[1:] = task_host[1:] != task_host[:-1]
    T = bucket(len(order))
    out = preemption_kernel(RebalanceInputs(
        task_dru=jnp.asarray(pad_to(task_dru, T)),
        task_res=jnp.asarray(pad_to(task_res, T)),
        task_host=jnp.asarray(pad_to(task_host, T)),
        host_start=jnp.asarray(pad_to(host_start, T, fill=True)),
        eligible=jnp.asarray(pad_to(eligible, T, fill=False)),
        spare=jnp.asarray(np.asarray(spare, dtype=F32)),
        host_ok=jnp.asarray(np.asarray(host_ok, dtype=bool)),
        demand=jnp.asarray(np.asarray(demand, dtype=F32))))
    if not bool(out.found):
        return None
    host = int(out.host)
    if bool(out.spare_only):
        return host, [], float("inf")
    mask = np.asarray(out.victim_mask)[:len(order)]
    victims = sorted(order[p] for p in np.nonzero(mask)[0])
    return host, victims, float(out.decision_dru)


def run_golden(task_dru, task_res, task_host, eligible, spare, host_ok, demand):
    # golden scans tasks per host in descending dru; feed it the same layout
    res = preemption_decision(
        np.asarray(task_dru, dtype=F32), np.asarray(task_res, dtype=F32),
        np.asarray(task_host), np.asarray(eligible, dtype=bool),
        np.asarray(spare, dtype=F32), np.asarray(host_ok, dtype=bool),
        np.asarray(demand, dtype=F32))
    if res is None:
        return None
    host, victims, dru = res
    return host, sorted(victims), dru


class TestPreemptionKernelParity:
    def test_simple_single_victim(self):
        # one host, one big task; preempting it fits the demand
        args = ([2.0], [[4, 400, 0, 0]], [0], [True],
                [[0, 0, 0, 0]], [True], [2, 200, 0, 0])
        assert run_golden(*args) == run_kernel(*args) == (0, [0], 2.0)

    def test_prefers_host_maximizing_min_victim_dru(self):
        # host 0: victims dru 3,1 ; host 1: victims dru 2,2 — preempting on
        # host1 needs both (min dru 2) vs host0 needs both (min dru 1)
        args = ([3.0, 1.0, 2.0, 2.0],
                [[2, 200, 0, 0]] * 4,
                [0, 0, 1, 1],
                [True] * 4,
                [[0, 0, 0, 0], [0, 0, 0, 0]],
                [True, True],
                [4, 400, 0, 0])
        g = run_golden(*args)
        k = run_kernel(*args)
        assert g == k
        assert g[0] == 1 and g[2] == 2.0

    def test_spare_only_wins(self):
        args = ([5.0], [[4, 400, 0, 0]], [0], [True],
                [[0, 0, 0, 0], [8, 800, 0, 0]], [True, True],
                [2, 200, 0, 0])
        g = run_golden(*args)
        k = run_kernel(*args)
        assert g == k == (1, [], float("inf"))

    def test_constraint_blocks_host(self):
        args = ([5.0, 4.0], [[4, 400, 0, 0]] * 2, [0, 1], [True, True],
                [[0, 0, 0, 0], [0, 0, 0, 0]], [False, True],
                [2, 200, 0, 0])
        g = run_golden(*args)
        k = run_kernel(*args)
        assert g == k
        assert g[0] == 1

    def test_no_decision_when_nothing_eligible(self):
        args = ([5.0], [[4, 400, 0, 0]], [0], [False],
                [[0, 0, 0, 0]], [True], [2, 200, 0, 0])
        assert run_golden(*args) is None
        assert run_kernel(*args) is None

    @pytest.mark.parametrize("seed", range(8))
    def test_randomized_parity(self, seed):
        rng = np.random.default_rng(seed)
        T, H = int(rng.integers(1, 60)), int(rng.integers(1, 12))
        task_dru = rng.random(T).astype(F32) * 4
        task_res = np.stack([
            rng.integers(1, 8, T), rng.integers(64, 1024, T),
            np.zeros(T), np.zeros(T)], axis=1).astype(F32)
        task_host = rng.integers(0, H, T)
        eligible = rng.random(T) < 0.8
        spare = np.stack([
            rng.integers(0, 6, H), rng.integers(0, 512, H),
            np.zeros(H), np.zeros(H)], axis=1).astype(F32)
        host_ok = rng.random(H) < 0.9
        demand = np.array([rng.integers(2, 12), rng.integers(128, 2048), 0, 0],
                          dtype=F32)
        g = run_golden(task_dru, task_res, task_host, eligible, spare,
                       host_ok, demand)
        k = run_kernel(task_dru, task_res, task_host, eligible, spare,
                       host_ok, demand)
        if g is None:
            assert k is None
        else:
            assert k is not None
            # same host and same decision quality; victim sets must agree
            assert g[0] == k[0]
            assert g[2] == pytest.approx(k[2])
            assert g[1] == k[1]


def make_job(user, cpus=4.0, mem=4096.0, priority=50):
    return Job(uuid=new_uuid(), user=user, command="x",
               resources=Resources(cpus=cpus, mem=mem), priority=priority)


@pytest.fixture(params=["cpu", "tpu"])
def backend(request):
    return request.param


class TestRebalanceCycle:
    def _full_cluster_setup(self, backend):
        """alice fills the cluster; bob's job waits."""
        store = Store()
        hosts = [FakeHost(f"h{i}", Resources(cpus=8, mem=8192))
                 for i in range(2)]
        cluster = FakeCluster("fake-1", hosts)
        cfg = Config(rebalancer=RebalancerConfig(
            safe_dru_threshold=0.0, min_dru_diff=0.0, max_preemption=10))
        if backend == "cpu":
            cfg.default_matcher.backend = "cpu"
        sched = Scheduler(store, cfg, [cluster], rank_backend=backend)
        store.set_share("default", "default", {"cpus": 8.0, "mem": 8192.0})
        alice = [make_job("alice") for _ in range(4)]
        store.create_jobs(alice)
        sched.step_rank()
        assert len(sched.step_match()["default"].launched_task_ids) == 4
        bob = make_job("bob")
        store.create_jobs([bob])
        sched.step_rank()
        # cluster is full: bob cannot match
        assert sched.step_match()["default"].launched_task_ids == []
        return store, cluster, sched, alice, bob

    def test_preempts_highest_dru_for_fair_share(self, backend):
        store, cluster, sched, alice, bob = self._full_cluster_setup(backend)
        decisions = sched.step_rebalance()["default"]
        assert len(decisions) == 1
        d = decisions[0]
        assert d.job_uuid == bob.uuid
        assert len(d.victim_task_ids) == 1
        # victim is one of alice's (highest cumulative dru)
        victim = store.instance(d.victim_task_ids[0])
        assert victim.status is InstanceStatus.FAILED
        assert victim.preempted
        assert victim.reason_code == Reasons.PREEMPTED_BY_REBALANCER.code
        # alice's preempted job requeues without consuming a retry
        victim_job = store.job(victim.job_uuid)
        assert victim_job.state is JobState.WAITING
        # next cycle bob launches
        sched.step_rank()
        res = sched.step_match()["default"]
        launched_jobs = {store.instance(t).job_uuid
                         for t in res.launched_task_ids}
        assert bob.uuid in launched_jobs

    def test_min_dru_diff_blocks_equal_users(self, backend):
        store, cluster, sched, alice, bob = self._full_cluster_setup(backend)
        sched.config.rebalancer.min_dru_diff = 10.0  # bob never deserves it
        assert sched.step_rebalance() == {}

    def test_safe_dru_threshold_protects_tasks(self, backend):
        store, cluster, sched, alice, bob = self._full_cluster_setup(backend)
        sched.config.rebalancer.safe_dru_threshold = 100.0
        assert sched.step_rebalance() == {}

    def test_over_quota_user_cannot_preempt_others(self, backend):
        store, cluster, sched, alice, bob = self._full_cluster_setup(backend)
        store.set_quota("bob", "default", {"cpus": 1.0})  # bob over quota
        assert sched.step_rebalance() == {}

    def test_multi_victim_reserves_host(self, backend):
        store = Store()
        hosts = [FakeHost("h0", Resources(cpus=8, mem=8192))]
        cluster = FakeCluster("fake-1", hosts)
        cfg = Config(rebalancer=RebalancerConfig(
            safe_dru_threshold=0.0, min_dru_diff=0.0, max_preemption=10))
        if backend == "cpu":
            cfg.default_matcher.backend = "cpu"
        sched = Scheduler(store, cfg, [cluster], rank_backend=backend)
        store.set_share("default", "default", {"cpus": 8.0, "mem": 8192.0})
        # bob's big share makes his pending dru lower than alice's tasks'
        store.set_share("bob", "default", {"cpus": 32.0, "mem": 32768.0})
        alice = [make_job("alice", cpus=4.0, mem=4096.0) for _ in range(2)]
        store.create_jobs(alice)
        sched.step_rank()
        sched.step_match()
        bob = make_job("bob", cpus=8.0, mem=8192.0)  # needs the whole host
        store.create_jobs([bob])
        sched.step_rank()
        decisions = sched.step_rebalance()["default"]
        assert len(decisions) == 1
        assert len(decisions[0].victim_task_ids) == 2
        assert sched.reserved_hosts.get(bob.uuid) == "h0"
        # bob launches on the reserved host next cycle
        sched.step_rank()
        res = sched.step_match()["default"]
        assert [store.instance(t).job_uuid
                for t in res.launched_task_ids] == [bob.uuid]
        # reservation consumed on launch
        assert bob.uuid not in sched.reserved_hosts

    def test_reservation_released_when_job_killed_while_waiting(self, backend):
        store = Store()
        hosts = [FakeHost("h0", Resources(cpus=8, mem=8192))]
        cluster = FakeCluster("fake-1", hosts)
        cfg = Config(rebalancer=RebalancerConfig(
            safe_dru_threshold=0.0, min_dru_diff=0.0, max_preemption=10))
        if backend == "cpu":
            cfg.default_matcher.backend = "cpu"
        sched = Scheduler(store, cfg, [cluster], rank_backend=backend)
        store.set_share("default", "default", {"cpus": 8.0, "mem": 8192.0})
        store.set_share("bob", "default", {"cpus": 32.0, "mem": 32768.0})
        store.create_jobs([make_job("alice", cpus=4.0, mem=4096.0)
                           for _ in range(2)])
        sched.step_rank(); sched.step_match()
        bob = make_job("bob", cpus=8.0, mem=8192.0)
        store.create_jobs([bob])
        sched.step_rank()
        sched.step_rebalance()
        assert sched.reserved_hosts.get(bob.uuid) == "h0"
        store.kill_job(bob.uuid)  # killed while still waiting
        # the reservation must not leak (h0 would be unusable forever)
        assert bob.uuid not in sched.reserved_hosts
        carol = make_job("carol", cpus=1.0, mem=100.0)
        store.create_jobs([carol])
        sched.step_rank()
        res = sched.step_match()["default"]
        assert [store.instance(t).job_uuid
                for t in res.launched_task_ids] == [carol.uuid]
