"""Columnar rank-path index (state/index.py): parity with the entity path
under live mutation, commit-latch invisibility, compaction, and the lazy
RankedQueue surface (VERDICT r1 weak #4)."""

import numpy as np
import pytest

from cook_tpu.config import Config, PoolQuota
from cook_tpu.sched.ranker import RankedQueue, Ranker
from cook_tpu.state import (
    InstanceStatus,
    Job,
    JobState,
    Pool,
    Resources,
    Store,
    new_uuid,
)


def make_job(user, pool="default", cpus=1.0, mem=100.0, priority=50,
             submit=0):
    return Job(uuid=new_uuid(), user=user, command="x", pool=pool,
               priority=priority, submit_time_ms=submit,
               resources=Resources(cpus=cpus, mem=mem), max_retries=5)


def ranked_uuids(store, config, pool="default", columnar=True):
    config.columnar_index = columnar
    ranker = Ranker(store, config, backend="tpu")
    out = ranker.rank_pool(pool)
    if isinstance(out, RankedQueue):
        return list(out.uuids)
    return [j.uuid for j in out]


def assert_parity(store, config, pool="default"):
    fast = ranked_uuids(store, config, pool, columnar=True)
    slow = ranked_uuids(store, config, pool, columnar=False)
    assert fast == slow


class TestRankParity:
    def test_random_store_parity(self):
        rng = np.random.default_rng(5)
        store = Store()
        cfg = Config()
        users = [f"u{i}" for i in range(7)]
        jobs = [make_job(users[rng.integers(len(users))],
                         cpus=float(rng.integers(1, 8)),
                         mem=float(rng.integers(64, 1024)),
                         priority=int(rng.integers(0, 100)),
                         submit=int(rng.integers(0, 10**6)))
                for _ in range(200)]
        store.create_jobs(jobs)
        store.ensure_index()
        # launch some, complete some, fail some
        for job in jobs[:80]:
            tid = new_uuid()
            store.launch_instance(job.uuid, tid, f"h{tid[:4]}")
            r = rng.random()
            if r < 0.3:
                store.update_instance_status(tid, InstanceStatus.RUNNING)
            elif r < 0.5:
                store.update_instance_status(tid, InstanceStatus.RUNNING)
                store.update_instance_status(tid, InstanceStatus.SUCCESS)
            elif r < 0.6:
                store.update_instance_status(tid, InstanceStatus.RUNNING)
                store.update_instance_status(tid, InstanceStatus.FAILED)
        assert_parity(store, cfg)

    def test_parity_across_incremental_mutations(self):
        store = Store()
        cfg = Config()
        store.ensure_index()  # attach BEFORE any writes: pure event-driven
        a, b = make_job("alice"), make_job("bob", priority=90)
        store.create_jobs([a, b])
        assert_parity(store, cfg)
        tid = new_uuid()
        store.launch_instance(a.uuid, tid, "h1")
        assert_parity(store, cfg)
        store.update_instance_status(tid, InstanceStatus.RUNNING)
        assert_parity(store, cfg)
        # preemption-style failure: job requeues as pending again
        store.update_instance_status(tid, InstanceStatus.FAILED,
                                     reason_code=2)
        assert_parity(store, cfg)
        store.kill_job(b.uuid)
        assert_parity(store, cfg)

    def test_uncommitted_jobs_invisible_until_latch(self):
        store = Store()
        cfg = Config()
        store.ensure_index()
        visible = make_job("alice")
        store.create_jobs([visible])
        latched = [make_job("bob") for _ in range(3)]
        store.create_jobs(latched, latch="L1")
        assert ranked_uuids(store, cfg) == [visible.uuid]
        store.commit_latch("L1")
        assert set(ranked_uuids(store, cfg)) == \
            {visible.uuid} | {j.uuid for j in latched}
        assert_parity(store, cfg)

    def test_multi_pool_isolation(self):
        store = Store()
        store.put_pool(Pool(name="gpu"))
        cfg = Config()
        store.ensure_index()
        d = make_job("alice")
        g = make_job("alice", pool="gpu")
        store.create_jobs([d, g])
        assert ranked_uuids(store, cfg, "default") == [d.uuid]
        assert ranked_uuids(store, cfg, "gpu") == [g.uuid]

    def test_pool_quota_caps_columnar(self):
        store = Store()
        cfg = Config()
        cfg.pool_quotas = {"default": PoolQuota(cpus=3.0)}
        store.ensure_index()
        store.create_jobs([make_job("alice", cpus=1.0) for _ in range(6)])
        fast = ranked_uuids(store, cfg, columnar=True)
        slow = ranked_uuids(store, cfg, columnar=False)
        assert fast == slow
        assert len(fast) == 3


class TestCompaction:
    def test_compaction_preserves_parity(self):
        store = Store()
        cfg = Config()
        idx = store.ensure_index()
        survivors = [make_job("alice") for _ in range(5)]
        store.create_jobs(survivors)
        # churn enough completed jobs to trigger compaction (>=4096 dead)
        for batch in range(5):
            jobs = [make_job("bob") for _ in range(1024)]
            store.create_jobs(jobs)
            for j in jobs:
                tid = new_uuid()
                store.launch_instance(j.uuid, tid, "h1")
                store.update_instance_status(tid, InstanceStatus.RUNNING)
                store.update_instance_status(tid, InstanceStatus.SUCCESS)
        before_rows = idx._n
        assert_parity(store, cfg)  # rank triggers _maybe_compact
        assert idx._n < before_rows
        assert_parity(store, cfg)
        # a compacted-away job that retries is re-inserted via its event
        late = make_job("carol")
        store.create_jobs([late])
        assert late.uuid in ranked_uuids(store, cfg)


class TestRankedQueueSurface:
    def test_lazy_materialization_and_slicing(self):
        store = Store()
        cfg = Config()
        store.ensure_index()
        jobs = [make_job("alice", priority=p) for p in (90, 50, 10)]
        store.create_jobs(jobs)
        ranker = Ranker(store, cfg, backend="tpu")
        q = ranker.rank_pool("default")
        assert isinstance(q, RankedQueue)
        assert len(q) == 3 and bool(q)
        prefix = q[:2]
        assert [j.priority for j in prefix] == [90, 50]
        assert all(isinstance(j, Job) for j in prefix)
        assert q.resources.shape == (3, 4)
        # a job killed after ranking still materializes (now completed);
        # staleness is the launch guard txn's job, exactly as on the
        # entity path (allowed-to-start? blocks the launch)
        store.kill_job(q.uuids[0])
        assert [j.uuid for j in q] == list(q.uuids)
        assert q[0].state is JobState.COMPLETED


class TestLongNames:
    def test_long_pool_and_user_names_not_truncated(self):
        """Fixed-width string columns widen instead of silently truncating
        (a truncated name would make its rows invisible to the pool scan)."""
        long_pool = "pool-" + "x" * 60
        long_user = "user-" + "y" * 90
        store = Store()
        store.put_pool(Pool(name=long_pool))
        cfg = Config()
        store.ensure_index()
        j = make_job(long_user, pool=long_pool)
        store.create_jobs([j])
        assert ranked_uuids(store, cfg, long_pool) == [j.uuid]
        assert_parity(store, cfg, long_pool)


class TestIncrementalOrderCache:
    """The per-pool sorted-order cache (index._ord) must stay bit-identical
    to a cold full lexsort across arbitrary scheduling churn — launches,
    completions, failures/requeues, kills, new users, latches."""

    def _cold_order(self, store, pool="default"):
        idx = store.ensure_index()
        with idx._lock:
            idx._ord.pop(pool, None)   # force the full-lexsort path
            got = idx._rank_rows_locked(pool)
        if got is None:
            return None
        arrays, rows_s, user_s, _ = got
        if user_s is None:  # order-cache path: user strings stay lazy
            user_s = idx._user[rows_s]
        return (list(idx._uuid[rows_s]), arrays["pending"].tolist(),
                list(user_s))

    def _cached_order(self, store, pool="default"):
        idx = store.ensure_index()
        with idx._lock:
            got = idx._rank_rows_locked(pool)   # seeds or repairs the cache
            if got is None:
                return None  # no pending jobs: nothing to seed
            assert pool in idx._ord
            got2 = idx._rank_rows_locked(pool)  # pure cache hit
        for a, b in zip(got[0].values(), got2[0].values()):
            assert np.array_equal(a, b)
        arrays, rows_s, user_s, _ = got
        if user_s is None:  # order-cache path: user strings stay lazy
            user_s = idx._user[rows_s]
        return (list(idx._uuid[rows_s]), arrays["pending"].tolist(),
                list(user_s))

    def test_random_churn_matches_cold_rebuild(self):
        rng = np.random.default_rng(11)
        store = Store()
        store.ensure_index()
        live_tids = []
        jobs = []
        for step in range(30):
            # submit a few jobs (sometimes from a brand-new user: user-id
            # shift must invalidate, not corrupt, the cache)
            fresh = [make_job(f"u{rng.integers(0, 6 + step // 10)}",
                              priority=int(rng.integers(0, 100)),
                              submit=int(rng.integers(0, 10**6)))
                     for _ in range(int(rng.integers(1, 5)))]
            store.create_jobs(fresh)
            jobs.extend(fresh)
            # launch a pending job
            pending = [j for j in jobs
                       if store.job(j.uuid).state is JobState.WAITING]
            if pending and rng.random() < 0.8:
                j = pending[int(rng.integers(len(pending)))]
                tid = new_uuid()
                store.launch_instance(j.uuid, tid, "h1")
                live_tids.append(tid)
            # complete/fail a live instance
            if live_tids and rng.random() < 0.6:
                tid = live_tids.pop(int(rng.integers(len(live_tids))))
                store.update_instance_status(tid, InstanceStatus.RUNNING)
                store.update_instance_status(
                    tid, InstanceStatus.SUCCESS if rng.random() < 0.5
                    else InstanceStatus.FAILED, reason_code=6)
            # kill something
            if jobs and rng.random() < 0.2:
                store.kill_job(jobs[int(rng.integers(len(jobs)))].uuid)
            cached = self._cached_order(store)
            cold = self._cold_order(store)
            assert cached == cold, f"diverged at step {step}"
            assert_parity(store, Config())

    def test_latch_commit_repairs_cache(self):
        store = Store()
        store.ensure_index()
        store.create_jobs([make_job("alice")])
        assert self._cached_order(store) == self._cold_order(store)
        store.create_jobs([make_job("bob") for _ in range(3)], latch="L")
        store.commit_latch("L")
        assert self._cached_order(store) == self._cold_order(store)

    def test_compaction_invalidates_and_reseeds_cache(self):
        """Compaction remaps row indices; a live order cache must be
        invalidated and reseeded, staying bit-identical to a cold
        rebuild (compaction needs >4096 dead rows, beyond the churn
        test's scale)."""
        store = Store()
        store.ensure_index()
        jobs = [make_job(f"u{i % 5}", priority=int(i % 100), submit=i)
                for i in range(9000)]
        store.create_jobs(jobs)
        assert self._cached_order(store) == self._cold_order(store)
        # run most jobs to completion: their rows go dead
        for j in jobs[:6500]:
            tid = new_uuid()
            store.launch_instance(j.uuid, tid, "h1")
            store.update_instance_status(tid, InstanceStatus.RUNNING)
            store.update_instance_status(tid, InstanceStatus.SUCCESS)
        idx = store.ensure_index()
        n_before = idx._n
        cached = self._cached_order(store)   # triggers _maybe_compact
        assert idx._n < n_before             # compaction actually ran
        assert cached == self._cold_order(store)
        # and the reseeded cache keeps repairing correctly
        fresh = [make_job("u9", priority=77) for _ in range(10)]
        store.create_jobs(fresh)
        assert self._cached_order(store) == self._cold_order(store)


class TestBulkAttach:
    def test_bulk_attach_matches_per_row_golden(self):
        """The vectorized initial scan (_bulk_attach_jobs) must build
        byte-identical columns to the per-row path it replaces, on a
        store with awkward shapes: mixed states, live instances, a
        non-canonical (UPPERCASE) uuid, a >64-char user, and a
        latch-uncommitted job."""
        from cook_tpu.state import Store, new_uuid
        from cook_tpu.state.index import ColumnarIndex

        store = Store()
        jobs = [make_job(f"u{i % 11}", priority=i % 100, cpus=1 + i % 4)
                for i in range(800)]
        store.create_jobs(jobs)
        store.create_jobs([make_job("x" * 80)])
        up = make_job("shouty")
        up.uuid = "DEADBEEF-0000-4000-8000-00000000CAFE"
        store.create_jobs([up])
        store.create_jobs([make_job("latched")], latch="L")
        for j in jobs[:25]:
            store.launch_instance(j.uuid, new_uuid(), "h0")

        idx_bulk = ColumnarIndex(store)
        orig = ColumnarIndex._bulk_attach_jobs
        ColumnarIndex._bulk_attach_jobs = \
            lambda self, js: [self._sync_job_raw(j) for j in js]
        try:
            idx_row = ColumnarIndex(Store.restore(store.snapshot()))
        finally:
            ColumnarIndex._bulk_attach_jobs = orig
        n = idx_bulk._n
        assert n == idx_row._n
        for col in ("_res", "_disk", "_prio", "_submit", "_uuid",
                    "_user", "_pool", "_pending", "_done", "_uid",
                    "_uhi", "_ulo", "_complex"):
            assert np.array_equal(getattr(idx_bulk, col)[:n],
                                  getattr(idx_row, col)[:n]), col
        assert idx_bulk._sortable is idx_row._sortable is False
        assert idx_bulk._user_names == idx_row._user_names
        assert idx_bulk._dead == idx_row._dead
