"""Chaos harness (sim/chaos.py): deterministic fault-schedule runs with
the robustness invariants asserted — all jobs terminal, mea-culpa
failures consume zero user retries, no duplicate live instances, and
leader kill/promotion replays every committed transaction.

The smoke test is tier-1 (fast, fixed seed); the soak is ``slow``-marked
and excluded from tier-1 (run it with ``pytest -m 'slow and chaos'`` or
``python -m cook_tpu.sim --chaos``)."""

import pytest

from cook_tpu.sim.chaos import ChaosConfig, run_chaos

pytestmark = pytest.mark.chaos


@pytest.mark.parametrize("seed", [7])
def test_chaos_smoke(tmp_path, seed):
    """Fixed-seed smoke: node loss + launch RPC faults + one leader
    kill/promotion mid-run.  Seed 7 is chosen because its kill lands
    with launch intents OPEN (the crash-consistency window actually
    executes, not just the happy path)."""
    cc = ChaosConfig(seed=seed, data_dir=str(tmp_path / "chaos"))
    result = run_chaos(cc)
    assert result.ok, result.violations
    assert result.completed == result.total
    assert result.leader_kills == 1
    assert result.node_losses > 0
    assert result.rpc_faults > 0
    # the window under test: the kill interrupted in-flight dispatches,
    # and every one of them was refunded/relaunched (ok + all-terminal
    # above prove no duplicate and no loss)
    assert result.intents_open_at_kill > 0
    # injected failures are all mea-culpa: zero user retries consumed
    assert result.user_retries_charged == 0


def test_chaos_is_deterministic(tmp_path):
    """Same seed, same fault sequence, same outcome counters — the replay
    property that makes a chaos failure debuggable."""
    a = run_chaos(ChaosConfig(seed=3, data_dir=str(tmp_path / "a")))
    b = run_chaos(ChaosConfig(seed=3, data_dir=str(tmp_path / "b")))
    assert (a.ok, a.completed, a.node_losses, a.rpc_faults,
            a.intents_open_at_kill, a.makespan_ms) == \
        (b.ok, b.completed, b.node_losses, b.rpc_faults,
         b.intents_open_at_kill, b.makespan_ms)


def test_chaos_disk_faults_heal_before_promotion(tmp_path):
    """The leader-kill leg under silent bit rot (docs/ROBUSTNESS.md
    "WAL v2"): ``store.journal.bitflip`` armed on every append, the
    pre-promotion scrub must detect and self-heal every flip, and the
    promoted store still replays to the exact pre-crash state.  Seed and
    probability are pinned so at least one flip actually lands."""
    cc = ChaosConfig(seed=7, data_dir=str(tmp_path / "df"),
                     disk_fault_probability=0.25)
    result = run_chaos(cc)
    assert result.ok, result.violations
    assert result.completed == result.total
    assert result.leader_kills == 1
    assert result.disk_corruptions_healed > 0


@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_chaos_soak(tmp_path, seed):
    """Longer soak across seeds: heavier RPC fault rate (enough to trip
    the launch circuit breaker and exercise half-open heal in virtual
    time), more jobs, leader kill later in the run."""
    cc = ChaosConfig(
        seed=seed,
        n_jobs=150,
        n_hosts=10,
        submit_span_ms=60_000,
        rpc_fault_probability=0.45,
        rpc_fault_max=40,
        node_loss_every_ms=7_000,
        node_loss_max=5,
        leader_kill_at_ms=25_000,
        breaker_failure_threshold=3,
        data_dir=str(tmp_path / f"soak{seed}"))
    result = run_chaos(cc)
    assert result.ok, result.violations
    assert result.completed == result.total
    assert result.leader_kills == 1
    assert result.user_retries_charged == 0
