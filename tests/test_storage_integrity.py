"""Storage-integrity plane contracts (docs/ROBUSTNESS.md "WAL v2"):
the CRC32C journal envelope, torn-tail vs mid-file-corruption verdicts,
mixed v1/v2 replay, checkpoint manifest fallback, the boot hygiene
sweep, ENOSPC clean aborts (unit + REST 503 + forced write-shed), the
background scrub's self-heal, and peer repair of a poisoned mirror.

Layered like test_robustness.py: pure integrity units first, store-level
recovery contracts, then the serving-plane and replication layers."""

import json
import os
import time

import pytest

from cook_tpu.client import JobClient, JobClientError
from cook_tpu.cluster import FakeCluster, FakeHost
from cook_tpu.config import Config
from cook_tpu.policy import QueueLimits
from cook_tpu.rest import ApiServer, CookApi
from cook_tpu.sched import Scheduler
from cook_tpu.state.integrity import (
    FrameError,
    JournalCorruptionError,
    crc32c,
    hygiene_sweep,
    parse_journal_line,
    scan_journal,
    seal_record,
    verify_snapshot,
    verify_window,
)
from cook_tpu.state.partition import PartitionedStore, PartitionMap
from cook_tpu.state.read_replica import FollowerReadView
from cook_tpu.state.repair import open_with_repair, quarantine
from cook_tpu.state.schema import InstanceStatus, Job, Resources
from cook_tpu.state.store import StorageFullError, Store
from cook_tpu.utils.faults import injector


def make_job(i, user="alice", pool="default"):
    return Job(uuid=f"00000000-0000-0000-0000-{i:012d}", user=user,
               pool=pool, command=f"echo {i}",
               resources=Resources(cpus=1, mem=64))


def run_workload(store, n=4):
    """Create / launch / transition enough jobs to exercise every
    journal record shape."""
    for i in range(n):
        store.create_jobs([make_job(i)])
        store.launch_instance(make_job(i).uuid, f"t-{i}", f"h-{i % 2}")
        store.update_instance_status(f"t-{i}", InstanceStatus.RUNNING)
        if i % 2 == 0:
            store.update_instance_status(f"t-{i}", InstanceStatus.SUCCESS)


def digest(store):
    return sorted(
        (j.uuid, j.state.name,
         tuple(sorted((t, store.instance(t).status.name)
                      for t in j.instances)))
        for j in store.jobs_where(lambda j: True))


@pytest.fixture(autouse=True)
def _clear_faults():
    injector.clear()
    yield
    injector.clear()


# ---------------------------------------------------------------------------
# integrity units: frames, scans, windows
# ---------------------------------------------------------------------------

class TestFrame:
    def test_seal_parse_roundtrip(self):
        rec = {"tx": 7, "w": [["jobs", {"uuid": "x"}]], "unicode": "λ"}
        line = seal_record(rec)
        assert line.startswith("v2 ") and line.endswith("\n")
        assert parse_journal_line(line.strip().encode()) == rec

    def test_crc_catches_single_bit_flip(self):
        line = seal_record({"tx": 1, "payload": "abcdef"}).strip().encode()
        flipped = bytearray(line)
        flipped[-3] ^= 0x01
        with pytest.raises(FrameError) as ei:
            parse_journal_line(bytes(flipped))
        # a complete frame failing its CRC can only be corruption
        assert ei.value.complete

    def test_short_payload_is_incomplete(self):
        line = seal_record({"tx": 1, "k": "vvvv"}).strip().encode()
        with pytest.raises(FrameError) as ei:
            parse_journal_line(line[:-4])
        assert not ei.value.complete

    def test_v1_bare_json_still_parses(self):
        assert parse_journal_line(b'{"tx": 3}') == {"tx": 3}

    def test_crc32c_known_vector(self):
        # iSCSI/ext4 Castagnoli check value for "123456789"
        assert crc32c(b"123456789") == 0xE3069283

    def test_crc32c_fallback_agrees_with_active_impl(self):
        # whichever implementation is active (native wheel or the pure-
        # Python table), the fallback must produce identical checksums —
        # a journal sealed on one box must verify on another
        from cook_tpu.state.integrity import _crc32c_py
        assert _crc32c_py(b"123456789") == 0xE3069283
        rng = __import__("random").Random(42)
        for n in (0, 1, 63, 64, 65, 300):
            blob = bytes(rng.randrange(256) for _ in range(n))
            assert crc32c(blob) == _crc32c_py(blob)
            half = n // 2
            assert crc32c(blob[half:], crc32c(blob[:half])) == crc32c(blob)


class TestScan:
    def _write(self, tmp_path, chunks):
        p = os.path.join(str(tmp_path), "journal.jsonl")
        with open(p, "wb") as f:
            for c in chunks:
                f.write(c)
        return p

    def test_torn_tail_is_excised_not_corrupt(self, tmp_path):
        whole = seal_record({"tx": 1}).encode()
        torn = seal_record({"tx": 2}).encode()[:-7]
        p = self._write(tmp_path, [whole, torn])
        scan = scan_journal(p)
        assert not scan.corrupt
        assert [r["tx"] for r in scan.records] == [1]
        assert scan.good == len(whole)

    def test_midfile_garbage_with_records_after_is_corruption(
            self, tmp_path):
        p = self._write(tmp_path, [seal_record({"tx": 1}).encode(),
                                   b"#### garbage ####\n",
                                   seal_record({"tx": 2}).encode()])
        scan = scan_journal(p)
        assert scan.corrupt
        assert scan.corrupt_offset == len(seal_record({"tx": 1}))

    def test_complete_frame_crc_fail_at_tail_is_corruption(
            self, tmp_path):
        bad = bytearray(seal_record({"tx": 2}).encode())
        bad[-3] ^= 0x10  # inside the payload, newline intact
        p = self._write(tmp_path, [seal_record({"tx": 1}).encode(),
                                   bytes(bad)])
        assert scan_journal(p).corrupt

    def test_legacy_triple_unpack(self, tmp_path):
        p = self._write(tmp_path, [seal_record({"tx": 1}).encode()])
        records, good, size = scan_journal(p)
        assert [r["tx"] for r in records] == [1] and good == size

    def test_verify_window_walks_the_file(self, tmp_path):
        lines = [seal_record({"tx": i}).encode() for i in range(20)]
        p = self._write(tmp_path, lines)
        off, size = 0, os.path.getsize(p)
        while off < size:
            res = verify_window(p, off, 64)
            assert not res.corrupt
            assert res.good > off  # progress every pass
            off = res.good
        assert off == size

    def test_verify_window_finds_midfile_damage(self, tmp_path):
        lines = [seal_record({"tx": i}).encode() for i in range(5)]
        p = self._write(tmp_path, lines)
        with open(p, "r+b") as f:
            f.seek(len(lines[0]) + len(lines[1]) // 2)
            b = f.read(1)
            f.seek(-1, os.SEEK_CUR)
            f.write(bytes([b[0] ^ 0x20]))
        res = verify_window(p, 0, 1 << 20)
        assert res.corrupt and res.corrupt_offset == len(lines[0])


# ---------------------------------------------------------------------------
# store-level recovery: mixed v1/v2 replay, manifest fallback, hygiene
# ---------------------------------------------------------------------------

def _downgrade_alternate_lines(journal):
    """Rewrite every other v2 frame as its bare-JSON v1 form — the
    mixed-version journal an in-place upgrade produces."""
    out = []
    with open(journal, "rb") as f:
        for i, line in enumerate(f.read().splitlines()):
            rec = parse_journal_line(line.strip())
            out.append(json.dumps(rec) + "\n" if i % 2
                       else seal_record(rec))
    with open(journal, "w", encoding="utf-8") as f:
        f.writelines(out)


class TestMixedReplay:
    def test_store_replays_v1_and_v2_interleaved(self, tmp_path):
        d = str(tmp_path / "s")
        store = Store.open(d)
        run_workload(store)
        expected = digest(store)
        store.close()
        _downgrade_alternate_lines(os.path.join(d, "journal.jsonl"))
        assert not scan_journal(os.path.join(d, "journal.jsonl")).corrupt
        reopened = Store.open(d)
        assert digest(reopened) == expected
        reopened.close()

    def test_partitioned_store_replays_mixed_shards(self, tmp_path):
        pmap = PartitionMap(count=2, pools={"alpha": 0, "beta": 1})
        d = str(tmp_path / "ps")
        ps = PartitionedStore.open(d, pmap)
        for i, pool in enumerate(["alpha", "beta", "alpha", "beta"]):
            ps.create_jobs([make_job(i, pool=pool)])
            ps.launch_instance(make_job(i).uuid, f"t-{i}", "h-0")
        expected = digest(ps)
        ps.close()
        for sub in os.listdir(d):
            j = os.path.join(d, sub, "journal.jsonl")
            if os.path.exists(j):
                _downgrade_alternate_lines(j)
        reopened = PartitionedStore.open(d, pmap)
        assert digest(reopened) == expected
        reopened.close()

    def test_read_view_replays_mixed_journal(self, tmp_path):
        d = str(tmp_path / "rv")
        store = Store.open(d)
        run_workload(store)
        store.checkpoint()  # the view's base snapshot
        for i in range(4, 7):
            store.create_jobs([make_job(i)])
        expected = digest(store)
        store.close()
        _downgrade_alternate_lines(os.path.join(d, "journal.jsonl"))
        view = FollowerReadView(d, start=False)
        try:
            view.poll()
            assert view.corrupt is None
            assert digest(view.store) == expected
        finally:
            view.stop()


class TestManifestFallback:
    def test_damaged_snapshot_falls_back_to_prev_generation(
            self, tmp_path):
        d = str(tmp_path / "s")
        store = Store.open(d)
        run_workload(store, n=2)
        store.checkpoint()
        store.create_jobs([make_job(7)])
        store.checkpoint()  # rotation keeps gen N-1 aside
        store.create_jobs([make_job(8)])
        expected = digest(store)
        store.close()
        snap = os.path.join(d, "snapshot.json")
        assert verify_snapshot(snap) is True
        with open(snap, "r+b") as f:
            f.seek(os.path.getsize(snap) // 2)
            b = f.read(1)
            f.seek(-1, os.SEEK_CUR)
            f.write(bytes([b[0] ^ 0x40]))
        assert verify_snapshot(snap) is False
        reopened = Store.open(d)
        assert digest(reopened) == expected
        reopened.close()

    def test_sole_damaged_generation_refuses(self, tmp_path):
        d = str(tmp_path / "s")
        store = Store.open(d)
        run_workload(store, n=2)
        store.checkpoint()
        store.close()
        snap = os.path.join(d, "snapshot.json")
        with open(snap, "r+b") as f:
            f.write(b"X")
        with pytest.raises(JournalCorruptionError):
            Store.open(d)


class TestHygiene:
    def test_sweep_removes_old_orphans_keeps_young(self, tmp_path):
        d = str(tmp_path)
        old_tmp = os.path.join(d, ".snapshot.json.tmp.123.456")
        young_tmp = os.path.join(d, ".snapshot.json.tmp.789.012")
        marker = os.path.join(d, "mirror_poisoned")
        normal = os.path.join(d, "journal.jsonl")
        for p in (old_tmp, young_tmp, marker, normal):
            with open(p, "w") as f:
                f.write("x")
        past = time.time() - 3600
        os.utime(old_tmp, (past, past))
        os.utime(marker, (past, past))
        assert hygiene_sweep(d, min_age_s=60) == 2
        assert not os.path.exists(old_tmp)
        assert not os.path.exists(marker)
        assert os.path.exists(young_tmp)  # a live writer's in-flight temp
        assert os.path.exists(normal)

    def test_store_open_runs_the_sweep_and_counts_it(self, tmp_path):
        d = str(tmp_path / "s")
        os.makedirs(d)
        orphan = os.path.join(d, ".config.json.tmp.1.2")
        with open(orphan, "w") as f:
            f.write("{}")
        past = time.time() - 3600
        os.utime(orphan, (past, past))
        store = Store.open(d)
        assert not os.path.exists(orphan)
        assert store.storage_stats()["hygiene_removed"] == 1
        store.close()


# ---------------------------------------------------------------------------
# ENOSPC: clean abort at the store, 503 + write-shed at the front door
# ---------------------------------------------------------------------------

class TestEnospc:
    def test_full_disk_aborts_cleanly(self, tmp_path):
        d = str(tmp_path / "s")
        store = Store.open(d)
        store.create_jobs([make_job(0)])
        injector.arm("store.journal.enospc", probability=1.0)
        with pytest.raises(StorageFullError):
            store.create_jobs([make_job(1)])
        injector.clear()
        # nothing installed in memory, nothing torn on disk: the journal
        # replays to exactly the pre-abort state
        assert store.job(make_job(1).uuid) is None
        assert store.storage_stats()["enospc_aborts"] == 1
        expected = digest(store)
        store.close()
        reopened = Store.open(d)
        assert digest(reopened) == expected
        reopened.close()

    def test_rest_503_sheds_writes_keeps_reads(self, tmp_path):
        store = Store.open(str(tmp_path / "s"))
        cluster = FakeCluster(
            "fake-1", [FakeHost("h0", Resources(cpus=8, mem=8192))])
        cfg = Config()
        cfg.default_matcher.backend = "cpu"
        cfg.admission.enabled = True
        sched = Scheduler(store, cfg, [cluster], rank_backend="cpu")
        api = CookApi(store, scheduler=sched, config=cfg,
                      queue_limits=QueueLimits(store, per_user_limit=100))
        server = ApiServer(api)
        server.start()
        try:
            client = JobClient(server.url, user="alice")
            client.throttle_retries = 0  # surface the 503, don't pace
            ok_uuid = client.submit_one("echo hi", cpus=1, mem=64)
            injector.arm("store.journal.enospc", probability=1.0)
            with pytest.raises(JobClientError) as ei:
                client.submit_one("echo blocked", cpus=1, mem=64)
            assert ei.value.status == 503
            assert ei.value.body.get("storage_full") is True
            assert ei.value.retry_after_s is not None
            # the failed append escalated the brownout ladder to
            # shed-writes (stage 3) so retry storms die at the front
            # door instead of hammering a full disk
            assert sched.admission is not None
            assert sched.admission.stage == 3
            # reads keep serving through the whole episode
            assert client.job(ok_uuid)["state"] == "waiting"
            assert api.debug_storage()["enospc_aborts"] >= 1
        finally:
            injector.clear()
            server.stop()
            store.close()


# ---------------------------------------------------------------------------
# the /debug/storage surface (REST + client + cs CLI)
# ---------------------------------------------------------------------------

class TestDebugStorageSurface:
    def test_panel_serves_over_http_client_and_cli(
            self, tmp_path, capsys, monkeypatch):
        import urllib.request
        from cook_tpu.cli.main import main as cli_main
        store = Store.open(str(tmp_path / "s"))
        run_workload(store, n=2)
        api = CookApi(store, config=Config())
        server = ApiServer(api)
        server.start()
        try:
            # raw HTTP: the panel is a plain GET, no auth gymnastics
            resp = urllib.request.urlopen(server.url + "/debug/storage")
            assert resp.status == 200
            doc = json.load(resp)
            assert doc["poisoned"] is False
            assert doc["corruptions"] == 0
            (shard,) = doc["shards"]
            assert shard["journal_bytes"] > 0
            assert shard["journal_poisoned"] is False
            # Config() wires the scrub block from config.storage
            assert doc["scrub"]["enabled"] is True
            assert doc["scrub"]["chunk_bytes"] > 0
            # client wrapper returns the same panel
            assert JobClient(server.url).debug_storage() == doc
            # and `cs debug storage` renders it as JSON on stdout
            monkeypatch.setenv("COOK_URL", server.url)
            rc = cli_main(["debug", "storage"])
            assert rc == 0
            printed = json.loads(capsys.readouterr().out)
            assert printed["shards"] == doc["shards"]
        finally:
            server.stop()
            store.close()


# ---------------------------------------------------------------------------
# scrub self-heal + peer repair
# ---------------------------------------------------------------------------

class TestScrubAndRepair:
    def _flip_journal_byte(self, d, frac=0.5):
        j = os.path.join(d, "journal.jsonl")
        size = os.path.getsize(j)
        with open(j, "r+b") as f:
            f.seek(int(size * frac))
            b = f.read(1)
            f.seek(-1, os.SEEK_CUR)
            f.write(bytes([b[0] ^ 0x08]))

    def test_scrub_detects_and_self_heals_live_store(self, tmp_path):
        d = str(tmp_path / "s")
        store = Store.open(d)
        run_workload(store)
        expected = digest(store)
        self._flip_journal_byte(d)
        hit = {}
        while True:
            doc = store.scrub(max_bytes=256, repair=True)
            if doc.get("corrupt"):
                hit = doc
                break
            assert doc.get("enabled")
            if doc.get("verified_offset", 0) >= doc.get(
                    "journal_bytes", 0):
                break
        assert hit and hit["repaired"]
        stats = store.storage_stats()
        assert stats["scrub_corruptions"] == 1
        assert stats["scrub_repairs"] == 1
        store.close()
        # the self-heal checkpointed from the in-memory authority: a
        # cold replay now verifies clean and reproduces the state
        reopened = Store.open(d)
        assert digest(reopened) == expected
        reopened.close()

    def test_cold_open_refuses_then_quarantine_recovers_checkpoint(
            self, tmp_path):
        d = str(tmp_path / "s")
        store = Store.open(d)
        run_workload(store, n=2)
        store.checkpoint()
        store.create_jobs([make_job(9)])
        store.close()
        self._flip_journal_byte(d)
        with pytest.raises(JournalCorruptionError):
            Store.open(d)
        with pytest.raises(JournalCorruptionError):
            open_with_repair(d)  # no peers: refusal must propagate
        quarantine(d)
        # the damaged generation is out of replay's way but kept for
        # forensics; the directory is a blank slate a peer resync (or a
        # fresh leader) can safely fill — never a silently-truncated
        # half-state
        assert os.path.exists(os.path.join(d, "journal.jsonl.corrupt"))
        assert os.path.exists(os.path.join(d, "snapshot.json.corrupt"))
        reopened = Store.open(d)
        assert digest(reopened) == []
        reopened.close()
        # every committed frame BEFORE the damage point is still
        # recoverable from the quarantined bytes
        scan = scan_journal(os.path.join(d, "journal.jsonl.corrupt"))
        assert scan.corrupt and scan.records

    def test_open_with_repair_pulls_from_peer(self, tmp_path):
        from cook_tpu.state.replication import (ReplicationServer,
                                                replication_available)
        if not replication_available():
            pytest.skip("native replication carrier unavailable")
        pristine = str(tmp_path / "leader")
        store = Store.open(pristine, fsync=True)
        run_workload(store)
        expected = digest(store)
        server = ReplicationServer(pristine, port=0)
        try:
            damaged = str(tmp_path / "damaged")
            import shutil
            shutil.copytree(pristine, damaged)
            self._flip_journal_byte(damaged)
            with pytest.raises(JournalCorruptionError):
                Store.open(damaged)
            healed = open_with_repair(
                damaged, peers=[("127.0.0.1", server.port)])
            assert digest(healed) == expected
            healed.close()
            with open(os.path.join(pristine, "journal.jsonl"),
                      "rb") as f:
                want = f.read()
            with open(os.path.join(damaged, "journal.jsonl"),
                      "rb") as f:
                got = f.read()
            assert got == want  # byte-identical convergence
        finally:
            server.stop()
            store.close()
