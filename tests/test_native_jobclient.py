"""Native C++ jobclient (native/jobclient.cpp via cook_tpu/native/jobclient.py)
against a live REST server — the build's equivalent of the reference's Java
jobclient surface (reference: jobclient/java/.../JobClient.java: batched
submit/query/abort, retry, listener poll loop, impersonation, basic auth),
exercised over a real TCP socket."""

import threading
import time

import pytest

from cook_tpu.cluster import FakeCluster, FakeHost
from cook_tpu.config import Config
from cook_tpu.native.jobclient import (
    NativeJobClient,
    NativeJobClientError,
    native_available,
)
from cook_tpu.policy import QueueLimits
from cook_tpu.rest import ApiServer, CookApi
from cook_tpu.sched import Scheduler
from cook_tpu.state import Resources, Store

pytestmark = pytest.mark.skipif(
    not native_available(), reason="no C++ toolchain")


@pytest.fixture()
def system():
    store = Store()
    cluster = FakeCluster(
        "fake-1", [FakeHost(f"h{i}", Resources(cpus=8, mem=8192))
                   for i in range(2)])
    cfg = Config()
    cfg.default_matcher.backend = "cpu"
    sched = Scheduler(store, cfg, [cluster], rank_backend="cpu")
    api = CookApi(store, scheduler=sched,
                  queue_limits=QueueLimits(store, per_user_limit=100),
                  admins=["admin"], impersonators=["proxy"])
    server = ApiServer(api)
    server.start()
    yield store, cluster, sched, server
    server.stop()


def native_client(server, user="alice", **kw) -> NativeJobClient:
    return NativeJobClient(server.host, server.port, user=user, **kw)


JOB = {"command": "true", "cpus": 1.0, "mem": 128.0}


class TestNativeJobClient:
    def test_submit_query_roundtrip(self, system):
        store, cluster, sched, server = system
        with native_client(server) as c:
            [uuid] = c.submit([JOB])
            jobs = c.query([uuid])
            assert len(jobs) == 1
            assert jobs[0]["uuid"] == uuid
            assert jobs[0]["user"] == "alice"
            assert jobs[0]["state"] == "waiting"

    def test_batched_submit(self, system):
        _store, _c, _s, server = system
        with native_client(server) as c:
            uuids = c.submit([dict(JOB) for _ in range(5)])
            assert len(set(uuids)) == 5
            got = {j["uuid"] for j in c.query(uuids)}
            assert got == set(uuids)

    def test_kill(self, system):
        _store, _c, _s, server = system
        with native_client(server) as c:
            [uuid] = c.submit([JOB])
            c.kill([uuid])
            [job] = c.query([uuid])
            assert job["state"] == "failed"

    def test_retry_resurrects_failed_job(self, system):
        store, cluster, sched, server = system
        with native_client(server) as c:
            [uuid] = c.submit([dict(JOB, max_retries=1)])
            sched.step_rank()
            [tid] = sched.step_match()["default"].launched_task_ids
            cluster.complete_task(tid, exit_code=3)
            [job] = c.query([uuid])
            assert job["state"] == "failed"
            c.retry(uuid, retries=5)
            [job] = c.query([uuid])
            assert job["state"] == "waiting"

    def test_wait_for_completion(self, system):
        store, cluster, sched, server = system
        with native_client(server) as c:
            [uuid] = c.submit([JOB])
            done = threading.Event()

            def drive():
                # launch, then complete the instance while wait() polls
                sched.step_rank()
                [tid] = sched.step_match()["default"].launched_task_ids
                time.sleep(0.3)
                cluster.complete_task(tid)
                done.set()

            t = threading.Thread(target=drive)
            t.start()
            jobs = c.wait([uuid], timeout_s=10.0, poll_s=0.05)
            t.join()
            assert done.is_set()
            assert jobs[0]["state"] == "success"

    def test_wait_timeout(self, system):
        _store, _c, _s, server = system
        with native_client(server) as c:
            [uuid] = c.submit([JOB])
            with pytest.raises(TimeoutError):
                c.wait([uuid], timeout_s=0.3, poll_s=0.05)

    def test_listener_sees_state_changes(self, system):
        """The native poll-loop listener fires on every state transition
        (JobClient.java JobListener semantics)."""
        store, cluster, sched, server = system
        with native_client(server) as c:
            [uuid] = c.submit([JOB])
            seen = []
            c.listen([uuid], lambda u, s: seen.append((u, s)),
                     interval_s=0.05)
            time.sleep(0.2)  # poll picks up "waiting"
            sched.step_rank()
            [tid] = sched.step_match()["default"].launched_task_ids
            time.sleep(0.2)  # poll picks up "running"
            cluster.complete_task(tid)
            deadline = time.time() + 5.0
            while time.time() < deadline:
                if (uuid, "success") in seen:
                    break
                time.sleep(0.05)
            states = [s for u, s in seen if u == uuid]
            assert states == ["waiting", "running", "success"]

    def test_impersonation(self, system):
        _store, _c, _s, server = system
        with native_client(server, user="proxy", impersonate="carol") as c:
            [uuid] = c.submit([JOB])
            [job] = c.query([uuid])
            assert job["user"] == "carol"

    def test_http_error_surfaces(self, system):
        _store, _c, _s, server = system
        with native_client(server) as c:
            with pytest.raises(NativeJobClientError) as ei:
                c.retry("00000000-0000-0000-0000-000000000000", retries=2)
            assert ei.value.status == 404

    def test_generic_request(self, system):
        """The raw round-trip surface reaches any endpoint (here /info)."""
        _store, _c, _s, server = system
        with native_client(server) as c:
            status, body = c.request("GET", "/info")
            assert status == 200
            assert "cook" in body.lower() or "version" in body.lower()


class TestGroups:
    """Group submit/query/kill through the C++ client (the Java
    jobclient's Group support, jobclient/java Group.java)."""

    def test_group_submit_query_kill(self, system):
        store, _cluster, sched, srv = system
        g = "99999999-aaaa-bbbb-cccc-eeeeeeeeeeee"
        with native_client(srv) as c:
            uuids = c.submit(
                [{"command": "sleep 999", "cpus": 1, "mem": 64, "group": g}
                 for _ in range(2)],
                groups=[{"uuid": g, "name": "native-grp"}])
            assert len(uuids) == 2
            sched.step_rank(); sched.step_match()
            [grp] = c.group([g], detailed=True)
            assert grp["uuid"] == g and grp["name"] == "native-grp"
            assert sorted(grp["jobs"]) == sorted(uuids)
            c.kill_groups([g])
            jobs = c.query(uuids)
            assert all(j["state"] in ("failed", "completed", "waiting")
                       for j in jobs)
