"""Statistical workload generator (reference: simulator/ system simulator)."""

import numpy as np
import pytest

from cook_tpu.sim.simulator import Simulator, load_hosts, load_trace
from cook_tpu.sim.workload import (
    generate_hosts,
    generate_trace,
    sample,
)

SPEC = {
    "seed": 7,
    "horizon_ms": 600_000,  # 10 virtual minutes
    "user_classes": [
        {"name": "batch", "users": 3, "arrival_rate_per_min": 6.0,
         "duration_ms": {"dist": "lognormal", "mu": 9.5, "sigma": 0.5},
         "cpus": {"dist": "choice", "values": [1, 2, 4],
                  "weights": [0.6, 0.3, 0.1]},
         "mem": {"dist": "uniform", "low": 128, "high": 1024},
         "priority": {"dist": "constant", "value": 50}},
        {"name": "interactive", "users": 2, "arrival_rate_per_min": 2.0,
         "duration_ms": {"dist": "exponential", "scale": 20_000},
         "cpus": 1.0, "mem": 256.0,
         "priority": {"dist": "constant", "value": 90}},
    ],
}


class TestDistributions:
    def test_sample_kinds(self):
        rng = np.random.default_rng(0)
        assert (sample(3.0, rng, 4) == 3.0).all()
        assert (sample({"dist": "constant", "value": 2}, rng, 4) == 2.0).all()
        u = sample({"dist": "uniform", "low": 1, "high": 2}, rng, 1000)
        assert (u >= 1).all() and (u <= 2).all()
        c = sample({"dist": "choice", "values": [1, 5]}, rng, 1000)
        assert set(np.unique(c)) <= {1.0, 5.0}
        ln = sample({"dist": "lognormal", "mu": 0.0, "sigma": 0.1}, rng, 1000)
        assert 0.8 < float(np.median(ln)) < 1.2


class TestGenerator:
    def test_deterministic_for_seed(self):
        assert generate_trace(SPEC) == generate_trace(SPEC)
        assert generate_trace(SPEC, seed=1) != generate_trace(SPEC, seed=2)

    def test_shape_and_rates(self):
        entries = generate_trace(SPEC)
        assert entries == sorted(entries, key=lambda e: e["submit_time"])
        users = {e["user"] for e in entries}
        assert users <= {"batch000", "batch001", "batch002",
                         "interactive000", "interactive001"}
        # 3 users x 6/min x 10 min = ~180 batch arrivals; allow 4 sigma
        batch = [e for e in entries if e["user"].startswith("batch")]
        assert 120 <= len(batch) <= 250, len(batch)
        assert all(0 <= e["submit_time"] < SPEC["horizon_ms"]
                   for e in entries)
        assert all(e["duration"] >= 1 for e in entries)
        interactive = [e for e in entries
                       if e["user"].startswith("interactive")]
        assert all(e["priority"] == 90 for e in interactive)

    def test_hosts(self):
        hosts = generate_hosts(3, cpus=8.0)
        assert [h["hostname"] for h in hosts] == \
            ["host0000", "host0001", "host0002"]
        assert all(h["cpus"] == 8.0 for h in hosts)


@pytest.mark.slow
class TestScale:
    def test_50k_job_statistical_run_wait_metrics(self):
        """The reference's system-simulator tier at scale (reference:
        simulator/README.md — statistical workloads against a fully
        stood-up scheduler, reporting wait times): >=50k generated jobs
        replayed through the REAL scheduler on the virtual clock, with
        wait-time and completion assertions on the summary metrics."""
        spec = {
            "seed": 11, "horizon_ms": 300_000,
            "user_classes": [
                # ~40k batch arrivals: 20 users x 400/min x 5 min
                {"name": "batch", "users": 20,
                 "arrival_rate_per_min": 400.0,
                 "duration_ms": {"dist": "constant", "value": 20_000},
                 "cpus": {"dist": "choice", "values": [1, 2],
                          "weights": [0.8, 0.2]},
                 "mem": {"dist": "uniform", "low": 64, "high": 512},
                 "priority": {"dist": "constant", "value": 50}},
                # ~12.5k interactive arrivals at higher priority
                {"name": "inter", "users": 5,
                 "arrival_rate_per_min": 500.0,
                 "duration_ms": {"dist": "constant", "value": 5_000},
                 "cpus": 1.0, "mem": 128.0,
                 "priority": {"dist": "constant", "value": 90}},
            ],
        }
        trace = load_trace(generate_trace(spec))
        assert len(trace) >= 50_000, len(trace)
        hosts = load_hosts(generate_hosts(400, cpus=32.0, mem=131072.0))
        sim = Simulator(trace, hosts, backend="tpu",
                        rank_interval_ms=5_000, match_interval_ms=5_000)
        result = sim.run()
        s = result.summary()
        assert result.completed == result.total == len(trace)
        assert s["placements"] >= len(trace)  # retries can add more
        # 400 hosts x 32 cpus ~= 12.8k slots vs ~10.6k concurrent demand:
        # waits stay bounded; the p50 job waits less than two match
        # intervals, the p99 less than a minute of virtual time
        assert s["wait_time_p50_s"] <= 10.0, s
        assert s["wait_time_p99_s"] <= 60.0, s
        # high-priority interactive jobs never starve: their wait must not
        # exceed the batch class's (dru ranks them first within a user,
        # and admission is fair across users)
        waits_by_class = {"batch": [], "inter": []}
        for rec in result.task_records:
            cls = "inter" if rec["user"].startswith("inter") else "batch"
            waits_by_class[cls].append(rec["wait_ms"])
        assert np.median(waits_by_class["inter"]) <= \
            np.median(waits_by_class["batch"]) + 5_000


class TestEndToEnd:
    def test_generated_workload_runs_through_simulator(self):
        spec = {
            "seed": 3, "horizon_ms": 120_000,
            "user_classes": [
                {"name": "u", "users": 2, "arrival_rate_per_min": 5.0,
                 "duration_ms": {"dist": "constant", "value": 5_000},
                 "cpus": 1.0, "mem": 128.0}],
        }
        trace = load_trace(generate_trace(spec))
        hosts = load_hosts(generate_hosts(4, cpus=4.0, mem=4096.0))
        sim = Simulator(trace, hosts, backend="cpu")
        result = sim.run()
        assert result.total == len(trace) > 0
        # ample capacity: everything completes with bounded waits
        assert result.completed == result.total
        s = result.summary()
        assert s["wait_time_p50_s"] < 30.0
        assert s["placements"] == result.total
