"""Pod-construction golden tests: full spec dicts compared field-by-field
for a matrix of job shapes (VERDICT r3 next #8; reference:
task-metadata->pod, scheduler/src/cook/kubernetes/api.clj:1370-1813).

Unlike behavior probes, these pin the ENTIRE compiled spec: any change to
pod construction shows up as an explicit golden diff here."""

import json

from cook_tpu.cluster.k8s.pod_spec import (COOK_WORKDIR, SIDECAR_PORT,
                                           SIDECAR_WORKDIR, build_pod_spec)
from cook_tpu.state import Job, Resources
from cook_tpu.state.schema import Checkpoint, CheckpointMode

U = "11111111-2222-3333-4444-555555555555"


def base_env(job, pool="default", extra=()):
    env = [{"name": "HOST_IP",
            "value_from": {"field_ref": {"field_path": "status.hostIP"}}},
           {"name": "COOK_JOB_UUID", "value": job.uuid},
           {"name": "COOK_JOB_USER", "value": job.user},
           {"name": "COOK_WORKDIR", "value": COOK_WORKDIR},
           {"name": "COOK_POOL", "value": pool},
           {"name": "COOK_JOB_CPUS", "value": str(job.resources.cpus)},
           {"name": "COOK_JOB_MEM_MB", "value": str(job.resources.mem)}]
    if job.resources.gpus:
        env.append({"name": "COOK_JOB_GPUS",
                    "value": str(job.resources.gpus)})
    if job.group:
        env.append({"name": "COOK_JOB_GROUP_UUID", "value": job.group})
    env.extend({"name": k, "value": v} for k, v in sorted(job.env.items()))
    env.extend(extra)
    return env


def sidecar_container(job):
    return {
        "name": "cook-sidecar",
        "image": "cook/sidecar:stable",
        "command": ["cook-sidecar", str(SIDECAR_PORT)],
        "ports": [SIDECAR_PORT],
        "env": [{"name": "COOK_JOB_UUID", "value": job.uuid},
                {"name": "COOK_SANDBOX", "value": COOK_WORKDIR},
                {"name": "COOK_WORKDIR", "value": COOK_WORKDIR},
                {"name": "COOK_FILE_SERVER_PORT",
                 "value": str(SIDECAR_PORT)}],
        "readiness_probe": {"http_get": {"port": SIDECAR_PORT,
                                         "path": "/readiness-probe"}},
        "resources": {"requests": {"cpu": 0.1, "memory_mb": 32.0},
                      "limits": {"memory_mb": 32.0}},
        "volume_mounts": [{"name": "cook-workdir",
                           "mount_path": COOK_WORKDIR, "read_only": True},
                          {"name": "cook-sidecar-workdir",
                           "mount_path": SIDECAR_WORKDIR}],
        "working_dir": SIDECAR_WORKDIR,
    }


def job_container(job, env, mounts=None):
    return {
        "name": "cook-job",
        "image": (job.container or {}).get("image",
                                           "cook/default-runtime:stable"),
        "command": ["/bin/sh", "-c", job.command],
        "env": env,
        "volume_mounts": mounts or [{"name": "cook-workdir",
                                     "mount_path": COOK_WORKDIR}],
        "resources": {
            "requests": {"cpu": job.resources.cpus,
                         "memory_mb": job.resources.mem,
                         "gpu": job.resources.gpus},
            "limits": {"memory_mb": job.resources.mem,
                       "gpu": job.resources.gpus},
        },
        "working_dir": COOK_WORKDIR,
    }


class TestGoldenSpecs:
    def test_plain_job_full_spec(self):
        job = Job(uuid=U, user="alice", command="echo hi",
                  resources=Resources(cpus=2.0, mem=512.0))
        spec = build_pod_spec(job, "default")
        assert spec == {
            "containers": [job_container(job, base_env(job)),
                           sidecar_container(job)],
            "init_containers": [],
            "port_count": 0,
            "volumes": [{"name": "cook-workdir", "empty_dir": {}},
                        {"name": "cook-sidecar-workdir", "empty_dir": {}}],
            "tolerations": [{"key": "cook-pool", "operator": "Equal",
                             "value": "default", "effect": "NoSchedule"}],
            "node_selector": {},
            "priority_class": "cook-pool-default",
            "restart_policy": "Never",
            "labels": {},
        }

    def test_gpu_job_selector_and_toleration(self):
        job = Job(uuid=U, user="alice", command="train",
                  resources=Resources(cpus=4.0, mem=8192.0, gpus=2.0),
                  labels={"gpu-model": "a100"})
        spec = build_pod_spec(job, "gpu", sidecar=False)
        assert spec["node_selector"] == {"gpu-model": "a100"}
        assert spec["tolerations"] == [
            {"key": "cook-pool", "operator": "Equal", "value": "gpu",
             "effect": "NoSchedule"},
            {"key": "nvidia.com/gpu", "operator": "Exists",
             "effect": "NoSchedule"}]
        [c] = spec["containers"]
        assert c["resources"]["requests"]["gpu"] == 2.0
        assert c["resources"]["limits"]["gpu"] == 2.0
        assert spec["priority_class"] == "cook-pool-gpu"

    def test_disk_shm_ports_job(self):
        job = Job(uuid=U, user="bob", command="x",
                  resources=Resources(cpus=1.0, mem=128.0),
                  labels={"disk-type": "ssd", "shm-size-mb": "256"},
                  ports=2)
        spec = build_pod_spec(job, "default", sidecar=False)
        assert spec["node_selector"] == {"disk-type": "ssd"}
        assert {"name": "shm",
                "empty_dir": {"medium": "Memory",
                              "size_limit_mb": 256}} in spec["volumes"]
        [c] = spec["containers"]
        assert {"name": "shm", "mount_path": "/dev/shm"} \
            in c["volume_mounts"]
        assert {"name": "COOK_PORT_COUNT", "value": "2"} in c["env"]
        assert spec["port_count"] == 2

    def test_checkpoint_job_full_init_container(self):
        job = Job(uuid=U, user="alice", command="train",
                  resources=Resources(cpus=1.0, mem=256.0),
                  checkpoint=Checkpoint(mode=CheckpointMode.PERIODIC,
                                        period_sec=300,
                                        volume_mounts=["/ckpt-extra"]))
        spec = build_pod_spec(job, "default", sidecar=False)
        assert spec["init_containers"] == [{
            "name": "checkpoint-init",
            "image": "cook/checkpoint-init:stable",
            "volume_mounts": [{"name": "cook-checkpoint",
                               "mount_path": "/mnt/checkpoint"}],
            "env": [{"name": "COOK_JOB_UUID", "value": U}],
        }]
        [c] = spec["containers"]
        for pair in ({"name": "COOK_CHECKPOINT_MODE", "value": "periodic"},
                     {"name": "COOK_CHECKPOINT_PATH",
                      "value": "/mnt/checkpoint"},
                     {"name": "COOK_CHECKPOINT_PERIOD_SEC",
                      "value": "300"}):
            assert pair in c["env"]
        assert {"name": "cook-checkpoint",
                "empty_dir": {}} in spec["volumes"]
        assert {"name": "cook-checkpoint", "mount_path": "/ckpt-extra",
                "sub_path": "ckpt-extra"} in c["volume_mounts"]

    def test_checkpoint_image_incremental_rollout(self):
        from cook_tpu.policy.incremental import IncrementalConfig
        inc = IncrementalConfig()
        inc.set_many({"checkpoint-init-image": [
            {"value": "ckpt:canary", "portion": 1.0}]})
        job = Job(uuid=U, user="alice", command="x",
                  resources=Resources(cpus=1.0, mem=64.0),
                  checkpoint=Checkpoint(mode=CheckpointMode.AUTO))
        spec = build_pod_spec(job, "default", incremental=inc,
                              sidecar=False)
        assert spec["init_containers"][0]["image"] == "ckpt:canary"

    def test_uri_fetch_modes_survive_the_wire(self):
        job = Job(uuid=U, user="alice", command="x",
                  resources=Resources(cpus=1.0, mem=64.0),
                  uris=[{"value": "http://a/t.tgz", "extract": True,
                         "cache": True},
                        {"value": "http://b/run.sh", "executable": True}])
        spec = build_pod_spec(job, "default", sidecar=False)
        [fetch] = spec["init_containers"]
        assert fetch["name"] == "cook-fetch"
        env = {e["name"]: e["value"] for e in fetch["env"]}
        assert json.loads(env["COOK_URIS_JSON"]) == [
            {"cache": True, "executable": False, "extract": True,
             "value": "http://a/t.tgz"},
            {"cache": False, "executable": True, "extract": False,
             "value": "http://b/run.sh"}]
        assert env["COOK_URIS"] == "http://a/t.tgz;http://b/run.sh"
        assert fetch["working_dir"] == COOK_WORKDIR

    def test_sidecar_incremental_image_and_probe(self):
        from cook_tpu.policy.incremental import IncrementalConfig
        inc = IncrementalConfig()
        inc.set_many({"sidecar-image": [
            {"value": "sidecar:canary", "portion": 1.0}]})
        job = Job(uuid=U, user="alice", command="x",
                  resources=Resources(cpus=1.0, mem=64.0))
        spec = build_pod_spec(job, "default", incremental=inc)
        side = [c for c in spec["containers"]
                if c["name"] == "cook-sidecar"][0]
        assert side["image"] == "sidecar:canary"
        assert side["readiness_probe"] == {
            "http_get": {"port": SIDECAR_PORT, "path": "/readiness-probe"}}
        assert side["ports"] == [SIDECAR_PORT]
        # the sidecar's sandbox view is read-only: it serves files, the
        # job writes them
        ro = [m for m in side["volume_mounts"]
              if m["name"] == "cook-workdir"][0]
        assert ro["read_only"] is True

    def test_user_volumes_golden(self):
        job = Job(uuid=U, user="alice", command="x",
                  resources=Resources(cpus=1.0, mem=64.0),
                  container={"image": "my:img",
                             "volumes": [{"host-path": "/data",
                                          "container-path": "/mnt/data",
                                          "mode": "RO"},
                                         {"host-path": "/scratch"}]})
        spec = build_pod_spec(job, "default", sidecar=False)
        assert {"name": "uservol-1", "host_path": "/data"} \
            in spec["volumes"]
        assert {"name": "uservol-2", "host_path": "/scratch"} \
            in spec["volumes"]
        [c] = spec["containers"]
        assert c["image"] == "my:img"
        assert {"name": "uservol-1", "mount_path": "/mnt/data",
                "read_only": True} in c["volume_mounts"]
        assert {"name": "uservol-2", "mount_path": "/scratch",
                "read_only": False} in c["volume_mounts"]


def test_launch_path_env_carries_instance_identity():
    """build_pod_spec with task_id/rest_url (the KubernetesCluster launch
    call shape) injects the instance identity + scheduler URL vars
    (reference: mesos/task.clj:114-135, kubernetes/api.clj:1440)."""
    job = Job(uuid=U, user="alice", command="true",
              resources=Resources(cpus=1.0, mem=128.0))
    job.instances = ["task-1"]  # the launching task, already recorded
    spec = build_pod_spec(job, "default", task_id="task-1",
                          rest_url="http://cook.example:12321")
    env = {e["name"]: e.get("value")
           for e in spec["containers"][0]["env"]}
    assert env["COOK_INSTANCE_UUID"] == "task-1"
    assert env["COOK_INSTANCE_NUM"] == "0"  # zero PRIOR attempts
    assert env["COOK_SCHEDULER_REST_URL"] == "http://cook.example:12321"
    # the no-task_id compile (goldens) stays free of instance identity
    bare = build_pod_spec(job, "default")
    bare_env = {e["name"] for e in bare["containers"][0]["env"]}
    assert "COOK_INSTANCE_UUID" not in bare_env


def test_docker_parameters_map_to_pod_fields():
    """workdir/env docker parameters translate to pod working_dir and env
    entries (reference: kubernetes/api.clj:1370-1813 honors them; other
    parameters are docker-runtime flags with no pod equivalent)."""
    job = Job(uuid=U, user="alice", command="x",
              resources=Resources(cpus=1.0, mem=64.0),
              container={"image": "img:1",
                         "parameters": [
                             {"key": "workdir", "value": "/srv/app"},
                             {"key": "env", "value": "MODE=fast"},
                             {"key": "label", "value": "ignored=true"}]})
    spec = build_pod_spec(job, "default", sidecar=False)
    [c] = spec["containers"]
    assert c["working_dir"] == "/srv/app"
    assert {"name": "MODE", "value": "fast"} in c["env"]
    assert not any(e["name"] == "label" for e in c["env"])
