"""Tracing spans around scheduler stages (reference: opentracing spans
scheduler.clj:2438, :662-671; tri-recorded durations prometheus_metrics.clj)."""

import threading

from cook_tpu.utils.metrics import registry
from cook_tpu.utils.tracing import span, tracer


def setup_function(_fn):
    tracer.reset()
    registry.reset()


def test_span_records_duration_and_tags():
    with span("match.schedule-once", pool="alpha", jobs=10) as sp:
        sp.set_tag("offers", 5)
    docs = tracer.recent()
    assert len(docs) == 1
    d = docs[0]
    assert d["span"] == "match.schedule-once"
    assert d["pool"] == "alpha"
    assert d["jobs"] == 10 and d["offers"] == 5
    assert d["duration_ms"] >= 0
    assert d["error"] is None
    snap = registry.snapshot()
    assert any("cook_span_duration_seconds" in k
               for k in snap["histogram_counts"])


def test_nested_spans_share_trace_id():
    with span("scheduler.pool-handler", pool="p"):
        with span("match.schedule-once", pool="p"):
            pass
    inner, outer = tracer.recent()
    assert inner["trace_id"] == outer["trace_id"]
    assert inner["parent_id"] == outer["span_id"]
    assert outer["parent_id"] is None
    assert tracer.traces(inner["trace_id"]) == [inner, outer]


def test_span_captures_error():
    try:
        with span("rank.cycle"):
            raise ValueError("boom")
    except ValueError:
        pass
    (d,) = tracer.recent()
    assert "ValueError: boom" == d["error"]


def test_none_tags_dropped():
    with span("x", pool=None, cluster="c"):
        pass
    (d,) = tracer.recent()
    assert "pool" not in d and d["cluster"] == "c"


def test_threads_have_independent_stacks():
    errs = []

    def worker():
        try:
            with span("worker.span"):
                assert tracer.current().name == "worker.span"
        except Exception as e:  # pragma: no cover
            errs.append(e)

    with span("main.span"):
        t = threading.Thread(target=worker)
        t.start()
        t.join()
        assert tracer.current().name == "main.span"
    assert not errs
    names = {d["span"] for d in tracer.recent()}
    assert names == {"worker.span", "main.span"}
    # the worker span must not have been parented under main.span
    wdoc = [d for d in tracer.recent() if d["span"] == "worker.span"][0]
    assert wdoc["parent_id"] is None


def test_scheduler_cycles_emit_spans():
    from cook_tpu.cluster import FakeCluster, FakeHost
    from cook_tpu.config import Config
    from cook_tpu.sched import Scheduler
    from cook_tpu.state import Job, Resources, Store, new_uuid

    store = Store()
    cluster = FakeCluster("fake-1", [FakeHost(
        hostname="h0", capacity=Resources(cpus=8.0, mem=8192.0))])
    config = Config()
    config.default_matcher.backend = "cpu"
    sched = Scheduler(store, config, [cluster], rank_backend="cpu")
    store.create_jobs([Job(uuid=new_uuid(), user="alice", command="true",
                           pool="default",
                           resources=Resources(cpus=1.0, mem=100.0))])
    tracer.reset()
    sched.step_rank()
    sched.step_match()
    docs = tracer.recent(limit=1000)
    names = {d["span"] for d in docs}
    assert {"rank.cycle", "rank.pool", "scheduler.pool-handler",
            "match.schedule-once", "cluster.launch-tasks"} <= names
    # pool-handler and its kernel dispatch share one trace
    handler = [d for d in docs if d["span"] == "scheduler.pool-handler"][0]
    kernel = [d for d in docs if d["span"] == "match.schedule-once"][0]
    assert kernel["trace_id"] == handler["trace_id"]
    assert kernel["parent_id"] == handler["span_id"]
    assert kernel["backend"] == "cpu"
