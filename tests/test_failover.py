"""Quorum-aware lossless failover: candidate ranking, coordinated
promotion (standby→standby delta pull), old-leader fencing, and the
indeterminate-commit contract.

Layered like the protocol itself:

- pure promotion-ordering logic (rank_key / choose_successor /
  candidate_position / assert_promotable) — no native library needed;
- indeterminate commits at the store and REST/client layers over a stub
  replication server — the phantom-commit hole (ADVICE r5) closed;
- the election medium's candidate-position plane (file sidecars and
  lease annotations);
- the full multi-standby chaos scenarios over REAL socket replication
  (tier-1 smoke with fixed winners; multi-seed soak is ``slow``).
"""

import json
import urllib.error
import urllib.request

import pytest

from cook_tpu.state import replication as repl
from cook_tpu.state.store import (
    ReplicationIndeterminate,
    ReplicationTimeout,
    Store,
)
from cook_tpu.state.schema import Job, Resources


def make_job(i, user="alice"):
    return Job(uuid=f"00000000-0000-0000-0000-{i:012d}", user=user,
               command=f"echo {i}", resources=Resources(cpus=1, mem=64))


# --------------------------------------------------------------------------
# Promotion-ordering logic (satellite: successor-logic edge cases)
# --------------------------------------------------------------------------

class TestCandidateRanking:
    def test_candidate_position_genesis(self, tmp_path):
        d = tmp_path / "genesis"
        d.mkdir()
        pos = repl.candidate_position(str(d))
        assert pos == {"epoch": 0, "offset": 0, "synced": False,
                       "began": False}

    def test_candidate_position_token_but_never_synced(self, tmp_path):
        d = tmp_path / "m"
        d.mkdir()
        (d / "repl_token").write_text("tok")
        (d / "journal.jsonl").write_bytes(b'{"tx": 1}\n{"torn')
        repl.record_followed_epoch(str(d), 3)
        pos = repl.candidate_position(str(d))
        assert pos["began"] and not pos["synced"]
        assert pos["epoch"] == 3
        # torn tail doesn't count: only whole records were ever acked
        assert pos["offset"] == len(b'{"tx": 1}\n')

    def test_rank_synced_beats_unsynced_then_epoch_then_offset(self):
        unsynced_big = {"synced": False, "epoch": 9, "offset": 10 ** 9}
        synced_old = {"synced": True, "epoch": 1, "offset": 10}
        synced_new_short = {"synced": True, "epoch": 2, "offset": 5}
        synced_new_long = {"synced": True, "epoch": 2, "offset": 50}
        ranked = sorted([unsynced_big, synced_old, synced_new_short,
                         synced_new_long], key=repl.rank_key)
        assert ranked == [unsynced_big, synced_old, synced_new_short,
                          synced_new_long]

    def test_choose_successor_prefers_strictly_ahead_synced_peer(self):
        me = {"synced": True, "epoch": 2, "offset": 100}
        peers = {
            "never-synced": {"synced": False, "epoch": 2,
                             "offset": 10 ** 9},           # holds nothing
            "lagged": {"synced": True, "epoch": 2, "offset": 50},
            "ahead": {"synced": True, "epoch": 2, "offset": 200},
            "older-leadership": {"synced": True, "epoch": 1,
                                 "offset": 10 ** 9},
        }
        peer_id, pos = repl.choose_successor(me, peers)
        assert peer_id == "ahead" and pos["offset"] == 200

    def test_choose_successor_none_when_best(self):
        me = {"synced": True, "epoch": 2, "offset": 100}
        assert repl.choose_successor(me, {
            "b": {"synced": True, "epoch": 2, "offset": 100},  # tie: me
            "c": {"synced": False, "epoch": 3, "offset": 999},
        }) is None

    def test_choose_successor_ignores_stale_ghosts(self):
        me = {"synced": True, "epoch": 2, "offset": 100}
        ghost = {"synced": True, "epoch": 2, "offset": 999, "ts": 0.0}
        assert repl.choose_successor(me, {"g": ghost}, now=100.0,
                                     stale_s=10.0) is None
        fresh = dict(ghost, ts=95.0)
        assert repl.choose_successor(me, {"g": fresh}, now=100.0,
                                     stale_s=10.0) == ("g", fresh)

    def test_assert_promotable_cases(self, tmp_path):
        # genesis (never followed): allowed
        d = tmp_path / "a"
        d.mkdir()
        repl.assert_promotable(str(d))
        # began following, never synced: refused
        (d / "repl_following").write_text("1")
        with pytest.raises(RuntimeError, match="never reached"):
            repl.assert_promotable(str(d))
        (d / "repl_token").write_text("tok")
        with pytest.raises(RuntimeError, match="never reached"):
            repl.assert_promotable(str(d))
        # once-synced (even if since lagged): passes the GATE — ordering
        # among synced candidates is choose_successor's job
        (d / "repl_synced").write_text("1")
        repl.assert_promotable(str(d))


# --------------------------------------------------------------------------
# Indeterminate commits (stub replication server; no native lib needed)
# --------------------------------------------------------------------------

class _StubRepl:
    """Minimal attach_replication target: scripted ack outcomes."""

    def __init__(self, acks=(True,), synced=1):
        self.acks = list(acks)
        self.synced = synced
        self.directory = ""
        self.port = 0

    def poke(self):
        pass

    def wait_acked(self, offset, timeout_s=0.0):
        return self.acks.pop(0) if self.acks else True

    @property
    def synced_follower_count(self):
        return self.synced

    def min_acked(self):
        return -1

    def status(self):
        return []


class TestIndeterminateCommit:
    def test_unacked_commit_is_indeterminate_not_aborted(self, tmp_path):
        store = Store.open(str(tmp_path / "d"))
        store.attach_replication(_StubRepl(acks=[False]), sync=True,
                                 timeout_s=0.01)
        job = make_job(1)
        with pytest.raises(ReplicationIndeterminate):
            store.create_jobs([job])
        # applied locally — NOT rolled back...
        assert store.job(job.uuid) is not None
        # ...and the record stays in the journal: the next open (this
        # leader surviving, or its mirror promoting) resolves it as
        # committed instead of resurrecting a phantom
        store.close()
        replayed = Store.replay_only(str(tmp_path / "d"))
        assert replayed.job(job.uuid) is not None

    def test_quorum_gate_still_aborts_cleanly_before_write(self,
                                                           tmp_path):
        store = Store.open(str(tmp_path / "d"))
        store.attach_replication(_StubRepl(synced=0), sync=True,
                                 timeout_s=0.01, min_followers=1)
        job = make_job(1)
        with pytest.raises(ReplicationTimeout):
            store.create_jobs([job])
        # a clean abort: nothing installed, nothing journaled
        assert store.job(job.uuid) is None
        store.close()
        assert Store.replay_only(str(tmp_path / "d")).job(job.uuid) is None

    def test_repl_ack_fault_point_injects_indeterminate(self, tmp_path):
        from cook_tpu.utils.faults import injector
        store = Store.open(str(tmp_path / "d"))
        store.attach_replication(_StubRepl(), sync=True)
        injector.arm("repl.ack", probability=1.0, max_fires=1)
        try:
            with pytest.raises(ReplicationIndeterminate):
                store.create_jobs([make_job(1)])
        finally:
            injector.disarm("repl.ack")
        assert store.job(make_job(1).uuid) is not None


@pytest.fixture()
def rest_pair(tmp_path):
    """ApiServer over a journaled store with a scriptable stub repl."""
    from cook_tpu.rest.api import ApiServer, CookApi
    store = Store.open(str(tmp_path / "rest"))
    stub = _StubRepl(acks=[])
    store.attach_replication(stub, sync=True, timeout_s=0.01)
    api = CookApi(store)
    server = ApiServer(api)
    server.start()
    yield store, stub, api, server
    server.stop()
    store.close()


class TestIndeterminateRest:
    def test_504_with_ambiguous_body_and_client_retry_heals(
            self, rest_pair):
        from cook_tpu.client import JobClient, JobClientError
        store, stub, _api, server = rest_pair
        client = JobClient(server.url, user="alice")
        # both the create txn and the latch commit go unconfirmed: the
        # worst case — jobs journaled but possibly stranded uncommitted
        stub.acks = [False, False]
        with pytest.raises(JobClientError) as e:
            client.submit([{"command": "x",
                            "uuid": "00000000-0000-4000-8000-0000000000aa"}],
                          indeterminate_retries=0)
        assert e.value.status == 504
        assert e.value.indeterminate
        assert e.value.body["jobs"] == [
            "00000000-0000-4000-8000-0000000000aa"]
        # replication heals (acks flow again); the client retry of the
        # SAME batch — the manual form of the auto-retry — must neither
        # lose nor duplicate the job
        stub.acks = []
        uuids = client.submit(
            [{"command": "x",
              "uuid": "00000000-0000-4000-8000-0000000000aa"}],
            idempotent=True)
        assert uuids == ["00000000-0000-4000-8000-0000000000aa"]
        [job] = client.query(uuids)
        assert job["uuid"] == uuids[0]
        # exactly one job exists (visible and committed)
        assert len(store.jobs_where(lambda j: True)) == 1
        # the stranded latch was reaped by the heal — it must not leak
        # into every future checkpoint/replay
        assert store._latches == {}

    def test_client_auto_retry_rides_out_one_indeterminate(self,
                                                           rest_pair):
        from cook_tpu.client import JobClient
        store, stub, _api, server = rest_pair
        client = JobClient(server.url, user="alice")
        stub.acks = [False, False]  # first attempt: create+latch unacked
        uuids = client.submit([{"command": "y"}])  # default retries
        assert len(uuids) == 1
        assert store.job(uuids[0]) is not None
        assert len(store.jobs_where(lambda j: True)) == 1

    def test_retry_after_lost_commit_recreates(self, rest_pair):
        """The other future: the commit was LOST in the failover (the
        promoted mirror never had it).  The same idempotent retry simply
        creates the job — nothing lost, nothing duplicated."""
        from cook_tpu.client import JobClient
        _store, _stub, api, server = rest_pair
        api.store = Store()  # "promoted" store that missed the commit
        client = JobClient(server.url, user="alice")
        body = {"jobs": [{"command": "z",
                          "uuid": "00000000-0000-4000-8000-0000000000bb"}],
                "idempotent": True}
        req = urllib.request.Request(
            server.url + "/jobs", method="POST",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json",
                     "X-Cook-User": "alice"})
        with urllib.request.urlopen(req, timeout=5) as resp:
            assert json.load(resp)["jobs"] == [
                "00000000-0000-4000-8000-0000000000bb"]
        assert api.store.job(
            "00000000-0000-4000-8000-0000000000bb") is not None

    def test_idempotent_refuses_foreign_uuid(self, rest_pair):
        from cook_tpu.client import JobClient, JobClientError
        _store, _stub, api, server = rest_pair
        mallory = JobClient(server.url, user="mallory")
        alice = JobClient(server.url, user="alice")
        [uuid] = alice.submit([{"command": "a"}])
        body = {"jobs": [{"command": "a", "uuid": uuid}],
                "idempotent": True}
        req = urllib.request.Request(
            server.url + "/jobs", method="POST",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json",
                     "X-Cook-User": "mallory"})
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=5)
        assert e.value.code == 409


class TestRestFencing:
    def test_superseded_leader_rejects_writes_serves_reads(self,
                                                           rest_pair):
        _store, _stub, api, server = rest_pair
        api.fence_guard = lambda: True  # a successor minted a higher epoch
        req = urllib.request.Request(
            server.url + "/jobs", method="POST",
            data=json.dumps({"jobs": [{"command": "x"}]}).encode(),
            headers={"Content-Type": "application/json",
                     "X-Cook-User": "alice"})
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=5)
        assert e.value.code == 503
        # reads still answer (clients re-resolve the leader themselves)
        with urllib.request.urlopen(server.url + "/jobs?user=alice",
                                    timeout=5) as resp:
            assert resp.status == 200
        # local debug surfaces are never fenced
        with urllib.request.urlopen(server.url + "/debug/replication",
                                    timeout=5) as resp:
            doc = json.load(resp)
        assert doc["role"] in ("none", "leader", "standby")


# --------------------------------------------------------------------------
# The election medium's candidate-position plane
# --------------------------------------------------------------------------

class TestCandidatePublication:
    def test_file_elector_sidecars_roundtrip(self, tmp_path):
        from cook_tpu.sched.election import FileLeaderElector
        a = FileLeaderElector(tmp_path / "lock", "http://a")
        b = FileLeaderElector(tmp_path / "lock", "http://b")
        a.publish_candidate("node a!", {"epoch": 1, "offset": 10,
                                        "synced": True})
        b.publish_candidate("node-b", {"epoch": 1, "offset": 20,
                                       "synced": False})
        got = a.read_candidates()
        assert got["node-a"]["offset"] == 10  # id sanitized for the fs
        assert got["node-b"]["synced"] is False
        a.clear_candidate("node a!")
        assert "node-a" not in b.read_candidates()

    def test_lease_elector_annotations_roundtrip(self):
        from cook_tpu.cluster.k8s.fake_api import FakeKubernetesApi
        from cook_tpu.sched.election import LeaseLeaderElector
        api = FakeKubernetesApi()
        clock = {"t": 0.0}
        a = LeaseLeaderElector(api, "node-a", "http://a:1",
                               clock=lambda: clock["t"])
        a.publish_candidate("node-a", {"epoch": 2, "offset": 7,
                                       "synced": True})
        # positions survive the holder's renewals (the lease is replaced
        # wholesale on every acquire — annotations must be preserved)
        assert a.try_once()
        got = a.read_candidates()
        assert got == {"node-a": {"epoch": 2, "offset": 7,
                                  "synced": True}}
        a.clear_candidate("node-a")
        assert a.read_candidates() == {}


# --------------------------------------------------------------------------
# Multi-standby chaos over real socket replication
# --------------------------------------------------------------------------

needs_native = pytest.mark.skipif(not repl.replication_available(),
                                  reason="C++ toolchain unavailable")


@needs_native
@pytest.mark.chaos
def test_failover_chaos_laggard_winner_pulls_delta(tmp_path):
    """Leader SIGKILL with one fault-lagged standby, where the LAGGARD
    wins the lock race: candidate ranking must still make the advanced
    mirror the authority — the winner pulls the delta first; zero
    committed transactions lost; the loser re-follows and converges."""
    from cook_tpu.sim.chaos import FailoverChaosConfig, run_failover_chaos
    r = run_failover_chaos(FailoverChaosConfig(
        seed=7, leader_mode="sigkill", winner="laggard",
        data_root=str(tmp_path)))
    assert r.ok, r.violations
    assert r.winner_was_laggard and r.delta_pulled
    assert r.laggard_converged
    assert r.indeterminate_commits == 1


@needs_native
@pytest.mark.chaos
def test_failover_chaos_partitioned_old_leader_is_fenced(tmp_path):
    """A partitioned-but-alive deposed leader: journal appends AND REST
    writes rejected, no split brain, and the successor holds every
    committed transaction (the advanced standby promotes directly)."""
    from cook_tpu.sim.chaos import FailoverChaosConfig, run_failover_chaos
    r = run_failover_chaos(FailoverChaosConfig(
        seed=7, leader_mode="partition", winner="advanced",
        data_root=str(tmp_path)))
    assert r.ok, r.violations
    assert not r.winner_was_laggard and not r.delta_pulled
    assert r.fenced_appends_rejected == 1
    assert r.fenced_rest_writes_rejected == 1
    assert r.laggard_converged


@needs_native
@pytest.mark.chaos
@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_failover_chaos_soak(tmp_path, seed):
    """Multi-seed soak: seeded winner/mode coin flips cover every
    combination of lock-race outcome and leader-death flavor."""
    import random
    from cook_tpu.sim.chaos import FailoverChaosConfig, run_failover_chaos
    rng = random.Random(seed)
    r = run_failover_chaos(FailoverChaosConfig(
        seed=seed,
        leader_mode=rng.choice(["sigkill", "partition"]),
        n_jobs_before_lag=30, n_jobs_after_lag=20,
        data_root=str(tmp_path)))
    assert r.ok, r.violations
    assert r.laggard_converged
    assert r.indeterminate_commits == 1


@needs_native
def test_daemon_replicated_failover_end_to_end(tmp_path):
    """Two in-process CookDaemons over real socket replication: the
    standby publishes candidate positions while following, and on
    leader handoff runs the COORDINATED promotion path (candidacy
    window, ranking, fence authority, /debug/replication role flip)
    with every committed job surviving."""
    from cook_tpu.client import JobClient
    from cook_tpu.daemon import CookDaemon

    election = tmp_path / "election"
    election.mkdir()

    def conf(node):
        return {
            "host": "127.0.0.1", "port": 0,
            "data_dir": str(tmp_path / f"data-{node}"),
            "election_dir": str(election),
            "replication": {"listen_port": 0, "sync": True,
                            "candidacy_window_seconds": 0.2,
                            "position_interval_seconds": 0.1},
            "clusters": [{"factory": "cook_tpu.cluster.fake.factory",
                          "kwargs": {"name": f"fake-{node}",
                                     "n_hosts": 2}}],
            "scheduler": {"rank_backend": "cpu", "cycle_mode": "split",
                          "match_interval_seconds": 0.1,
                          "rank_interval_seconds": 0.1},
        }

    def wait_for(pred, timeout=20.0):
        import time as _t
        deadline = _t.time() + timeout
        while _t.time() < deadline:
            if pred():
                return True
            _t.sleep(0.05)
        return bool(pred())

    a = CookDaemon(conf("a"))
    b = None
    try:
        a.start()
        assert wait_for(lambda: a.scheduler is not None), \
            "node A never took leadership"
        b = CookDaemon(conf("b"))
        b.start()
        # the standby mirrors and publishes its candidate position into
        # the election medium (the ranking inputs of a future failover)
        assert wait_for(lambda: a.repl_server is not None
                        and a.repl_server.synced_follower_count >= 1)
        assert wait_for(lambda: any(
            pos.get("synced")
            for nid, pos in a.elector.read_candidates().items()
            if nid != a._node_id)), "standby never published synced"
        client_a = JobClient(a.node_url, user="alice")
        uuids = client_a.submit([{"command": "sleep 999", "cpus": 1,
                                  "mem": 64} for _ in range(3)])
        # leader writes return the commit position (the read-your-writes
        # token the follower fleet honors)
        assert client_a.last_commit_offset
        panel = client_a.debug_replication()
        assert panel["role"] == "leader" and panel["epoch"] == 1
        assert panel["synced_followers"] >= 1
        # group commit is armed on the promoted leader by default
        assert panel.get("group_commit") is not None
        # ---- the standby's READ FLEET serves GETs locally ------------
        assert b.read_view is not None
        # the REST layer serves the VIEW's store (the initial on_swap
        # must land even if the mirror never re-bases again — a dropped
        # swap would freeze api.store at the boot-time replay)
        assert b.api.store is b.read_view.store
        assert wait_for(lambda: b.read_view.offset
                        >= a.store.commit_offset())
        import http.client as _hc
        conn = _hc.HTTPConnection(
            b.node_url.replace("http://", ""), timeout=10)
        conn.request("GET", f"/jobs/{uuids[0]}",
                     headers={"X-Cook-User": "alice"})
        resp = conn.getresponse()
        body = json.loads(resp.read())
        conn.close()
        assert resp.status == 200, "standby redirected instead of serving"
        assert resp.getheader("X-Cook-Replication-Offset") is not None
        assert resp.getheader("X-Cook-Replication-Age-Ms") is not None
        assert body["uuid"] == uuids[0]
        assert b.api.follower_reads >= 1
        # read-your-writes THROUGH the standby: the min-offset token is
        # satisfied by the synced mirror (no redirect needed)
        reader = JobClient(b.node_url, user="alice")
        reader.last_commit_offset = client_a.last_commit_offset
        got = {j["uuid"] for j in reader.query(uuids)}
        assert got == set(uuids)
        # ---- handoff: A dies; B must promote with every job ----------
        a.shutdown()
        assert wait_for(lambda: b.scheduler is not None, timeout=30), \
            "standby never promoted"
        client_b = JobClient(b.node_url, user="alice")
        got = {j["uuid"] for j in client_b.query(uuids)}
        assert got == set(uuids), "committed jobs lost in failover"
        panel = client_b.debug_replication()
        assert panel["role"] == "leader" and panel["epoch"] == 2
        # promotion retired the read view: B serves as the authority now
        assert b.read_view is None and b.api.read_view is None
        # the promoted store fences against the SHARED election epoch
        assert str(b.store._epoch_path) == str(a.elector.epoch_path)
        # ---- the promoted leader's followers re-sync and serve -------
        c = CookDaemon(conf("c"), api_only=True)
        try:
            c.start()
            assert wait_for(lambda: b.repl_server is not None
                            and b.repl_server.synced_follower_count >= 1
                            ), "new standby never synced to the winner"
            assert c.read_view is not None
            assert wait_for(lambda: c.read_view.offset
                            >= b.store.commit_offset())
            reader_c = JobClient(c.node_url, user="alice")
            reader_c.last_commit_offset = client_b.last_commit_offset
            got = {j["uuid"] for j in reader_c.query(uuids)}
            assert got == set(uuids), \
                "re-synced follower does not serve the winner's state"
        finally:
            c.shutdown()
    finally:
        if b is not None:
            b.shutdown()
        a.shutdown()
