"""Serving-plane request observability (ISSUE 9, docs/OBSERVABILITY.md
"tracing one request"): end-to-end trace propagation from client to
launch, RED metrics on every endpoint (templated labels), the request-id
error contract, the capture rings, gzip on the observability surfaces,
and the /debug/health roll-up.
"""

import gzip
import json
import time
import urllib.request
import uuid as uuidlib

import pytest

from cook_tpu.client import JobClient, JobClientError
from cook_tpu.config import Config, HttpConfig
from cook_tpu.rest import ApiServer, CookApi
from cook_tpu.rest import instrument
from cook_tpu.rest.api import API_ROUTES
from cook_tpu.state import Resources, Store
from cook_tpu.utils.metrics import registry
from cook_tpu.utils.tracing import (make_traceparent, parse_traceparent,
                                    tracer)


@pytest.fixture(autouse=True)
def _clean_observability():
    registry.reset()
    tracer.reset()
    tracer.enabled = True
    tracer.io_spans = True
    instrument.request_log.reset()
    instrument.request_log.enabled = True
    yield
    registry.reset()
    tracer.reset()
    instrument.request_log.reset()
    instrument.request_log.enabled = True


@pytest.fixture()
def server():
    store = Store()
    api = CookApi(store, admins=["admin"])
    srv = ApiServer(api)
    srv.start()
    yield srv, store, api
    srv.stop()


def wait_until(cond, timeout=3.0):
    """The http.request span closes AFTER the response bytes hit the
    socket (the write is part of the measured request), so span/metric
    asserts made immediately after a client call can beat the server
    thread by microseconds — poll briefly instead of racing it."""
    import time
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        result = cond()
        if result:
            return result
        time.sleep(0.005)
    return cond()


def _http(url, method="GET", body=None, headers=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


# ---------------------------------------------------------------------------
# W3C trace-context helpers
# ---------------------------------------------------------------------------

class TestTraceparent:
    def test_roundtrip_internal_ids(self):
        tp = make_traceparent("a" * 16, "b" * 16)
        assert tp == f"00-{'0' * 16}{'a' * 16}-{'b' * 16}-01"
        assert parse_traceparent(tp) == ("a" * 16, "b" * 16)

    def test_full_width_trace_id_kept(self):
        tid = uuidlib.uuid4().hex
        assert parse_traceparent(f"00-{tid}-{'c' * 16}-01") == \
            (tid, "c" * 16)

    @pytest.mark.parametrize("bad", [
        None, "", "garbage", "00-zz-cc-01",
        "00-" + "0" * 32 + "-" + "c" * 16 + "-01",   # all-zero trace
        "00-" + "a" * 30 + "-" + "c" * 16 + "-01",   # short trace
    ])
    def test_malformed_headers_ignored(self, bad):
        assert parse_traceparent(bad) is None


# ---------------------------------------------------------------------------
# RED metrics: the golden endpoint-table walk
# ---------------------------------------------------------------------------

class TestRedMetrics:
    def test_every_registered_endpoint_emits_red_metrics(self, server):
        """Walk the WHOLE route table: every endpoint — success or error
        — must emit cook_http_requests with the TEMPLATED endpoint label
        (never the raw uuid) and a duration histogram observation."""
        srv, _store, _api = server
        raw_uuid = str(uuidlib.uuid4())
        for method, path, _summary, _leader in API_ROUTES:
            concrete = path.replace("{uuid}", raw_uuid) \
                           .replace("{task_id}", raw_uuid) \
                           .replace("{name}", "c1")
            body = {} if method in ("POST", "PUT") else None
            _http(srv.url + concrete, method=method, body=body,
                  headers={"X-Cook-User": "nobody"})
        def _counts():
            c = {}
            for labels, _v in registry.series("cook_http_requests"):
                c.setdefault((labels["method"], labels["endpoint"]), 0)
                c[(labels["method"], labels["endpoint"])] += _v
            return c if len(c) >= len({(m, pth) for m, pth, _s, _l
                                       in API_ROUTES}) else None
        wait_until(lambda: _counts() is not None)
        counted = {}
        for labels, value in registry.series("cook_http_requests"):
            counted.setdefault((labels["method"], labels["endpoint"]),
                               0)
            counted[(labels["method"], labels["endpoint"])] += value
            assert raw_uuid not in labels["endpoint"]
        for method, path, _summary, _leader in API_ROUTES:
            assert counted.get((method, path), 0) >= 1, \
                f"no RED metric for {method} {path}"
        # duration histograms exist per endpoint template too
        text = registry.expose()
        assert 'cook_http_request_duration_seconds_count' in text
        assert 'endpoint="/jobs/{uuid}"' in text

    def test_unknown_paths_fold_to_unmatched(self, server):
        srv, _store, _api = server
        for i in range(3):
            _http(srv.url + f"/no/such/endpoint-{i}")
        # a wrong-METHOD probe against a known path must not skew that
        # endpoint's series either
        _http(srv.url + "/metrics", method="DELETE")

        def seen():
            return {(lbl["method"], lbl["endpoint"]) for lbl, _v in
                    registry.series("cook_http_requests")}

        # wait for the DELETE's series specifically: the earlier GETs
        # already satisfy a bare "any series" condition while the last
        # request's finally-block recording is still in flight
        wait_until(lambda: ("DELETE", instrument.UNMATCHED) in seen())
        endpoints = seen()
        assert any(e == instrument.UNMATCHED for _m, e in endpoints)
        assert not any("no/such" in e for _m, e in endpoints)
        assert ("DELETE", "/metrics") not in endpoints
        assert ("DELETE", instrument.UNMATCHED) in endpoints

    def test_malformed_content_length_still_answered(self, server):
        """A garbage Content-Length must get an HTTP error response, not
        a dropped connection (the instrumented prologue parses it)."""
        import socket
        srv, _store, _api = server
        with socket.create_connection((srv.host, srv.port),
                                      timeout=5) as s:
            s.sendall(b"POST /jobs HTTP/1.1\r\nHost: x\r\n"
                      b"Content-Length: abc\r\n\r\n")
            head = s.recv(4096).decode(errors="replace")
        assert head.startswith("HTTP/1.1 "), head
        status = int(head.split()[1])
        assert 400 <= status < 600

    def test_inflight_gauge_and_request_bytes(self, server):
        srv, _store, _api = server
        client = JobClient(srv.url, user="alice")
        client.submit([{"command": "true"}])
        # begin() publishes 1, end() publishes 0 after the response hit
        # the socket — wait for the settle
        wait_until(lambda: registry.series("cook_http_inflight")
                   == [({}, 0.0)])
        assert registry.series("cook_http_inflight") == [({}, 0.0)]
        text = registry.expose()
        assert "cook_http_request_bytes_bucket" in text


# ---------------------------------------------------------------------------
# Propagation: client traceparent -> server root span -> I/O children
# ---------------------------------------------------------------------------

class TestPropagation:
    def test_client_traceparent_becomes_server_root_span(self, server):
        srv, store, _api = server
        client = JobClient(srv.url, user="alice")
        [uuid] = client.submit([{"command": "true"}])
        assert client.last_trace_id
        spans = wait_until(lambda: [
            d for d in tracer.finished
            if d["span"] == "http.request"
            and d.get("endpoint") == "/jobs"])
        assert spans, "no http.request span recorded"
        root = spans[-1]
        assert root["trace_id"] == client.last_trace_id
        assert root["method"] == "POST"
        assert root["status"] == 200
        assert root["user"] == "alice"
        # the job is stamped with the request trace
        assert store.job(uuid).trace_id == client.last_trace_id
        # ... and the submitted audit event records it
        [sub] = [e for e in store.audit.timeline(uuid)
                 if e["kind"] == "submitted"]
        assert sub["data"]["trace"] == client.last_trace_id

    def test_explicit_traceparent_header(self, server):
        srv, _store, _api = server
        tid = uuidlib.uuid4().hex
        _http(srv.url + "/pools",
              headers={"traceparent": f"00-{tid}-{'d' * 16}-01"})
        [sp] = wait_until(lambda: [
            d for d in tracer.finished
            if d["span"] == "http.request" and d["trace_id"] == tid])
        assert sp["parent_id"] == "d" * 16

    def test_journal_and_ack_wait_spans_nest_under_request(
            self, tmp_path):
        """A sync-replicated write's journal append and replication
        ack wait are children of the http.request root — the per-phase
        decomposition the slow-request ring serves."""

        class _StubRepl:
            fenced = False
            synced_follower_count = 1

            def poke(self):
                pass

            def wait_acked(self, offset, timeout_s):
                return True

        store = Store.open(str(tmp_path))
        store.attach_replication(_StubRepl(), sync=True)
        api = CookApi(store)
        srv = ApiServer(api)
        srv.start()
        try:
            client = JobClient(srv.url, user="alice")
            client.submit([{"command": "true"}])
            root = wait_until(lambda: [
                d for d in tracer.finished
                if d["span"] == "http.request"])[-1]
            by_name = {d["span"]: d for d in tracer.finished}
            for name in ("journal.append", "repl.ack_wait"):
                sp = by_name[name]
                assert sp["trace_id"] == client.last_trace_id
                assert sp["parent_id"] == root["span_id"], name
            # the capture ring recorded the ack-wait phase share
            snap = instrument.request_log.snapshot()
            rec = [r for r in snap["recent"]
                   if r["method"] == "POST"][-1]
            assert "repl.ack_wait" in rec["phases_ms"]
            assert "journal.append" in rec["phases_ms"]
        finally:
            srv.stop()

    def test_no_io_spans_without_active_trace(self, tmp_path):
        """A bare-store bulk write (no request, no cycle) opens no
        journal spans — the bulk-load path stays span-free."""
        from cook_tpu.state import Job, new_uuid
        store = Store.open(str(tmp_path))
        store.create_jobs([Job(uuid=new_uuid(), user="u",
                               command="x")])
        assert not any(d["span"] == "journal.append"
                       for d in tracer.finished)


# ---------------------------------------------------------------------------
# Request-id contract
# ---------------------------------------------------------------------------

class TestRequestId:
    def test_minted_and_echoed_on_success(self, server):
        srv, _store, _api = server
        status, headers, _body = _http(srv.url + "/pools")
        assert status == 200
        assert headers.get("X-Cook-Request-Id")

    def test_client_sent_id_echoed_verbatim(self, server):
        srv, _store, _api = server
        _status, headers, _body = _http(
            srv.url + "/pools",
            headers={"X-Cook-Request-Id": "my-req-42"})
        assert headers.get("X-Cook-Request-Id") == "my-req-42"

    def test_error_body_carries_request_id(self, server):
        srv, _store, _api = server
        client = JobClient(srv.url, user="alice")
        with pytest.raises(JobClientError) as err:
            client.job(str(uuidlib.uuid4()))
        assert err.value.status == 404
        assert err.value.request_id
        # the ring's record carries the same id — a pasted error report
        # joins to the capture ring.  The keep-alive client can observe
        # the response a hair before the server's finally-block records
        # it, so poll briefly.
        deadline = time.time() + 2.0
        ids: set = set()
        while err.value.request_id not in ids and time.time() < deadline:
            ids = {r["request_id"] for r
                   in instrument.request_log.snapshot()["recent"]}
            time.sleep(0.01)
        assert err.value.request_id in ids


# ---------------------------------------------------------------------------
# Capture rings (/debug/requests)
# ---------------------------------------------------------------------------

class TestDebugRequests:
    def test_slow_ring_and_redaction(self, server):
        srv, _store, api = server
        api.request_obs.slow_ms = 0.0  # everything is "slow"
        client = JobClient(srv.url, user="alice")
        _http(srv.url + "/share?user=alice&token=hunter2",
              headers={"X-Cook-User": "alice"})
        # same race as the request-id join above: the client can see the
        # /share response a hair before the finally-block records it
        deadline = time.time() + 2.0
        doc: dict = {}
        while not doc.get("slow") and time.time() < deadline:
            doc = client.debug_requests(limit=10)
            time.sleep(0.01)
        assert doc["slow"], "slow ring empty with threshold 0"
        rec = [r for r in doc["slow"]
               if r["endpoint"] == "/share"][-1]
        assert rec["params"]["token"] == ["[redacted]"]
        assert rec["params"]["user"] == ["alice"]
        assert rec["duration_ms"] >= 0
        assert rec["request_id"]

    def test_snapshot_limit_zero_is_totals_only(self, server):
        srv, _store, _api = server
        _http(srv.url + "/pools")
        wait_until(
            lambda: instrument.request_log.snapshot(limit=5)["recent"])
        snap = instrument.request_log.snapshot(limit=0)
        assert snap["recent"] == [] and snap["slow"] == []
        assert snap["totals"]["requests_s"] > 0

    def test_ring_is_bounded(self, server):
        srv, _store, api = server
        api.request_obs.configure(HttpConfig(request_log=8, slow_log=4))
        for _ in range(20):
            _http(srv.url + "/pools")
        snap = instrument.request_log.snapshot(limit=100)
        assert len(snap["recent"]) <= 8
        api.request_obs.configure(HttpConfig())

    def test_observe_off_still_echoes_request_ids(self, server):
        srv, _store, api = server
        api.request_obs.enabled = False
        status, headers, _ = _http(srv.url + "/pools")
        assert status == 200
        assert headers.get("X-Cook-Request-Id")
        assert not instrument.request_log.snapshot()["recent"]
        assert not any(d["span"] == "http.request"
                       for d in tracer.finished)


# ---------------------------------------------------------------------------
# gzip on the observability surfaces
# ---------------------------------------------------------------------------

class TestGzip:
    def test_metrics_gzipped_when_accepted(self, server):
        srv, _store, _api = server
        for _ in range(30):   # fatten the exposition past the threshold
            _http(srv.url + "/pools")
        status, headers, body = _http(
            srv.url + "/metrics", headers={"Accept-Encoding": "gzip"})
        assert status == 200
        assert headers.get("Content-Encoding") == "gzip"
        assert headers.get("Content-Type") == "text/plain"
        text = gzip.decompress(body).decode()
        assert "cook_http_requests_total" in text
        assert int(headers["Content-Length"]) == len(body)

    def test_debug_gzipped_and_parseable(self, server):
        srv, _store, _api = server
        for _ in range(30):
            _http(srv.url + "/pools")
        _status, headers, body = _http(
            srv.url + "/debug/requests?limit=50",
            headers={"Accept-Encoding": "gzip"})
        assert headers.get("Content-Encoding") == "gzip"
        doc = json.loads(gzip.decompress(body))
        assert "recent" in doc

    def test_no_gzip_without_accept_or_off_surface(self, server):
        srv, _store, _api = server
        for _ in range(30):
            _http(srv.url + "/pools")
        _s, headers, body = _http(srv.url + "/metrics")
        assert headers.get("Content-Encoding") is None
        assert b"cook_http" in body
        # non-observability JSON surfaces stay uncompressed even with
        # Accept-Encoding (only /metrics and /debug/* opt in)
        _s, headers, _b = _http(srv.url + "/pools",
                                headers={"Accept-Encoding": "gzip"})
        assert headers.get("Content-Encoding") is None

    def test_q_zero_optout(self):
        assert not instrument.wants_gzip("gzip;q=0")
        assert instrument.wants_gzip("gzip;q=0.5")
        assert instrument.wants_gzip("deflate, gzip")
        assert not instrument.wants_gzip("identity")


# ---------------------------------------------------------------------------
# /debug/health roll-up + cs debug health
# ---------------------------------------------------------------------------

class TestDebugHealth:
    def test_rollup_shape(self, server):
        srv, _store, _api = server
        client = JobClient(srv.url, user="alice")
        doc = client.debug_health()
        for key in ("healthy", "slo_burn_rates", "breakers",
                    "replication", "resident_repacks", "audit", "http"):
            assert key in doc, key
        assert doc["healthy"] is True
        assert "inflight" in doc["http"]

    def test_cli_debug_health(self, server, capsys):
        from cook_tpu.cli.main import main as cli_main
        srv, _store, _api = server
        rc = cli_main(["--url", srv.url, "--user", "alice",
                       "debug", "health"])
        assert rc == 0
        doc = json.loads(capsys.readouterr().out)
        assert "slo_burn_rates" in doc

    def test_cli_debug_requests(self, server, capsys):
        from cook_tpu.cli.main import main as cli_main
        srv, _store, _api = server
        _http(srv.url + "/pools")
        rc = cli_main(["--url", srv.url, "--user", "alice",
                       "debug", "requests", "--limit", "5"])
        assert rc == 0
        doc = json.loads(capsys.readouterr().out)
        assert "recent" in doc and "slow" in doc


# ---------------------------------------------------------------------------
# Endpoint-latency SLO wiring (sched/monitor.py)
# ---------------------------------------------------------------------------

class TestEndpointSlo:
    def test_burn_rate_published_per_endpoint(self, server):
        from cook_tpu.sched.monitor import Monitor
        srv, store, api = server
        cfg = Config()
        cfg.slo.endpoint_latency_objective_s = 0.0  # everything breaches
        # breach counting happens at request time against the SERVING
        # api's objective; the monitor only publishes the ratio
        api.config.slo.endpoint_latency_objective_s = 0.0
        for _ in range(4):
            _http(srv.url + "/pools")
        wait_until(lambda: "/pools" in {
            e for e in instrument.request_log._slo_window})
        monitor = Monitor(store, config=cfg)
        monitor.sweep()
        burns = {lbl.get("endpoint"): v for lbl, v in
                 registry.series("cook_slo_burn_rate")
                 if lbl.get("slo") == "endpoint-latency"}
        assert burns.get("/pools", 0) > 0
        # a quiet endpoint is re-published at 0 the next sweep — one
        # slow request must not stick as a permanent burn alarm
        monitor.sweep()
        burns = {lbl.get("endpoint"): v for lbl, v in
                 registry.series("cook_slo_burn_rate")
                 if lbl.get("slo") == "endpoint-latency"}
        assert burns.get("/pools") == 0.0


# ---------------------------------------------------------------------------
# Config plumbing
# ---------------------------------------------------------------------------

class TestHttpConfig:
    def test_daemon_section_boot_validated(self):
        from cook_tpu.daemon import build_scheduler_config
        cfg = build_scheduler_config(
            {"http": {"observe": False, "slow_request_ms": 100,
                      "request_log": 32}})
        assert cfg.http.observe is False
        assert cfg.http.slow_request_ms == 100.0
        with pytest.raises(ValueError, match="unknown http key"):
            build_scheduler_config({"http": {"slowrequest_ms": 5}})
        with pytest.raises(ValueError, match="boolean"):
            build_scheduler_config({"http": {"observe": "false"}})

    def test_cookapi_applies_http_config(self):
        cfg = Config()
        cfg.http.observe = False
        CookApi(Store(), config=cfg)
        assert instrument.request_log.enabled is False
        instrument.request_log.enabled = True


# ---------------------------------------------------------------------------
# End-to-end: one submission is ONE stitched trace (the demo the issue
# names as acceptance)
# ---------------------------------------------------------------------------

class TestStitchedTrace:
    @pytest.fixture()
    def cell(self, tmp_path):
        from cook_tpu.cluster import FakeCluster, FakeHost
        from cook_tpu.sched import Scheduler
        store = Store.open(str(tmp_path))
        cfg = Config()
        cfg.pipeline.depth = 0
        hosts = [FakeHost(f"h{i}", Resources(cpus=8.0, mem=1024.0))
                 for i in range(4)]
        sched = Scheduler(store, cfg, [FakeCluster("fake-1", hosts)])
        api = CookApi(store, scheduler=sched, config=cfg)
        srv = ApiServer(api)
        srv.start()
        yield srv, store, sched
        srv.stop()

    def test_submit_to_launch_single_export(self, cell):
        srv, store, sched = cell
        client = JobClient(srv.url, user="alice")
        [uuid] = client.submit([{"command": "true", "cpus": 1.0,
                                 "mem": 64.0}])
        req_trace = client.last_trace_id
        sched.step_cycle()
        sched.flush_status_updates()
        # the launched audit event records BOTH stitch points
        [launched] = [e for e in store.audit.timeline(uuid)
                      if e["kind"] == "launched"]
        assert launched["data"]["trace"] == req_trace
        cycle_trace = launched["data"]["cycle_trace"]
        assert cycle_trace and cycle_trace != req_trace
        # ONE export: request span tree + cycle flamegraph + job lane
        trace = client.debug_trace(job=uuid)
        events = trace["traceEvents"]
        names = {e["name"] for e in events}
        assert "http.request" in names
        assert "journal.append" in names
        assert "fused.cycle" in names or "cycle" in names
        assert "fused.launch" in names or \
            "cluster.launch-tasks" in names
        assert "launched" in names          # audit lane instant event
        # distinct tracks: cycle (1), job lane (2), request track (3)
        assert {e["tid"] for e in events} >= {1, 2, 3}
        http_ev = [e for e in events if e["name"] == "http.request"][0]
        assert http_ev["tid"] == 3
        # request-track spans really are the request trace's
        assert http_ev["args"]["request_id"]

    def test_cs_why_perfetto_includes_request_track(self, cell,
                                                    tmp_path):
        from cook_tpu.cli.main import main as cli_main
        srv, _store, sched = cell
        client = JobClient(srv.url, user="alice")
        [uuid] = client.submit([{"command": "true", "cpus": 1.0,
                                 "mem": 64.0}])
        sched.step_cycle()
        sched.flush_status_updates()
        out_file = tmp_path / "why.json"
        rc = cli_main(["--url", srv.url, "--user", "alice", "why",
                       uuid, "--perfetto", str(out_file)])
        assert rc == 0
        trace = json.loads(out_file.read_text())
        names = {e["name"] for e in trace["traceEvents"]}
        assert "http.request" in names
        assert "launched" in names

    def test_job_only_export_before_launch(self, cell):
        """A still-waiting job's export is the request trace alone —
        the submission is traceable before any cycle ran."""
        srv, _store, _sched = cell
        client = JobClient(srv.url, user="alice")
        [uuid] = client.submit([{"command": "true", "cpus": 1.0,
                                 "mem": 64.0}])
        wait_until(lambda: [d for d in tracer.finished
                            if d["span"] == "http.request"])
        trace = client.debug_trace(job=uuid)
        names = {e["name"] for e in trace["traceEvents"]}
        assert "http.request" in names
        assert "submitted" in names
