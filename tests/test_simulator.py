"""Simulator tests: trace replay completes, fairness holds, decision parity
between TPU kernels and CPU fallback (reference: the simulator is the
decision-parity + benchmark harness, SURVEY.md section 4 tier 3)."""

import numpy as np
import pytest

from cook_tpu.sim import (
    Simulator,
    generate_example_hosts,
    generate_example_trace,
    load_hosts,
    load_trace,
)


class TestSimulator:
    def test_small_trace_completes(self):
        trace = load_trace(generate_example_trace(n_jobs=50, seed=1))
        hosts = load_hosts(generate_example_hosts(n_hosts=10, seed=1))
        sim = Simulator(trace, hosts, backend="cpu")
        result = sim.run()
        assert result.completed == 50
        summary = result.summary()
        assert summary["placements"] >= 50
        assert summary["makespan_virtual_s"] > 0

    def test_overloaded_cluster_queues_then_completes(self):
        # 30 jobs of 4 cpus on one 8-cpu host: long queue, all finish
        trace = load_trace([{
            "user": f"u{i % 3}", "submit_time": 0, "duration": 1000,
            "cpus": 4.0, "mem": 100.0} for i in range(30)])
        hosts = load_hosts([{"hostname": "h0", "cpus": 8, "mem": 10000}])
        sim = Simulator(trace, hosts, backend="cpu")
        result = sim.run()
        assert result.completed == 30
        # only 2 at a time -> makespan at least 15 virtual seconds
        assert result.makespan_ms >= 14_000

    def test_decision_parity_tpu_vs_cpu(self):
        trace_entries = generate_example_trace(n_jobs=80, seed=3)
        for i, e in enumerate(trace_entries):
            e["uuid"] = f"job-{i:04d}"
        host_entries = generate_example_hosts(n_hosts=8, seed=3)
        placements = {}
        for backend, cycle_mode in (
                ("cpu", "split"), ("tpu", "split"), ("tpu", "fused")):
            # identical rank/match cadence across modes: the fused cycle
            # re-ranks every dispatch, so give split mode the same cadence
            sim = Simulator(load_trace(trace_entries),
                            load_hosts(host_entries), backend=backend,
                            cycle_mode=cycle_mode, rank_interval_ms=1000)
            result = sim.run()
            assert result.completed == 80
            key = f"{backend}/{cycle_mode}"
            # compare (job -> ordered host list) instead of task ids
            placements[key + "_by_job"] = sorted(
                (r["job"], r["host"], r["status"])
                for r in result.task_records)
        # full decision parity: same job -> host assignments across the CPU
        # fallback, the split kernel path, and the fused production cycle
        assert placements["cpu/split_by_job"] == placements["tpu/split_by_job"]
        assert placements["cpu/split_by_job"] == placements["tpu/fused_by_job"]

    def test_cli_entry(self, tmp_path, capsys):
        from cook_tpu.sim.__main__ import main
        out_csv = tmp_path / "tasks.csv"
        assert main(["--backend", "cpu", "--jobs", "20", "--n-hosts", "5",
                     "--out", str(out_csv)]) == 0
        import json
        summary = json.loads(capsys.readouterr().out)
        assert summary["jobs_completed"] == 20
        assert out_csv.exists()


class TestSystemSimulator:
    """The system-simulator CLI (reference: simulator/ subproject —
    generate a workload, replay it against a LIVE daemon, report wait/
    turnaround/overhead), distinct from the faster-than-real-time
    scheduler simulator above."""

    def test_generate_simulate_report_roundtrip(self, tmp_path):
        import json
        from test_integration_scenarios import (spawn, wait_leader,
                                                wait_serving)
        from cook_tpu.sim.system import build_report, main

        sched_file = tmp_path / "sched.json"
        out_file = tmp_path / "results.json"
        assert main(["generate", "-f", str(sched_file), "--users", "2",
                     "--jobs-per-user", "4", "--duration-s", "4",
                     "--mean-job-duration-ms", "600", "--seed", "3"]) == 0
        schedule = json.loads(sched_file.read_text())
        assert len(schedule["users"]) == 2
        assert all(len(u["jobs"]) == 4 for u in schedule["users"])

        conf = {
            "host": "127.0.0.1", "port": 0,
            "data_dir": str(tmp_path / "data"),
            "election_dir": str(tmp_path),
            "admins": ["admin"],
            "clusters": [{"factory": "cook_tpu.cluster.fake.factory",
                          "kwargs": {"name": "a", "n_hosts": 3,
                                     "cpus": 8.0, "mem": 8192.0,
                                     "auto_advance": True}}],
            "scheduler": {"rank_backend": "cpu", "cycle_mode": "split",
                          "match_interval_seconds": 0.1,
                          "rank_interval_seconds": 0.1},
        }
        proc = spawn(conf, tmp_path, "sim")
        try:
            url = wait_serving(proc)
            assert wait_leader(url)
            assert main(["simulate", "-f", str(sched_file), "--url", url,
                         "--out", str(out_file), "--time-scale", "4",
                         "--settle-timeout-s", "60"]) == 0
            results = json.loads(out_file.read_text())
            assert len(results["jobs"]) == 8
            assert results["errors"] == []
            report = build_report(results)
            assert report["finished"] == 8
            assert report["never_scheduled"] == []
            assert report["overall"]["wait"]["count"] == 8
            # overhead = turnaround - intended duration; a broken
            # time_scale division would blow this far past a cycle time
            overhead = report["overall"]["overhead"]
            assert overhead["count"] == 8
            turnaround = report["overall"]["turnaround"]
            assert 0 < overhead["mean_ms"] < turnaround["mean_ms"]
            assert set(report["by_user"]) == {"sim000", "sim001"}
            # the CLI report command renders the same JSON
            assert main(["report", "-f", str(out_file)]) == 0
        finally:
            if proc.poll() is None:
                proc.kill()
            proc.wait(timeout=10)
