"""Multi-cell federation tests: cell-qualified commit tokens, the
front-door router, the federated user-summary merge and its honesty at
the staleness bound, single-cell wire parity, the boot surface, and the
full-cell-outage chaos invariants (cook_tpu/federation/;
docs/DEPLOY.md multi-cell federation)."""

import json
import time
import urllib.error
import urllib.request

import pytest

from cook_tpu.client import JobClient
from cook_tpu.cluster import FakeCluster, FakeHost
from cook_tpu.config import Config, FederationConfig
from cook_tpu.federation import (CellHandle, CellSpec,
                                 FederatedUserSummaries, RouteRejected,
                                 cells_in_token, qualify_token,
                                 split_entry, strip_for_cell)
from cook_tpu.federation.rest import build_federation_node
from cook_tpu.rest import ApiServer, CookApi
from cook_tpu.sched import Scheduler
from cook_tpu.state import Resources, Store
from cook_tpu.state.partition import SummaryStalenessError

pytestmark = pytest.mark.federation


def make_cell(data_dir=None, n_hosts=2, prefix="h"):
    store = Store.open(str(data_dir)) if data_dir else Store()
    cluster = FakeCluster(
        f"{prefix}-cluster",
        [FakeHost(f"{prefix}{i}", Resources(cpus=8, mem=8192))
         for i in range(n_hosts)])
    cfg = Config()
    cfg.default_matcher.backend = "cpu"
    sched = Scheduler(store, cfg, [cluster], rank_backend="cpu")
    api = CookApi(store, scheduler=sched, config=cfg)
    server = ApiServer(api)
    server.start()
    return store, cluster, sched, server


def fed_over(cells, **conf):
    section = {"cells": [{"id": cid, "url": srv.url, **extra}
                         for cid, srv, extra in cells]}
    section.update(conf)
    node = build_federation_node(section)
    node.start()
    return node


# ---------------------------------------------------------------- tokens
class TestTokens:
    def test_qualify_prefixes_every_entry(self):
        assert qualify_token("cellA", "p0:3:128,p1:3:64") == \
            "cellA/p0:3:128,cellA/p1:3:64"
        assert qualify_token("cellA", "2372") == "cellA/2372"

    def test_qualify_is_idempotent_per_cell(self):
        t = qualify_token("cellA", "p0:3:128")
        assert qualify_token("cellA", t) == t

    def test_split_entry(self):
        assert split_entry("cellA/p0:3:128") == ("cellA", "p0:3:128")
        assert split_entry("p0:3:128") == (None, "p0:3:128")

    def test_cells_in_token(self):
        assert cells_in_token("cellA/p0:1:2,cellB/9,p1:0:4") == \
            {"cellA", "cellB"}

    def test_strip_for_cell_reduces_and_reports(self):
        cell_token, others = strip_for_cell(
            "cellA/p0:3:128,cellB/2372,p1:0:9", "cellA")
        # target cell's entries lose the prefix; unqualified entries
        # pass through verbatim; every OTHER cell is reported so the
        # read can be honestly labeled stale with respect to it
        assert set(cell_token.split(",")) == {"p0:3:128", "p1:0:9"}
        assert others == {"cellB"}

    def test_strip_for_cell_none_when_absent(self):
        cell_token, others = strip_for_cell("cellB/2372", "cellA")
        assert cell_token is None
        assert others == {"cellB"}


class TestClientTokenMerge:
    def c(self):
        return JobClient("http://127.0.0.1:1", user="u")

    def test_cell_qualified_merges_per_cell_partition(self):
        c = self.c()
        c._merge_commit_token("cellA/p0:1:10")
        c._merge_commit_token("cellB/p0:1:20")
        c._merge_commit_token("cellA/p0:2:30")  # same (cell, partition)
        assert c.last_commit_offset == "cellA/p0:2:30,cellB/p0:1:20"

    def test_cell_qualified_simple_tokens_merge_per_cell(self):
        c = self.c()
        c._merge_commit_token("cellA/100")
        c._merge_commit_token("cellB/200")
        c._merge_commit_token("cellA/300")
        assert c.last_commit_offset == "cellA/300,cellB/200"

    def test_unqualified_replaces_wholesale(self):
        c = self.c()
        c._merge_commit_token("cellA/p0:1:10")
        c._merge_commit_token("4594")  # a non-federated server's token
        assert c.last_commit_offset == "4594"

    def test_partition_vector_still_merges(self):
        c = self.c()
        c._merge_commit_token("p0:1:10,p1:1:20")
        c._merge_commit_token("p0:1:30")
        assert c.last_commit_offset == "p0:1:30,p1:1:20"


# ---------------------------------------------------------------- config
class TestFederationConfig:
    def test_unknown_key_fails_boot(self):
        with pytest.raises(ValueError, match="unknown federation key"):
            FederationConfig.from_conf(
                {"cells": [{"id": "a", "url": "http://x:1"}],
                 "tpyo": True})

    def test_empty_cells_fails_boot(self):
        with pytest.raises(ValueError, match="at least one cell"):
            FederationConfig.from_conf({"cells": []})

    def test_bad_cell_entries_fail_boot(self):
        for cells in ([{"id": "a/b", "url": "http://x:1"}],
                      [{"id": "a", "url": "ftp://x:1"}],
                      [{"id": "a", "url": "http://x:1", "tier": "weird"}],
                      [{"id": "a", "url": "http://x:1"},
                       {"id": "a", "url": "http://y:1"}]):
            with pytest.raises(ValueError):
                FederationConfig.from_conf({"cells": cells})

    def test_example_federation_conf_boots(self):
        import os
        path = os.path.join(os.path.dirname(__file__), "..",
                            "examples", "cook-federation.json")
        conf = json.load(open(path))
        node = build_federation_node(conf["federation"])
        # never start()ed: boot validation is the point
        assert not node.router.single_cell
        assert set(node.router.cells) == {"cellA", "cellB"}
        assert node.router.cells["cellB"].spec.tier == "spot"
        cfg = FederationConfig.from_conf(conf["federation"])
        assert cfg.max_user_pending == 5000

    def test_daemon_refuses_federation_plus_cell_state(self):
        from cook_tpu.daemon import CookDaemon
        d = CookDaemon({"federation": {"cells": [
            {"id": "a", "url": "http://127.0.0.1:1"}]},
            "scheduler": {"rank_backend": "cpu"}})
        with pytest.raises(ValueError, match="stateless front-door"):
            d.start()

    def test_daemon_federation_role_boots_and_stops(self):
        from cook_tpu.daemon import CookDaemon
        d = CookDaemon({"federation": {"cells": [
            {"id": "a", "url": "http://127.0.0.1:1"}]}})
        d.start()
        try:
            assert d.federation is not None
            assert d.store is None and d.elector is None
            doc = json.load(urllib.request.urlopen(
                d.node_url + "/debug/federation"))
            assert doc["single_cell"] is True
            assert [c["id"] for c in doc["cells"]] == ["a"]
        finally:
            d.shutdown()

    def test_cellspec_validation(self):
        with pytest.raises(ValueError):
            CellSpec(id="a,b", url="http://x:1")
        with pytest.raises(ValueError):
            CellSpec(id="a", url="http://x:1", weight=0.0)


# ------------------------------------------------------------ wire parity
class TestSingleCellParity:
    """One configured cell ⇒ the front door is decision- and
    wire-identical to the cell: PR 19 deployments keep their exact
    behavior when a router is slotted in front."""

    def test_submit_token_and_reads_are_wire_identical(self, tmp_path):
        store, _c, sched, server = make_cell(tmp_path / "cell")
        fed = fed_over([("solo", server, {})],
                       max_user_pending=1)  # caps must NOT engage
        try:
            direct = JobClient(server.url, user="alice")
            routed = JobClient(fed.url, user="alice")
            u1 = direct.submit_one("echo a", cpus=1, mem=64)
            u2 = routed.submit_one("echo b", cpus=1, mem=64)
            # same token grammar: UNqualified (no cell prefix) — the
            # single-cell front door never rewrites the wire
            assert "/" not in direct.last_commit_offset
            assert "/" not in routed.last_commit_offset
            # a third submit would trip max_user_pending=1 were the
            # router enforcing globally; single-cell must pass through
            routed.submit_one("echo c", cpus=1, mem=64)
            # reads answer identically through either path
            assert routed.job(u1)["uuid"] == u1
            d1, d2 = direct.job(u2), routed.job(u2)
            assert d1 == d2
        finally:
            fed.stop()
            server.stop()

    def test_single_cell_proxies_every_path(self, tmp_path):
        _store, _c, _s, server = make_cell(tmp_path / "cell")
        fed = fed_over([("solo", server, {})])
        try:
            for path in ("/pools", "/list?user=alice&state=waiting",
                         "/failure_reasons", "/info"):
                a = urllib.request.urlopen(server.url + path).read()
                b = urllib.request.urlopen(fed.url + path).read()
                assert a == b, path
        finally:
            fed.stop()
            server.stop()


# --------------------------------------------------------- two-cell router
class TestTwoCellRouting:
    @pytest.fixture()
    def duo(self, tmp_path):
        sa = make_cell(tmp_path / "a", prefix="a")
        sb = make_cell(tmp_path / "b", prefix="b")
        yield sa, sb
        for s in (sa, sb):
            try:
                s[3].stop()
            except Exception:
                pass

    def test_locality_pin_routes_to_named_cell(self, duo):
        sa, sb = duo
        fed = fed_over([("cellA", sa[3], {}), ("cellB", sb[3], {})])
        try:
            cli = JobClient(fed.url, user="alice")
            uuids = cli.submit(
                [{"command": "x", "cpus": 1, "mem": 64,
                  "labels": {"cell-attribute/cell": "cellB"}}])
            assert fed.router.cell_of_uuid(uuids[0]) == "cellB"
            assert cli.last_commit_offset.startswith("cellB/")
        finally:
            fed.stop()

    def test_attribute_demand_matches_cells(self, duo):
        sa, sb = duo
        fed = fed_over([
            ("cellA", sa[3], {"attributes": {"region": "east"}}),
            ("cellB", sb[3], {"attributes": {"region": "west"}})])
        try:
            cli = JobClient(fed.url, user="alice")
            uuids = cli.submit(
                [{"command": "x", "cpus": 1, "mem": 64,
                  "labels": {"cell-attribute/region": "west"}}])
            assert fed.router.cell_of_uuid(uuids[0]) == "cellB"
            # an unsatisfiable demand refuses loudly, routing nowhere
            with pytest.raises(Exception) as ei:
                cli.submit([{"command": "x", "cpus": 1, "mem": 64,
                             "labels": {"cell-attribute/region": "mars"}}])
            assert "503" in str(ei.value) or "no eligible" in str(ei.value)
        finally:
            fed.stop()

    def test_global_pending_cap_spans_cells(self, duo):
        sa, sb = duo
        fed = fed_over([("cellA", sa[3], {}), ("cellB", sb[3], {})],
                       max_user_pending=3,
                       summary_max_age_seconds=0.05)
        try:
            cli = JobClient(fed.url, user="alice")
            # 2 jobs pinned to each cell: per-cell pending never
            # exceeds 2, so only a GLOBAL merge can see 4
            cli.submit([{"command": "x", "cpus": 1, "mem": 64,
                         "labels": {"cell-attribute/cell": "cellA"}}
                        for _ in range(2)])
            time.sleep(0.06)
            with pytest.raises(Exception) as ei:
                cli.submit([{"command": "x", "cpus": 1, "mem": 64,
                             "labels": {"cell-attribute/cell": "cellB"}}
                            for _ in range(2)])
            msg = str(ei.value)
            assert "pending" in msg
            # the refusal quotes the staleness window it enforced under
            assert "stale" in msg and "bound" in msg
            # a different user is not capped (per-user, not global-total)
            other = JobClient(fed.url, user="bob")
            other.submit([{"command": "x", "cpus": 1, "mem": 64}])
        finally:
            fed.stop()

    def test_gang_routes_whole_to_one_cell(self, duo):
        import uuid as _uuid
        sa, sb = duo
        fed = fed_over([("cellA", sa[3], {}), ("cellB", sb[3], {})])
        try:
            cli = JobClient(fed.url, user="alice")
            g = str(_uuid.uuid4())
            uuids = cli.submit(
                [{"command": "x", "cpus": 1, "mem": 64, "group": g}
                 for _ in range(3)],
                groups=[{"uuid": g, "gang": {"size": 3}}])
            owners = {fed.router.cell_of_uuid(u) for u in uuids}
            assert len(owners) == 1
        finally:
            fed.stop()

    def test_cross_cell_query_merges_with_honest_staleness(self, duo):
        sa, sb = duo
        fed = fed_over([("cellA", sa[3], {}), ("cellB", sb[3], {})])
        try:
            cli = JobClient(fed.url, user="alice")
            ua = cli.submit([{"command": "x", "cpus": 1, "mem": 64,
                              "labels": {"cell-attribute/cell": "cellA"}}])
            ub = cli.submit([{"command": "x", "cpus": 1, "mem": 64,
                              "labels": {"cell-attribute/cell": "cellB"}}])
            docs = cli.query(ua + ub)
            assert {d["uuid"] for d in docs} == set(ua + ub)
            # a single-cell read carrying a 2-cell token declares the
            # OTHER cell stale instead of faking freshness
            req = urllib.request.Request(
                f"{fed.url}/jobs/{ua[0]}",
                headers={"X-Cook-Min-Offset": cli.last_commit_offset})
            with urllib.request.urlopen(req) as r:
                assert r.headers["X-Cook-Federation-Stale-Cells"] == \
                    "cellB"
        finally:
            fed.stop()


# --------------------------------------- federated summary edge semantics
class TestFederatedSummaryEdges:
    """Satellite: the federated UserSummaryExchange at its edges — an
    unreachable peer must surface SummaryStalenessError at the bound
    (never a silently-served stale view), and a drained/rejoined cell
    must re-converge."""

    def test_unreachable_cell_raises_at_bound(self, tmp_path):
        sa = make_cell(tmp_path / "a", prefix="a")
        sb = make_cell(tmp_path / "b", prefix="b")
        JobClient(sb[3].url, user="alice").submit_one(
            "x", cpus=1, mem=64)
        cells = {
            "cellA": CellHandle(CellSpec(id="cellA", url=sa[3].url)),
            "cellB": CellHandle(CellSpec(id="cellB", url=sb[3].url))}
        fs = FederatedUserSummaries(cells, max_age_s=1.5)
        try:
            fs.refresh()
            assert fs.user_totals("alice")["pending"] == 1.0
            sb[3].kill()  # full outage: listener + live sockets die
            # inside the bound the CACHED table still serves (honestly
            # within the window)
            assert fs.user_totals("alice")["pending"] == 1.0
            time.sleep(1.6)
            # past the bound: loud failure, never a silent stale serve
            with pytest.raises(SummaryStalenessError) as ei:
                fs.user_totals("alice")
            assert "stale" in str(ei.value)
        finally:
            sa[3].stop()

    def test_never_fetched_cell_is_infinitely_stale(self, tmp_path):
        sa = make_cell(tmp_path / "a", prefix="a")
        cells = {
            "cellA": CellHandle(CellSpec(id="cellA", url=sa[3].url)),
            "dead": CellHandle(CellSpec(
                id="dead", url="http://127.0.0.1:1"))}
        fs = FederatedUserSummaries(cells, max_age_s=0.5)
        try:
            # the unreachable cell's users are invisible; enforcement
            # must refuse rather than enforce around them
            with pytest.raises(SummaryStalenessError):
                fs.user_totals("alice")
        finally:
            sa[3].stop()

    def test_drain_excludes_and_rejoin_reconverges(self, tmp_path):
        sa = make_cell(tmp_path / "a", prefix="a")
        sb = make_cell(tmp_path / "b", prefix="b")
        fed = fed_over([("cellA", sa[3], {}), ("cellB", sb[3], {})],
                       summary_max_age_seconds=0.05)
        try:
            cli = JobClient(fed.url, user="alice")
            cli.submit([{"command": "x", "cpus": 1, "mem": 64,
                         "labels": {"cell-attribute/cell": "cellB"}}])
            router = fed.router
            router.summaries.refresh()
            assert router.summaries.user_totals("alice")["pending"] == 1.0
            router.drain_cell("cellB")
            time.sleep(0.06)
            # drained: cellB's demand leaves the merge (operator
            # intent — a re-routed user must not double-count)
            assert router.summaries.user_totals("alice")["pending"] == 0.0
            # drained cells take no new demand
            with pytest.raises(RouteRejected):
                router.pick_cell({"jobs": [{
                    "labels": {"cell-attribute/cell": "cellB"}}]})
            router.rejoin_cell("cellB")
            time.sleep(0.06)
            assert router.summaries.user_totals("alice")["pending"] == 1.0
        finally:
            fed.stop()
            for s in (sa, sb):
                try:
                    s[3].stop()
                except Exception:
                    pass

    def test_stale_enforcement_answers_503_not_silence(self, tmp_path):
        sa = make_cell(tmp_path / "a", prefix="a")
        sb = make_cell(tmp_path / "b", prefix="b")
        fed = fed_over([("cellA", sa[3], {}), ("cellB", sb[3], {})],
                       max_user_pending=100,
                       summary_max_age_seconds=0.2)
        try:
            cli = JobClient(fed.url, user="alice")
            cli.submit_one("x", cpus=1, mem=64)
            sb[3].kill()
            fed.router.cells["cellB"].breaker.trip()
            time.sleep(0.25)
            body = json.dumps({"jobs": [{
                "uuid": "00000000-0000-4000-8000-000000000001",
                "command": "x", "cpus": 1, "mem": 64}]}).encode()
            req = urllib.request.Request(
                fed.url + "/jobs", data=body, method="POST",
                headers={"Content-Type": "application/json",
                         "X-Cook-User": "alice"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req)
            assert ei.value.code == 503
            doc = json.loads(ei.value.read())
            assert doc.get("reason") == "summary-stale"
            assert ei.value.headers.get("Retry-After")
        finally:
            fed.stop()
            sa[3].stop()


# ------------------------------------------------------------ cell outage
class TestCellOutage:
    def test_outage_smoke(self):
        from cook_tpu.sim.federation import (CellOutageConfig,
                                             run_cell_outage)
        res = run_cell_outage(CellOutageConfig(n_batches=8))
        assert res.ok, res.violations
        assert res.lost_jobs == 0
        assert res.split_gangs == 0
        assert res.rerouted_batches > 0
        assert res.breaker_states[res.victim] in ("open", "half-open")

    @pytest.mark.slow
    @pytest.mark.chaos
    def test_outage_soak(self):
        from cook_tpu.sim.federation import (CellOutageConfig,
                                             run_cell_outage)
        res = run_cell_outage(CellOutageConfig(soak=True))
        assert res.ok, res.violations
        assert res.jobs_acked >= 150
        assert res.lost_jobs == 0 and res.split_gangs == 0
