"""End-to-end slice: submit -> rank -> match -> launch -> status -> complete
against the fake cluster (SURVEY.md section 7 step 4, the first full loop)."""

import numpy as np
import pytest

from cook_tpu.cluster import FakeCluster, FakeHost
from cook_tpu.config import Config, MatcherConfig, PoolQuota
from cook_tpu.sched import Scheduler
from cook_tpu.state import (
    Constraint,
    Group,
    GroupPlacementType,
    InstanceStatus,
    Job,
    JobState,
    Pool,
    Reasons,
    Resources,
    SchedulerKind,
    Store,
    new_uuid,
)


def make_job(user="alice", pool="default", cpus=1.0, mem=100.0, gpus=0.0,
             **kw) -> Job:
    return Job(uuid=new_uuid(), user=user, command="true", pool=pool,
               resources=Resources(cpus=cpus, mem=mem, gpus=gpus), **kw)


def std_cluster(n_hosts=4, cpus=8.0, mem=8192.0, **kw):
    hosts = [FakeHost(hostname=f"h{i}", capacity=Resources(cpus=cpus, mem=mem))
             for i in range(n_hosts)]
    return FakeCluster("fake-1", hosts, **kw)


@pytest.fixture(params=["cpu", "tpu"])
def backend(request):
    return request.param


def mk_sched(store, cluster, backend, config=None):
    config = config or Config()
    if backend == "cpu":
        config.default_matcher.backend = "cpu"
    return Scheduler(store, config, [cluster], rank_backend=backend)


class TestFullCycle:
    def test_submit_rank_match_run_complete(self, backend):
        store = Store()
        cluster = std_cluster(default_task_duration_ms=1000)
        sched = mk_sched(store, cluster, backend)
        uuids = store.create_jobs([make_job(user=u) for u in
                                   ("alice", "alice", "bob")])
        sched.step_rank()
        res = sched.step_match()["default"]
        assert len(res.launched_task_ids) == 3
        for uuid in uuids:
            assert store.job(uuid).state is JobState.RUNNING
        # virtual time passes; tasks complete
        cluster.advance_to(1500)
        for uuid in uuids:
            assert store.job(uuid).state is JobState.COMPLETED

    def test_failed_task_retries_then_succeeds(self, backend):
        store = Store()
        cluster = std_cluster()
        sched = mk_sched(store, cluster, backend)
        [uuid] = store.create_jobs([make_job(max_retries=2)])
        sched.step_rank()
        res = sched.step_match()["default"]
        [tid] = res.launched_task_ids
        cluster.fail_task(tid, Reasons.NODE_LOST.code)
        job = store.job(uuid)
        assert job.state is JobState.WAITING  # mea-culpa, retry free
        sched.step_rank()
        res = sched.step_match()["default"]
        [tid2] = res.launched_task_ids
        cluster.complete_task(tid2)
        assert store.job(uuid).state is JobState.COMPLETED

    def test_novel_host_constraint_after_failure(self, backend):
        # job must not be relaunched on the host where it failed
        store = Store()
        cluster = std_cluster(n_hosts=2)
        sched = mk_sched(store, cluster, backend)
        [uuid] = store.create_jobs([make_job(max_retries=5)])
        sched.step_rank()
        res = sched.step_match()["default"]
        [tid] = res.launched_task_ids
        first_host = store.instance(tid).hostname
        cluster.fail_task(tid, Reasons.NON_ZERO_EXIT.code)
        sched.step_rank()
        res = sched.step_match()["default"]
        [tid2] = res.launched_task_ids
        assert store.instance(tid2).hostname != first_host

    def test_kill_running_job_kills_backend_task(self, backend):
        store = Store()
        cluster = std_cluster()
        sched = mk_sched(store, cluster, backend)
        [uuid] = store.create_jobs([make_job()])
        sched.step_rank()
        [tid] = sched.step_match()["default"].launched_task_ids
        assert tid in cluster.running_task_ids()
        store.kill_job(uuid)
        assert store.job(uuid).state is JobState.COMPLETED
        assert tid not in cluster.running_task_ids()

    def test_insufficient_resources_head_backoff(self, backend):
        # a giant head-of-queue job can't match; backoff shrinks considerable
        store = Store()
        cluster = std_cluster(n_hosts=1, cpus=4.0)
        cfg = Config()
        cfg.default_matcher = MatcherConfig(
            backend="cpu" if backend == "cpu" else "tpu-greedy",
            max_jobs_considered=10)
        sched = mk_sched(store, cluster, backend, cfg)
        store.create_jobs([make_job(user="hog", cpus=100.0, priority=90),
                           make_job(user="small", cpus=1.0)])
        sched.step_rank()
        res = sched.step_match()["default"]
        assert not res.head_matched
        assert sched.matcher._backoff["default"].num_considerable < 10

    def test_max_runtime_reaper(self, backend):
        store = Store()
        cluster = std_cluster()
        sched = mk_sched(store, cluster, backend)
        [uuid] = store.create_jobs([make_job(max_runtime_ms=10, max_retries=1)])
        sched.step_rank()
        [tid] = sched.step_match()["default"].launched_task_ids
        start = store.instance(tid).start_time_ms
        killed = sched.step_reapers(current_ms=start + 100)
        assert killed == [tid]
        inst = store.instance(tid)
        assert inst.status is InstanceStatus.FAILED
        assert inst.reason_code == Reasons.MAX_RUNTIME_EXCEEDED.code
        assert store.job(uuid).state is JobState.COMPLETED  # retries consumed


class TestQuotasAndFairness:
    def test_user_quota_limits_considerable(self, backend):
        store = Store()
        cluster = std_cluster()
        sched = mk_sched(store, cluster, backend)
        store.set_quota("alice", "default", {"cpus": 2.0})
        store.create_jobs([make_job(user="alice") for _ in range(5)])
        sched.step_rank()
        res = sched.step_match()["default"]
        assert len(res.launched_task_ids) == 2

    def test_pool_quota_caps_launches(self, backend):
        store = Store()
        cluster = std_cluster()
        cfg = Config(pool_quotas={"default": PoolQuota(count=3)})
        if backend == "cpu":
            cfg.default_matcher = MatcherConfig(backend="cpu")
        sched = Scheduler(store, cfg, [cluster], rank_backend=backend)
        store.create_jobs([make_job(user=f"u{i}") for i in range(6)])
        sched.step_rank()
        res = sched.step_match()["default"]
        assert len(res.launched_task_ids) == 3

    def test_fair_share_interleaves_users(self, backend):
        store = Store()
        cluster = std_cluster(n_hosts=1, cpus=4.0)
        sched = mk_sched(store, cluster, backend)
        store.set_share("default", "default", {"cpus": 4.0, "mem": 4096.0})
        store.create_jobs([make_job(user="alice") for _ in range(4)]
                          + [make_job(user="bob") for _ in range(4)])
        sched.step_rank()
        res = sched.step_match()["default"]
        launched_users = sorted(
            store.job(store.instance(t).job_uuid).user
            for t in res.launched_task_ids)
        assert launched_users == ["alice", "alice", "bob", "bob"]


class TestGroupsAndConstraints:
    def test_unique_host_group_spreads(self, backend):
        store = Store()
        cluster = std_cluster(n_hosts=3)
        sched = mk_sched(store, cluster, backend)
        guuid = new_uuid()
        jobs = [make_job(user="alice", group=guuid) for _ in range(3)]
        group = Group(uuid=guuid, placement_type=GroupPlacementType.UNIQUE,
                      jobs=[j.uuid for j in jobs])
        store.create_jobs(jobs, groups=[group])
        sched.step_rank()
        res = sched.step_match()["default"]
        hosts = [store.instance(t).hostname for t in res.launched_task_ids]
        assert len(set(hosts)) == len(hosts)  # all distinct

    def test_attribute_constraint(self, backend):
        store = Store()
        hosts = [FakeHost("rack-a", Resources(cpus=8, mem=8192),
                          attributes={"rack": "a"}),
                 FakeHost("rack-b", Resources(cpus=8, mem=8192),
                          attributes={"rack": "b"})]
        cluster = FakeCluster("fake-1", hosts)
        sched = mk_sched(store, cluster, backend)
        store.create_jobs([make_job(
            constraints=[Constraint("rack", "EQUALS", "b")])])
        sched.step_rank()
        res = sched.step_match()["default"]
        [tid] = res.launched_task_ids
        assert store.instance(tid).hostname == "rack-b"

    def test_gpu_job_isolation(self, backend):
        store = Store()
        hosts = [FakeHost("cpu-host", Resources(cpus=8, mem=8192)),
                 FakeHost("gpu-host", Resources(cpus=8, mem=8192, gpus=4),
                          gpu_model="a100")]
        cluster = FakeCluster("fake-1", hosts)
        sched = mk_sched(store, cluster, backend)
        store.create_jobs([make_job(user="g", gpus=1.0),
                           make_job(user="c")])
        sched.step_rank()
        res = sched.step_match()["default"]
        placement = {store.job(store.instance(t).job_uuid).user:
                     store.instance(t).hostname
                     for t in res.launched_task_ids}
        assert placement == {"g": "gpu-host", "c": "cpu-host"}


class TestDirectMode:
    def test_direct_pool_launches_without_matching(self, backend):
        store = Store()
        hosts = [FakeHost(hostname=f"h{i}", capacity=Resources(cpus=8, mem=8192),
                          pool="direct") for i in range(2)]
        cluster = FakeCluster("fake-1", hosts)
        cfg = Config()
        if backend == "cpu":
            cfg.default_matcher = MatcherConfig(backend="cpu")
        store_pool = Pool(name="direct", scheduler=SchedulerKind.DIRECT)
        store.put_pool(store_pool)
        sched = Scheduler(store, cfg, [cluster], rank_backend=backend)
        store.create_jobs([make_job(pool="direct") for _ in range(2)])
        sched.step_rank()
        res = sched.step_match("direct")["direct"]
        assert len(res.launched_task_ids) == 2
        # backend reported placement via status update
        for tid in res.launched_task_ids:
            assert store.instance(tid).hostname != ""

    def test_step_cycle_prunes_direct_launches_from_queue(self, backend):
        """Direct-pool launches must disappear from pending_queues within
        the same step_cycle (regression: _match_direct once skipped
        launched_job_uuids, leaving launched jobs visible as pending)."""
        store = Store()
        hosts = [FakeHost(hostname=f"h{i}",
                          capacity=Resources(cpus=8, mem=8192),
                          pool="direct") for i in range(2)]
        cluster = FakeCluster("fake-1", hosts)
        cfg = Config()
        if backend == "cpu":
            cfg.default_matcher = MatcherConfig(backend="cpu")
        store.put_pool(Pool(name="direct", scheduler=SchedulerKind.DIRECT))
        sched = Scheduler(store, cfg, [cluster], rank_backend=backend)
        store.create_jobs([make_job(pool="direct") for _ in range(2)])
        results = sched.step_cycle()
        assert len(results["direct"].launched_task_ids) == 2
        assert len(sched.pending_queues.get("direct", [])) == 0
