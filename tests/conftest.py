"""Test bootstrap: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; all sharding tests run on
XLA's host-platform device virtualization (the driver separately dry-runs
the multi-chip path via __graft_entry__.dryrun_multichip).

Note: the environment may preload jax at interpreter startup (site hook)
with a TPU platform selected, so env vars alone are too late — the platform
is overridden through jax.config before the backend initializes.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
