"""Test bootstrap: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; all sharding tests run on
XLA's host-platform device virtualization (the driver separately dry-runs
the multi-chip path via __graft_entry__.dryrun_multichip).

Note: the environment may preload jax at interpreter startup (site hook)
with a TPU platform selected, so env vars alone are too late — the platform
is overridden through jax.config before the backend initializes.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def lock_sanitizer():
    """Run the whole tier-1 suite under the dynamic lock-order sanitizer
    (cook_tpu/utils/locks.py, docs/ANALYSIS.md): every named-lock
    acquisition records its graph edge, and blocking syscalls (fsync /
    sleep / socket send+connect) are checked against the held-lock
    allowlist.  The teardown assert makes ANY acquisition-graph cycle,
    declared-rank inversion, or unallowlisted blocking-under-lock event
    anywhere in the run a tier-1 failure.

    COOK_LOCK_SANITIZER=0 opts out (e.g. when bisecting an unrelated
    failure); tests that deliberately construct violations use their own
    LockMonitor instance so this global stays meaningful."""
    from cook_tpu.utils import locks

    if os.environ.get("COOK_LOCK_SANITIZER", "1") == "0":
        yield
        return
    locks.monitor.arm_blocking_detector()
    try:
        yield
    finally:
        locks.monitor.disarm_blocking_detector()
        problems = locks.monitor.check()
        assert not problems, (
            "lock-order sanitizer violations during the run "
            "(utils/locks.py contract; docs/ANALYSIS.md):\n\n"
            + "\n\n".join(problems))
        # static-coverage contract (docs/ANALYSIS.md): every ordering
        # the dynamic sanitizer OBSERVED anywhere in this run must be
        # in the interprocedural analysis's static edge set — an
        # observed-only edge is a call-resolution gap that would let a
        # statically-invisible inversion ship.  (The reverse direction
        # — static edges tier-1 never drove — is the `cs lint
        # --lock-coverage` report, not a failure.)
        from cook_tpu.analysis.summaries import static_edge_families
        static = set(static_edge_families(wait=True) or [])
        observed = set(locks.monitor.observed_edges())
        missing = sorted(observed - static)
        assert not missing, (
            "lock orderings observed at runtime but missing from the "
            "static lock-edge set (cs lint --lock-coverage; a "
            "resolution gap in cook_tpu/analysis/callgraph.py): "
            + ", ".join(missing))
