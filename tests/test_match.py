"""Match kernel tests: greedy scan parity (bit-exact) and multipass
convergence (statistical parity per BASELINE.md >=99.9%)."""

import numpy as np
import pytest

import jax.numpy as jnp

from cook_tpu.ops import (
    MatchInputs,
    greedy_match_kernel,
    host_prep,
    multipass_match_kernel,
    reference_impl,
)


def to_inputs(arrays):
    return MatchInputs(
        job_res=jnp.asarray(arrays["job_res"]),
        constraint_mask=jnp.asarray(arrays["constraint_mask"]),
        avail=jnp.asarray(arrays["avail"]),
        capacity=jnp.asarray(arrays["capacity"]),
        valid=jnp.asarray(arrays["valid"]),
    )


def random_case(rng, J, H, tight=False):
    job_res = np.stack([
        rng.integers(1, 8, J).astype(np.float32),
        rng.integers(64, 1024, J).astype(np.float32),
        (rng.random(J) < 0.2) * rng.integers(0, 4, J).astype(np.float32),
        np.zeros(J, dtype=np.float32),
    ], axis=1)
    scale = 4 if not tight else 1
    capacity = np.stack([
        rng.integers(8, 32 * scale, H).astype(np.float32),
        rng.integers(1024, 8192 * scale, H).astype(np.float32),
        rng.integers(0, 8, H).astype(np.float32),
        np.full(H, 1e6, dtype=np.float32),
    ], axis=1)
    used_frac = rng.random((H, 1)).astype(np.float32) * 0.5
    avail = (capacity * (1 - used_frac)).astype(np.float32)
    cmask = rng.random((J, H)) < (0.9 if not tight else 0.7)
    return job_res, cmask, avail, capacity


class TestGreedyParity:
    def test_simple_binpack_prefers_fuller_host(self):
        job_res = np.array([[1, 100, 0, 0]], dtype=np.float32)
        capacity = np.array([[10, 1000, 0, 0], [10, 1000, 0, 0]], dtype=np.float32)
        avail = np.array([[10, 1000, 0, 0], [5, 500, 0, 0]], dtype=np.float32)
        cmask = np.ones((1, 2), dtype=bool)
        golden = reference_impl.greedy_match(job_res, cmask, avail, capacity)
        assert golden[0] == 1  # host 1 is half-used -> higher fitness
        arrays = host_prep.pack_match_inputs(job_res, cmask, avail, capacity)
        assign, _ = greedy_match_kernel(to_inputs(arrays))
        assert np.asarray(assign)[0] == 1

    def test_infeasible_job_unassigned(self):
        job_res = np.array([[100, 100, 0, 0]], dtype=np.float32)
        capacity = avail = np.array([[10, 1000, 0, 0]], dtype=np.float32)
        cmask = np.ones((1, 1), dtype=bool)
        arrays = host_prep.pack_match_inputs(job_res, cmask, avail, capacity)
        assign, _ = greedy_match_kernel(to_inputs(arrays))
        assert np.asarray(assign)[0] == -1

    def test_constraint_mask_respected(self):
        job_res = np.array([[1, 100, 0, 0]], dtype=np.float32)
        capacity = avail = np.array([[10, 1000, 0, 0], [10, 1000, 0, 0]],
                                    dtype=np.float32)
        cmask = np.array([[False, True]])
        arrays = host_prep.pack_match_inputs(job_res, cmask, avail, capacity)
        assign, _ = greedy_match_kernel(to_inputs(arrays))
        assert np.asarray(assign)[0] == 1

    def test_sequential_depletion(self):
        # two jobs, one host that fits exactly one of them
        job_res = np.array([[4, 400, 0, 0], [4, 400, 0, 0]], dtype=np.float32)
        capacity = np.array([[8, 800, 0, 0]], dtype=np.float32)
        avail = np.array([[5, 500, 0, 0]], dtype=np.float32)
        cmask = np.ones((2, 1), dtype=bool)
        arrays = host_prep.pack_match_inputs(job_res, cmask, avail, capacity)
        assign, left = greedy_match_kernel(to_inputs(arrays))
        assert list(np.asarray(assign)[:2]) == [0, -1]
        np.testing.assert_allclose(np.asarray(left)[0],
                                   [1, 100, 0, 0], rtol=1e-6)

    @pytest.mark.parametrize("seed", range(6))
    def test_randomized_exact_parity(self, seed):
        rng = np.random.default_rng(seed)
        J, H = int(rng.integers(5, 120)), int(rng.integers(3, 60))
        job_res, cmask, avail, capacity = random_case(rng, J, H, tight=bool(seed % 2))
        golden = reference_impl.greedy_match(job_res, cmask, avail, capacity)
        arrays = host_prep.pack_match_inputs(job_res, cmask, avail, capacity)
        assign, _ = greedy_match_kernel(to_inputs(arrays))
        np.testing.assert_array_equal(np.asarray(assign)[:J], golden)

    def test_gpu_dimension_feasibility(self):
        job_res = np.array([[1, 100, 2, 0]], dtype=np.float32)
        capacity = np.array([[10, 1000, 0, 0], [10, 1000, 4, 0]], dtype=np.float32)
        avail = capacity.copy()
        cmask = np.ones((1, 2), dtype=bool)
        arrays = host_prep.pack_match_inputs(job_res, cmask, avail, capacity)
        assign, _ = greedy_match_kernel(to_inputs(arrays))
        assert np.asarray(assign)[0] == 1


class TestMultipass:
    def test_never_oversubscribes(self):
        for seed in range(4):
            rng = np.random.default_rng(100 + seed)
            J, H = 80, 20
            job_res, cmask, avail, capacity = random_case(rng, J, H, tight=True)
            arrays = host_prep.pack_match_inputs(job_res, cmask, avail, capacity)
            assign, left = multipass_match_kernel(to_inputs(arrays))
            assign = np.asarray(assign)[:J]
            left = np.asarray(left)
            # availability never goes negative
            assert (left[:H] >= -1e-3).all()
            # assigned jobs respect their constraint mask
            for j, h in enumerate(assign):
                if h >= 0:
                    assert cmask[j, h]

    def test_statistical_parity_with_greedy(self):
        total = agree = 0
        placed_golden = placed_multi = 0
        for seed in range(8):
            rng = np.random.default_rng(200 + seed)
            J, H = 100, 30
            job_res, cmask, avail, capacity = random_case(rng, J, H)
            golden = reference_impl.greedy_match(job_res, cmask, avail, capacity)
            arrays = host_prep.pack_match_inputs(job_res, cmask, avail, capacity)
            assign, _ = multipass_match_kernel(to_inputs(arrays))
            assign = np.asarray(assign)[:J]
            total += J
            agree += int((assign == golden).sum())
            placed_golden += int((golden >= 0).sum())
            placed_multi += int((assign >= 0).sum())
        # The auction mode guarantees placement-*count* parity (BASELINE.md's
        # utilization-bearing metric); individual host choices may differ from
        # the sequential greedy order because fitness is computed against
        # cycle-start availability.  The greedy kernel is the bit-exact mode.
        assert placed_multi >= 0.999 * placed_golden
        assert agree / total > 0.15  # sanity: choices correlate with greedy


class TestWaterfill:
    """Prefix-packing large-J kernel: safety (never oversubscribes, honors
    the constraint mask) + statistical placement parity with greedy."""

    def test_never_oversubscribes_and_respects_cmask(self):
        from cook_tpu.ops.match import waterfill_match_kernel
        for seed in range(4):
            rng = np.random.default_rng(300 + seed)
            J, H = 80, 20
            job_res, cmask, avail, capacity = random_case(rng, J, H, tight=True)
            arrays = host_prep.pack_match_inputs(job_res, cmask, avail, capacity)
            assign, left = waterfill_match_kernel(to_inputs(arrays))
            assign = np.asarray(assign)[:J]
            assert (np.asarray(left)[:H] >= -1e-3).all()
            used = np.zeros_like(avail)
            for j, h in enumerate(assign):
                if h >= 0:
                    assert cmask[j, h]
                    used[h] += job_res[j]
            assert (used <= avail + 1e-3).all()

    def test_placement_count_parity_with_greedy(self):
        from cook_tpu.ops.match import waterfill_match_kernel
        placed_golden = placed_wf = 0
        for seed in range(8):
            rng = np.random.default_rng(400 + seed)
            J, H = 100, 30
            job_res, cmask, avail, capacity = random_case(rng, J, H)
            golden = reference_impl.greedy_match(job_res, cmask, avail, capacity)
            arrays = host_prep.pack_match_inputs(job_res, cmask, avail, capacity)
            assign, _ = waterfill_match_kernel(to_inputs(arrays))
            placed_golden += int((golden >= 0).sum())
            placed_wf += int((np.asarray(assign)[:J] >= 0).sum())
        assert placed_wf >= 0.99 * placed_golden

    def test_matcher_auto_backend_selects_by_size(self):
        """backend="auto" routes small considerable sets to the bit-exact
        greedy scan and large ones to waterfill (VERDICT r1 #9)."""
        from cook_tpu.config import MatcherConfig
        from cook_tpu.sched.matcher import Matcher

        rng = np.random.default_rng(7)
        J, H = 12, 6
        job_res, cmask, avail, capacity = random_case(rng, J, H)
        m = Matcher.__new__(Matcher)  # dispatch only; no scheduler wiring
        mc = MatcherConfig(backend="auto", auto_large_j_threshold=8)
        a_large = m._dispatch(mc, job_res, cmask, avail, capacity)
        mc_small = MatcherConfig(backend="auto", auto_large_j_threshold=1000)
        a_small = m._dispatch(mc_small, job_res, cmask, avail, capacity)
        golden = reference_impl.greedy_match(job_res, cmask, avail, capacity)
        # small path is the bit-exact greedy kernel
        assert (a_small == golden).all()
        # large path still places a comparable count without oversubscribing
        assert (a_large >= 0).sum() >= 0.9 * (golden >= 0).sum()

    def test_auto_backend_places_constraint_restricted_job(self):
        """A job whose cmask allows exactly one host must still be placed
        when the auto backend routes the bulk through waterfill (the
        exponential probe can step over sparse rows; the matcher routes
        sparse-mask jobs to the exact greedy scan instead)."""
        from cook_tpu.config import MatcherConfig
        from cook_tpu.sched.matcher import Matcher

        rng = np.random.default_rng(9)
        J, H = 17, 16
        job_res, cmask, avail, capacity = random_case(rng, J, H)
        cmask[:] = True
        cmask[0, :] = False
        cmask[0, 2] = True            # job 0 may only run on host 2
        avail[:] = capacity           # plenty of room everywhere
        m = Matcher.__new__(Matcher)
        mc = MatcherConfig(backend="auto", auto_large_j_threshold=4)
        assign = m._dispatch(mc, job_res, cmask, avail, capacity)
        assert assign[0] == 2
        # dense bulk placed too, never on a masked host, never oversubscribed
        used = np.zeros_like(avail)
        for j, h in enumerate(assign):
            if h >= 0:
                assert cmask[j, h]
                used[h] += job_res[j]
        assert (used <= avail + 1e-3).all()

    def test_dispatch_accepts_plain_lists(self):
        """match_pool passes plain Python lists; the sparse/dense split
        fancy-indexes them, so _dispatch must coerce to arrays first."""
        from cook_tpu.config import MatcherConfig
        from cook_tpu.sched.matcher import Matcher

        rng = np.random.default_rng(11)
        J, H = 12, 6
        job_res, cmask, avail, capacity = random_case(rng, J, H)
        cmask[:] = True
        cmask[0, :] = False
        cmask[0, 3] = True
        avail[:] = capacity
        m = Matcher.__new__(Matcher)
        mc = MatcherConfig(backend="auto", auto_large_j_threshold=4)
        assign = m._dispatch(mc, job_res.tolist(),
                             cmask.tolist(), avail.tolist(),
                             capacity.tolist())
        assert assign[0] == 3
        assert (assign >= 0).sum() >= J - 1


class TestWaterfillCompaction:
    """Compaction rounds migrate placements onto strictly tighter hosts:
    never lose a placement, never loosen packing (docs/
    PLACEMENT_QUALITY.md: 0.783 -> 0.822 mean util at 10k x 50k)."""

    def test_compaction_preserves_count_and_tightens(self):
        from cook_tpu.ops.match import waterfill_match_kernel
        rng = np.random.default_rng(11)
        J, H = 600, 400
        job_res = np.stack([rng.integers(1, 8, J), rng.integers(64, 2048, J),
                            np.zeros(J), np.zeros(J)], axis=1).astype(np.float32)
        avail = np.stack([np.full(H, 16.0), np.full(H, 16384.0),
                          np.zeros(H), np.full(H, 10**6)], axis=1).astype(np.float32)
        # hosts at varied initial fill so tightness ordering matters
        frac = rng.uniform(0.3, 1.0, H).astype(np.float32)
        avail[:, :2] *= frac[:, None]
        capacity = avail.copy()
        arrays = host_prep.pack_match_inputs(
            job_res, np.ones((J, H), dtype=bool), avail, capacity)
        inp = MatchInputs(job_res=jnp.asarray(arrays["job_res"]),
                          constraint_mask=jnp.asarray(arrays["constraint_mask"]),
                          avail=jnp.asarray(arrays["avail"]),
                          capacity=jnp.asarray(arrays["capacity"]),
                          valid=jnp.asarray(arrays["valid"]))
        base = np.asarray(waterfill_match_kernel(inp, num_compaction=0)[0])[:J]
        comp = np.asarray(waterfill_match_kernel(inp, num_compaction=16)[0])[:J]
        assert (comp >= 0).sum() == (base >= 0).sum()  # no lost placements

        def mean_util(assign):
            placed = assign >= 0
            used = np.zeros((H, 2))
            np.add.at(used, assign[placed], job_res[placed][:, :2])
            host_used = used.sum(axis=1) > 0
            f = used / np.maximum(avail[:, :2], 1e-9)
            return f.max(axis=1)[host_used].mean(), int(host_used.sum())
        u0, h0 = mean_util(base)
        u1, h1 = mean_util(comp)
        # every accepted move is individually tightness-improving (the
        # source/destination sets are disjoint per round); the MEAN-util
        # metric could in principle dip when a multi-job source drains,
        # so these aggregate assertions are a fixed-seed regression pin,
        # not a universal invariant
        assert u1 >= u0 - 1e-6
        assert h1 <= h0
        # availability accounting stayed consistent: no host oversubscribed
        used = np.zeros((H, 4))
        np.add.at(used, comp[comp >= 0], job_res[comp >= 0])
        assert (used <= avail + 1e-3).all()


class TestAuctionWaterfillTail:
    """The production tpu-auction backend finishes auction leftovers with
    waterfill (matcher._run_kernel): full placement at tighter-than-
    waterfill packing (docs/PLACEMENT_QUALITY.md: 10000/10000 at 0.923
    mean util vs waterfill-alone 0.822 at 10k x 50k)."""

    def test_tail_places_leftovers_without_oversubscription(self):
        from cook_tpu.config import MatcherConfig
        from cook_tpu.sched.matcher import Matcher
        rng = np.random.default_rng(7)
        J, H = 1200, 500   # contended: auction alone leaves a residual
        job_res = np.stack([rng.integers(1, 8, J),
                            rng.integers(64, 2048, J),
                            np.zeros(J), np.zeros(J)],
                           axis=1).astype(np.float32)
        avail = np.stack([np.full(H, 24.0), np.full(H, 24576.0),
                          np.zeros(H), np.full(H, 10**6)],
                         axis=1).astype(np.float32)
        capacity = avail.copy()
        cmask = np.ones((J, H), dtype=bool)
        mc = MatcherConfig(backend="tpu-auction")
        matcher = Matcher.__new__(Matcher)  # _run_kernel needs no state
        assign, left = matcher._run_kernel(
            "tpu-auction", mc, job_res, cmask, avail, capacity)
        placed = assign >= 0
        # auction+tail must match the greedy placement count
        g_assign, _ = matcher._run_kernel(
            "tpu-greedy", mc, job_res, cmask, avail, capacity)
        assert placed.sum() == (g_assign >= 0).sum()
        used = np.zeros((H, 4))
        np.add.at(used, assign[placed], job_res[placed])
        assert (used <= avail + 1e-2).all()
        # remaining availability accounting is consistent
        assert np.allclose(np.asarray(left), avail - used, atol=1e-2)


class TestAdaptiveAuctionConvergence:
    """The adaptive refresh loop reaches greedy's placement count on a
    contended workload where the historical fixed-8 budget could not
    (kernel-level twin of the verify drive probe)."""

    def test_contended_reaches_greedy_count(self):
        from cook_tpu.ops.match import auction_match_kernel
        rng = np.random.default_rng(11)
        # moderately contended with VARIED host fill: enough hosts that
        # the K=16 preference structure doesn't exhaust, and varied
        # utilization so fitness ties don't herd every proposal onto the
        # same hosts (perfectly uniform hosts are the pathological case;
        # the production path's waterfill tail covers residuals there,
        # TestAuctionWaterfillTail)
        J, H = 2000, 3000
        job_res = np.stack([
            rng.integers(1, 16, J).astype(np.float32),
            rng.integers(64, 4096, J).astype(np.float32),
            np.zeros(J, dtype=np.float32),
            np.zeros(J, dtype=np.float32)], axis=1)
        # heterogeneous, partially consumed hosts like real offers (the
        # bench workload shape): varied capacity and fill differentiate
        # bin-packing fitness so proposals spread; perfectly uniform
        # hosts tie everywhere and herd (that pathological regime is the
        # production tail's job, TestAuctionWaterfillTail)
        capacity = np.stack([
            rng.integers(16, 128, H).astype(np.float32),
            rng.integers(4096, 65536, H).astype(np.float32),
            np.zeros(H, dtype=np.float32),
            np.full(H, 1e6, dtype=np.float32)], axis=1)
        avail = (capacity * rng.uniform(0.3, 1.0, (H, 1))).astype(np.float32)
        arrays = host_prep.pack_match_inputs(
            job_res, np.ones((J, H), dtype=bool), avail, capacity)
        inp = to_inputs(arrays) if "to_inputs" in globals() else None
        if inp is None:
            import jax.numpy as jnp2
            from cook_tpu.ops import MatchInputs as MI
            inp = MI(job_res=jnp2.asarray(arrays["job_res"]),
                     constraint_mask=jnp2.asarray(arrays["constraint_mask"]),
                     avail=jnp2.asarray(arrays["avail"]),
                     capacity=jnp2.asarray(arrays["capacity"]),
                     valid=jnp2.asarray(arrays["valid"]))
        adaptive = int((np.asarray(
            auction_match_kernel(inp)[0])[:J] >= 0).sum())
        fixed8 = int((np.asarray(auction_match_kernel(
            inp, num_refresh=8)[0])[:J] >= 0).sum())
        greedy = int((np.asarray(
            greedy_match_kernel(inp)[0])[:J] >= 0).sum())
        assert adaptive >= 0.99 * greedy, (adaptive, greedy)
        assert adaptive >= fixed8  # never worse than the old budget


class TestAutoPackingPolicy:
    def test_resolve_backend_auto_packing(self):
        from cook_tpu.config import MatcherConfig
        from cook_tpu.sched.matcher import Matcher
        mc = MatcherConfig()
        assert Matcher.resolve_backend(mc, 100) == "tpu-greedy"
        assert Matcher.resolve_backend(mc, 5000) == "tpu-waterfill"
        mc.auto_packing = "tight"
        assert Matcher.resolve_backend(mc, 100) == "tpu-greedy"
        assert Matcher.resolve_backend(mc, 5000) == "tpu-auction"
        mc.backend = "tpu-waterfill"  # explicit backend always wins
        assert Matcher.resolve_backend(mc, 5000) == "tpu-waterfill"

    @staticmethod
    def _uniform_inp(J, H):
        import jax.numpy as jnp
        from cook_tpu.ops import MatchInputs, host_prep
        job_res = np.tile(np.array([1.0, 64.0, 0.0, 1.0], np.float32),
                          (J, 1))
        cap = np.tile(np.array([8.0, 8192.0, 0.0, 1e9], np.float32),
                      (H, 1))
        cmask = np.ones((J, H), dtype=bool)
        arrays = host_prep.pack_match_inputs(job_res, cmask, cap.copy(),
                                             cap)
        return MatchInputs(
            job_res=jnp.asarray(arrays["job_res"]),
            constraint_mask=jnp.asarray(arrays["constraint_mask"]),
            avail=jnp.asarray(arrays["avail"]),
            capacity=jnp.asarray(arrays["capacity"]),
            valid=jnp.asarray(arrays["valid"]))

    def test_uniform_fleet_tie_break_places_everything(self):
        """On a PERFECTLY uniform fleet every host ties on bin-packing
        fitness; without the deterministic per-(job, host) tie-break the
        herd exhausts ~K hosts per refresh pass and the adaptive exit
        fires early (measured r5: 2048/5000 placed on a fleet fitting
        16000).  The tie-break's contract is PLACEMENT COMPLETENESS: it
        trades first-pass packing tightness (jobs spread over tied empty
        hosts) for convergence; once hosts differentiate, fitness packs
        again."""
        from cook_tpu.ops.match import auction_match_kernel
        inp = self._uniform_inp(1000, 256)  # fleet fits 2048
        assign = np.asarray(auction_match_kernel(inp)[0])[:1000]
        assert (assign >= 0).sum() == 1000

    def test_uniform_fleet_saturates_at_capacity(self):
        """When the uniform fleet fits FEWER jobs than offered, every
        slot must fill (the herding failure left most slots empty)."""
        from cook_tpu.ops.match import auction_match_kernel
        inp = self._uniform_inp(1000, 100)  # fleet fits 800 < 1000
        assign = np.asarray(auction_match_kernel(inp)[0])[:1000]
        assert (assign >= 0).sum() == 800
