"""Native transport cluster backend: cook_agentd + libcooktransport driver
(the framework's libmesos-equivalent, reference: mesos_compute_cluster.clj
+ executor/cook/executor.py)."""

import time
from pathlib import Path

import pytest

from cook_tpu.cluster.remote import (
    AgentConnection,
    LocalAgentProcess,
    RemoteComputeCluster,
    native_available,
)
from cook_tpu.state.schema import InstanceStatus, JobState, Reasons

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="C++ toolchain unavailable")


@pytest.fixture
def agent(tmp_path):
    a = LocalAgentProcess("nodeA", cpus=4.0, mem=4096.0,
                          workdir=str(tmp_path))
    yield a
    a.stop()


def wait_for(pred, timeout=10.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


class TestAgentConnection:
    def test_registered_info(self, agent):
        conn = AgentConnection("127.0.0.1", agent.port)
        assert conn.hostname == "nodeA"
        assert conn.capacity.cpus == 4.0 and conn.capacity.mem == 4096.0
        assert conn.running_at_connect == []
        conn.close()

    def test_launch_status_stream(self, agent):
        conn = AgentConnection("127.0.0.1", agent.port)
        assert conn.launch("t-ok", "echo out; echo err >&2; exit 0", 1, 64)
        events = []
        while len(events) < 2:
            ev = conn.poll(timeout_ms=2000)
            assert ev is not None, f"timed out, got {events}"
            events.append(ev)
        assert events[0][:3] == ["STATUS", "t-ok", "running"]
        assert events[1][:4] == ["STATUS", "t-ok", "finished", "0"]
        sandbox = events[1][4]
        assert open(sandbox + "/stdout").read() == "out\n"
        assert open(sandbox + "/stderr").read() == "err\n"
        conn.close()

    def test_launch_refuses_wire_delimiter_in_fields(self, agent):
        # the env/volume/docker-parameter channels are \x1e-joined on the
        # wire and split agent-side: an embedded \x1e in any value would
        # inject extra entries (e.g. a --privileged runtime flag) past
        # REST validation, so the transport refuses the launch outright
        conn = AgentConnection("127.0.0.1", agent.port)
        try:
            assert not conn.launch(
                "t-evil-param", "true", 1, 64, image="img",
                params=[{"key": "env", "value": "A=B\x1eprivileged="}])
            assert not conn.launch(
                "t-evil-env", "true", 1, 64,
                env={"GOOD": "x\x1eBAD=y"})
            assert not conn.launch(
                "t-evil-vol", "true", 1, 64, image="img",
                volumes=["/a:/b\x1e/etc:/host-etc"])
            # NUL would truncate the C-string at the ctypes boundary,
            # silently dropping everything marshaled after it
            assert not conn.launch(
                "t-evil-nul", "true", 1, 64,
                env={"A": "x\x00"})
            assert not conn.launch(
                "t-evil-cmd", "echo hi\x00", 1, 64)
            # clean launch still goes through on the same connection
            assert conn.launch("t-clean", "true", 1, 64,
                               env={"GOOD": "val"})
        finally:
            conn.close()

    def test_nonzero_exit_is_failed(self, agent):
        conn = AgentConnection("127.0.0.1", agent.port)
        conn.launch("t-bad", "exit 3", 1, 64)
        terminal = None
        for _ in range(20):
            ev = conn.poll(timeout_ms=2000)
            if ev and ev[1] == "t-bad" and ev[2] != "running":
                terminal = ev
                break
        assert terminal[2] == "failed" and terminal[3] == "3"
        conn.close()

    def test_kill_escalation(self, agent):
        conn = AgentConnection("127.0.0.1", agent.port)
        # the shell ignores TERM and respawns its sleep children, so only
        # the SIGKILL escalation can end it; "running" is broadcast at fork
        # time, so wait for the ready marker before killing or the TERM can
        # land before the trap is installed
        conn.launch("t-stuck",
                    "trap '' TERM; touch ready; while true; do sleep 0.2; done",
                    1, 64)
        ev = conn.poll(timeout_ms=2000)
        assert ev[2] == "running"
        sandbox = ev[4]
        assert wait_for(lambda: (Path(sandbox) / "ready").exists())
        conn.kill("t-stuck", grace_ms=300)
        terminal = None
        for _ in range(40):
            ev = conn.poll(timeout_ms=500)
            if ev and ev[1] == "t-stuck" and ev[2] != "running":
                terminal = ev
                break
        assert terminal is not None, "kill escalation never landed"
        assert terminal[2] == "killed"
        assert terminal[3] == str(128 + 9)  # SIGKILL
        conn.close()

    def test_reconcile_replays_state(self, agent):
        c1 = AgentConnection("127.0.0.1", agent.port)
        c1.launch("t-live", "sleep 30", 1, 64)
        assert c1.poll(timeout_ms=2000)[2] == "running"
        # a second driver connection sees the live task at registration
        c2 = AgentConnection("127.0.0.1", agent.port)
        assert c2.running_at_connect == ["t-live"]
        c2.reconcile()
        seen = []
        while True:
            ev = c2.poll(timeout_ms=2000)
            assert ev is not None
            if ev[0] == "RECONCILE_DONE":
                break
            seen.append(ev)
        assert ["STATUS", "t-live", "running"] in [e[:3] for e in seen]
        c1.kill("t-live", grace_ms=100)
        c1.close()
        c2.close()


class TestRemoteComputeCluster:
    def _mk(self, agents, store=None):
        cluster = RemoteComputeCluster(
            "remote-1", [("127.0.0.1", a.port) for a in agents], store=store,
            kill_grace_ms=300)
        return cluster

    def test_offers_track_consumption(self, agent):
        from cook_tpu.cluster.base import LaunchSpec
        from cook_tpu.state.schema import Resources

        updates = []
        cluster = self._mk([agent])
        cluster.initialize(lambda tid, st, rc, **kw: updates.append((tid, st)))
        [offer] = cluster.pending_offers("default")
        assert offer.hostname == "nodeA" and offer.available.cpus == 4.0
        cluster.launch_tasks("default", [LaunchSpec(
            task_id="t-c1", job_uuid="j1", hostname="nodeA", slave_id="",
            resources=Resources(cpus=1.5, mem=512.0))])
        [offer] = cluster.pending_offers("default")
        assert offer.available.cpus == 2.5 and offer.task_count == 1
        # default command is "true" (no store) -> completes, frees capacity
        assert wait_for(lambda: (("t-c1", InstanceStatus.SUCCESS) in updates))
        [offer] = cluster.pending_offers("default")
        assert offer.available.cpus == 4.0
        cluster.shutdown()

    def test_agent_loss_is_node_lost(self, tmp_path):
        from cook_tpu.cluster.base import LaunchSpec
        from cook_tpu.state.schema import Resources

        from cook_tpu.state import Job, Store, new_uuid

        agent = LocalAgentProcess("nodeB", workdir=str(tmp_path / "b"))
        updates = []
        store = Store()
        job = Job(uuid=new_uuid(), user="alice", command="sleep 60",
                  pool="default", resources=Resources(cpus=1.0, mem=64.0))
        store.create_jobs([job])
        cluster = self._mk([agent], store=store)
        cluster.initialize(
            lambda tid, st, rc, **kw: updates.append((tid, st, rc)))
        cluster.launch_tasks("default", [LaunchSpec(
            task_id="t-lost", job_uuid=job.uuid, hostname="nodeB",
            slave_id="", resources=Resources(cpus=1.0, mem=64.0))])
        assert wait_for(lambda: any(t == "t-lost" and s is InstanceStatus.RUNNING
                                    for t, s, _ in updates))
        agent.proc.kill()  # node dies hard
        assert wait_for(lambda: any(
            t == "t-lost" and s is InstanceStatus.FAILED
            and rc == Reasons.NODE_LOST.code for t, s, rc in updates))
        assert cluster.pending_offers("default") == []
        cluster.shutdown()


class TestReconnectAndRobustness:
    def test_unreachable_endpoint_does_not_block_healthy(self, agent):
        cluster = RemoteComputeCluster(
            "remote-1", [("127.0.0.1", 1), ("127.0.0.1", agent.port)])
        cluster.initialize(lambda *a, **k: None)
        assert [o.hostname for o in cluster.pending_offers("default")] \
            == ["nodeA"]
        cluster.shutdown()

    def test_restart_adopts_live_tasks(self, agent):
        """Scheduler restart: a fresh cluster object reconnecting to an
        agent with a live task must subtract its consumption from offers
        (reference: state reconstruction on re-register)."""
        from cook_tpu.state import Job, Store, new_uuid
        from cook_tpu.state.schema import Resources

        store = Store()
        job = Job(uuid=new_uuid(), user="a", command="sleep 30",
                  pool="default", resources=Resources(cpus=2.0, mem=256.0))
        store.create_jobs([job])
        updates = []
        c1 = RemoteComputeCluster(
            "remote-1", [("127.0.0.1", agent.port)], store=store)
        c1.initialize(lambda tid, st, rc, **kw: updates.append((tid, st)))
        from cook_tpu.cluster.base import LaunchSpec
        store.launch_instance(job.uuid, "t-adopt", hostname="nodeA",
                              compute_cluster="remote-1")
        c1.launch_tasks("default", [LaunchSpec(
            task_id="t-adopt", job_uuid=job.uuid, hostname="nodeA",
            slave_id="", resources=job.resources)])
        # wait until the agent actually runs it (launch_tasks tracks the
        # task synchronously, before the agent has forked)
        assert wait_for(lambda: ("t-adopt", InstanceStatus.RUNNING)
                        in updates)
        # "restart": new cluster object, same agent
        c2 = RemoteComputeCluster(
            "remote-1", [("127.0.0.1", agent.port)], store=store)
        c2.initialize(lambda *a, **k: None)
        [offer] = c2.pending_offers("default")
        assert offer.available.cpus == 2.0  # 4 - 2 adopted
        assert offer.task_count == 1
        c1.kill_task("t-adopt")
        c1.shutdown()
        c2.shutdown()

    def test_store_reconcile_marks_unknown_tasks_node_lost(self, agent):
        """A task the store believes is running on this cluster but no
        agent knows about becomes NODE_LOST at initialize."""
        from cook_tpu.state import Job, Store, new_uuid
        from cook_tpu.state.schema import Resources

        store = Store()
        job = Job(uuid=new_uuid(), user="a", command="sleep 30",
                  pool="default", resources=Resources(cpus=1.0, mem=64.0))
        store.create_jobs([job])
        store.launch_instance(job.uuid, "t-ghost", hostname="gone-node",
                              compute_cluster="remote-1")
        store.update_instance_status("t-ghost", InstanceStatus.RUNNING)
        updates = []
        cluster = RemoteComputeCluster(
            "remote-1", [("127.0.0.1", agent.port)], store=store)
        cluster.initialize(
            lambda tid, st, rc, **kw: updates.append((tid, st, rc)))
        assert ("t-ghost", InstanceStatus.FAILED,
                Reasons.NODE_LOST.code) in updates
        cluster.shutdown()

    def test_missing_job_command_fails_launch(self, agent):
        """No silent 'true' substitute: an unresolvable command must fail
        the task, not fake a success."""
        from cook_tpu.cluster.base import LaunchSpec
        from cook_tpu.state import Store
        from cook_tpu.state.schema import Resources

        store = Store()  # job uuid not present
        updates = []
        cluster = RemoteComputeCluster(
            "remote-1", [("127.0.0.1", agent.port)], store=store)
        cluster.initialize(
            lambda tid, st, rc, **kw: updates.append((tid, st, rc)))
        cluster.launch_tasks("default", [LaunchSpec(
            task_id="t-nocmd", job_uuid="no-such-job", hostname="nodeA",
            slave_id="", resources=Resources(cpus=1.0, mem=64.0))])
        assert ("t-nocmd", InstanceStatus.FAILED,
                Reasons.CONTAINER_LAUNCH_FAILED.code) in updates
        [offer] = cluster.pending_offers("default")
        assert offer.available.cpus == 4.0  # nothing left tracked
        cluster.shutdown()


class TestSchedulerIntegration:
    def test_end_to_end_real_processes(self, agent, tmp_path):
        """submit -> rank -> match -> native launch -> real /bin/sh run ->
        status -> job completed, with sandbox writeback."""
        from cook_tpu.config import Config
        from cook_tpu.sched import Scheduler
        from cook_tpu.state import Job, Resources, Store, new_uuid

        store = Store()
        cluster = RemoteComputeCluster(
            "remote-1", [("127.0.0.1", agent.port)], store=store)
        cfg = Config()
        cfg.default_matcher.backend = "cpu"
        sched = Scheduler(store, cfg, [cluster], rank_backend="cpu")
        marker = tmp_path / "ran.txt"
        good = Job(uuid=new_uuid(), user="alice",
                   command=f"echo done > {marker}",
                   pool="default", resources=Resources(cpus=1.0, mem=128.0))
        bad = Job(uuid=new_uuid(), user="bob", command="exit 7",
                  pool="default", max_retries=1,
                  resources=Resources(cpus=1.0, mem=128.0))
        store.create_jobs([good, bad])
        sched.step_rank()
        res = sched.step_match()["default"]
        assert len(res.launched_task_ids) == 2

        def settled():
            sched.flush_status_updates()
            return (store.job(good.uuid).state is JobState.COMPLETED
                    and store.job(bad.uuid).state is JobState.COMPLETED)
        assert wait_for(settled, timeout=15)
        assert marker.read_text().strip() == "done"
        g_insts = [store.instance(t) for t in store.job(good.uuid).instances]
        assert any(i.status is InstanceStatus.SUCCESS for i in g_insts)
        b_insts = [store.instance(t) for t in store.job(bad.uuid).instances]
        failed = [i for i in b_insts if i.status is InstanceStatus.FAILED]
        assert failed and failed[0].exit_code == 7
        assert failed[0].sandbox_directory  # writeback happened
        cluster.shutdown()

    def test_kill_running_job(self, agent):
        from cook_tpu.config import Config
        from cook_tpu.sched import Scheduler
        from cook_tpu.state import Job, Resources, Store, new_uuid

        store = Store()
        cluster = RemoteComputeCluster(
            "remote-1", [("127.0.0.1", agent.port)], store=store,
            kill_grace_ms=300)
        cfg = Config()
        cfg.default_matcher.backend = "cpu"
        sched = Scheduler(store, cfg, [cluster], rank_backend="cpu")
        job = Job(uuid=new_uuid(), user="alice", command="sleep 60",
                  pool="default", resources=Resources(cpus=1.0, mem=128.0))
        store.create_jobs([job])
        sched.step_rank()
        [tid] = sched.step_match()["default"].launched_task_ids

        def running():
            sched.flush_status_updates()
            inst = store.instance(tid)
            return inst is not None and inst.status is InstanceStatus.RUNNING
        assert wait_for(running)
        store.kill_job(job.uuid)  # tx-report side effect kills the live task

        def dead():
            sched.flush_status_updates()
            return store.job(job.uuid).state is JobState.COMPLETED
        assert wait_for(dead, timeout=15)
        cluster.shutdown()


class TestPortsAndContainers:
    """Port assignment + container compilation at launch (reference:
    mesos/task.clj:114-294 — port ranges into PORT0../env, container
    image/volumes compiled into every task)."""

    def test_ports_assigned_and_recorded(self, tmp_path):
        from cook_tpu.config import Config
        from cook_tpu.sched import Scheduler
        from cook_tpu.state import Job, Resources, Store, new_uuid

        agent = LocalAgentProcess("nodeP", workdir=str(tmp_path),
                                  ports_begin=21000, ports_end=21010)
        try:
            store = Store()
            cluster = RemoteComputeCluster(
                "remote-1", [("127.0.0.1", agent.port)], store=store)
            cfg = Config()
            cfg.default_matcher.backend = "cpu"
            sched = Scheduler(store, cfg, [cluster], rank_backend="cpu")
            out = tmp_path / "ports.txt"
            job = Job(uuid=new_uuid(), user="alice",
                      command=f'echo "$PORT0 $PORT1 $COOK_PORT0" > {out}',
                      ports=2, env={"MY_VAR": "my-value"},
                      pool="default", resources=Resources(cpus=1.0, mem=64.0))
            probe = tmp_path / "env.txt"
            envjob = Job(uuid=new_uuid(), user="alice",
                         command=f'echo "$MY_VAR" > {probe}',
                         env={"MY_VAR": "my-value"},
                         pool="default", resources=Resources(cpus=1.0, mem=64.0))
            store.create_jobs([job, envjob])
            sched.step_rank()
            sched.step_match()

            def done():
                sched.flush_status_updates()
                return (store.job(job.uuid).state is JobState.COMPLETED
                        and store.job(envjob.uuid).state is JobState.COMPLETED)
            assert wait_for(done, timeout=15)
            insts = [store.instance(t) for t in store.job(job.uuid).instances]
            inst = next(i for i in insts
                        if i.status is InstanceStatus.SUCCESS)
            assert len(inst.ports) == 2
            assert all(21000 <= p < 21010 for p in inst.ports)
            assert len(set(inst.ports)) == 2
            # task saw its assigned ports in the environment
            p0, p1, c0 = out.read_text().split()
            assert [int(p0), int(p1)] == inst.ports
            assert int(c0) == inst.ports[0]
            # plain env passthrough
            assert probe.read_text().strip() == "my-value"
            cluster.shutdown()
        finally:
            agent.stop()

    def test_port_exhaustion_fails_launch(self, tmp_path):
        agent = LocalAgentProcess("nodeQ", workdir=str(tmp_path),
                                  ports_begin=22000, ports_end=22001)
        try:
            conn = AgentConnection("127.0.0.1", agent.port)
            assert conn.launch("t-ports", "sleep 5", 1, 64, port_count=2)
            ev = conn.poll(timeout_ms=2000)
            assert ev is not None and ev[:3] == ["STATUS", "t-ports", "failed"]
            conn.close()
        finally:
            agent.stop()

    def test_ports_released_after_terminal(self, tmp_path):
        agent = LocalAgentProcess("nodeR", workdir=str(tmp_path),
                                  ports_begin=23000, ports_end=23001)
        try:
            conn = AgentConnection("127.0.0.1", agent.port)
            assert conn.launch("t-a", "true", 1, 64, port_count=1)
            seen = []
            while not any(e[1] == "t-a" and e[2] in ("finished", "failed")
                          for e in seen):
                ev = conn.poll(timeout_ms=3000)
                assert ev is not None
                seen.append(ev)
            # the single port in the range is free again
            assert conn.launch("t-b", "true", 1, 64, port_count=1)
            seen = []
            while not any(e[1] == "t-b" and e[2] == "finished" for e in seen):
                ev = conn.poll(timeout_ms=3000)
                assert ev is not None
                seen.append(ev)
            running = [e for e in seen if e[1] == "t-b" and e[2] == "running"]
            assert running and running[0][5] == "23000"
            conn.close()
        finally:
            agent.stop()

    def test_container_launch_uses_runtime(self, tmp_path):
        """A job with a container image runs through the configured runtime
        (a recording fake standing in for docker/podman)."""
        from cook_tpu.config import Config
        from cook_tpu.sched import Scheduler
        from cook_tpu.state import Job, Resources, Store, new_uuid

        record = tmp_path / "runtime-args.txt"
        fake_rt = tmp_path / "fake-docker"
        # records its argv, then execs the trailing `/bin/sh -c <cmd>`
        fake_rt.write_text(
            "#!/bin/sh\n"
            f'echo "$@" > {record}\n'
            'while [ "$1" != "/bin/sh" ] && [ $# -gt 0 ]; do shift; done\n'
            'exec "$@"\n')
        fake_rt.chmod(0o755)

        agent = LocalAgentProcess("nodeC", workdir=str(tmp_path / "w"),
                                  container_runtime=str(fake_rt))
        try:
            store = Store()
            cluster = RemoteComputeCluster(
                "remote-1", [("127.0.0.1", agent.port)], store=store)
            cfg = Config()
            cfg.default_matcher.backend = "cpu"
            sched = Scheduler(store, cfg, [cluster], rank_backend="cpu")
            out = tmp_path / "cout.txt"
            job = Job(uuid=new_uuid(), user="alice",
                      command=f"echo from-container > {out}",
                      container={"image": "busybox:1.36",
                                 "volumes": ["/data:/mnt/data"]},
                      pool="default", resources=Resources(cpus=1.0, mem=64.0))
            store.create_jobs([job])
            sched.step_rank()
            sched.step_match()

            def done():
                sched.flush_status_updates()
                return store.job(job.uuid).state is JobState.COMPLETED
            assert wait_for(done, timeout=15)
            assert out.read_text().strip() == "from-container"
            args = record.read_text()
            assert "run" in args and "busybox:1.36" in args
            assert "/data:/mnt/data" in args  # volume compiled in
            cluster.shutdown()
        finally:
            agent.stop()

    def test_no_runtime_runs_command_directly(self, tmp_path):
        """Without --container-runtime the image is ignored and the command
        still runs (documented fallback, not a silent failure)."""
        agent = LocalAgentProcess("nodeD", workdir=str(tmp_path))
        try:
            conn = AgentConnection("127.0.0.1", agent.port)
            assert conn.launch("t-c", "true", 1, 64, image="busybox")
            seen = []
            while not any(e[1] == "t-c" and e[2] == "finished" for e in seen):
                ev = conn.poll(timeout_ms=3000)
                assert ev is not None
                seen.append(ev)
            conn.close()
        finally:
            agent.stop()


class TestUriFetch:
    def test_local_uri_copied_executable_and_archive_extracted(self, agent,
                                                               tmp_path):
        """URI artifacts land in the sandbox before the command runs
        (reference: mesos fetcher semantics from :job/uri)."""
        import subprocess as sp

        from cook_tpu.config import Config
        from cook_tpu.sched import Scheduler
        from cook_tpu.state import Job, Resources, Store, new_uuid

        tool = tmp_path / "tool.sh"
        tool.write_text("#!/bin/sh\necho tool-ran\n")
        archive = tmp_path / "data.tar"
        datafile = tmp_path / "payload.txt"
        datafile.write_text("payload\n")
        sp.run(["tar", "-cf", str(archive), "-C", str(tmp_path),
                "payload.txt"], check=True)

        store = Store()
        cluster = RemoteComputeCluster(
            "remote-1", [("127.0.0.1", agent.port)], store=store)
        cfg = Config()
        cfg.default_matcher.backend = "cpu"
        sched = Scheduler(store, cfg, [cluster], rank_backend="cpu")
        out = tmp_path / "uri-out.txt"
        job = Job(uuid=new_uuid(), user="alice",
                  command=f"./tool.sh > {out}; cat payload.txt >> {out}",
                  uris=[{"value": str(tool), "executable": True},
                        {"value": f"file://{archive}", "extract": True}],
                  pool="default", resources=Resources(cpus=1.0, mem=64.0))
        store.create_jobs([job])
        sched.step_rank()
        sched.step_match()

        def done():
            sched.flush_status_updates()
            return store.job(job.uuid).state is JobState.COMPLETED
        assert wait_for(done, timeout=15)
        insts = [store.instance(t) for t in store.job(job.uuid).instances]
        assert any(i.status is InstanceStatus.SUCCESS for i in insts), \
            [(i.status, i.exit_code) for i in insts]
        assert out.read_text() == "tool-ran\npayload\n"
        cluster.shutdown()

    def test_missing_uri_fails_task_before_command(self, agent, tmp_path):
        from cook_tpu.config import Config
        from cook_tpu.sched import Scheduler
        from cook_tpu.state import Job, Resources, Store, new_uuid

        store = Store()
        cluster = RemoteComputeCluster(
            "remote-1", [("127.0.0.1", agent.port)], store=store)
        cfg = Config()
        cfg.default_matcher.backend = "cpu"
        sched = Scheduler(store, cfg, [cluster], rank_backend="cpu")
        marker = tmp_path / "never.txt"
        job = Job(uuid=new_uuid(), user="alice",
                  command=f"echo ran > {marker}",
                  uris=[{"value": str(tmp_path / "does-not-exist.bin")}],
                  max_retries=1,
                  pool="default", resources=Resources(cpus=1.0, mem=64.0))
        store.create_jobs([job])
        sched.step_rank()
        sched.step_match()

        def done():
            sched.flush_status_updates()
            return store.job(job.uuid).state is JobState.COMPLETED
        assert wait_for(done, timeout=15)
        insts = [store.instance(t) for t in store.job(job.uuid).instances]
        assert all(i.status is InstanceStatus.FAILED for i in insts)
        assert not marker.exists()  # user command never ran
        cluster.shutdown()


class TestCookExecutorChoice:
    def test_executor_cook_tracks_progress_through_rest(self, agent,
                                                        tmp_path):
        """:job/executor "cook" wraps the command in the progress-tracking
        executor; progress lines in stdout land on the instance through
        POST /progress (reference: executor choice in task.clj:114-160 +
        progress plumbing)."""
        from cook_tpu.config import Config
        from cook_tpu.rest.api import ApiServer, CookApi
        from cook_tpu.sched import Scheduler
        from cook_tpu.state import Job, Resources, Store, new_uuid

        store = Store()
        cfg = Config()
        cfg.default_matcher.backend = "cpu"
        cluster = RemoteComputeCluster(
            "remote-1", [("127.0.0.1", agent.port)], store=store)
        sched = Scheduler(store, cfg, [cluster], rank_backend="cpu")
        srv = ApiServer(CookApi(store, scheduler=sched))
        srv.start()
        cluster.progress_url = f"http://127.0.0.1:{srv.port}"
        try:
            job = Job(uuid=new_uuid(), user="alice",
                      command='echo "progress: 30 warming"; sleep 0.3; '
                              'echo "progress: 80 almost"; sleep 0.2',
                      executor="cook",
                      pool="default",
                      resources=Resources(cpus=1.0, mem=128.0))
            store.create_jobs([job])
            sched.step_rank()
            sched.step_match()

            def done():
                sched.flush_status_updates()
                return store.job(job.uuid).state is JobState.COMPLETED
            assert wait_for(done, timeout=20)
            insts = [store.instance(t)
                     for t in store.job(job.uuid).instances]
            inst = next(i for i in insts
                        if i.status is InstanceStatus.SUCCESS)
            assert inst.progress == 80
            assert inst.progress_message == "almost"
        finally:
            srv.stop()
            cluster.shutdown()

    def test_kill_cook_executor_job_kills_workload(self, agent, tmp_path):
        """Killing a cook-executor task must kill the USER COMMAND, not
        just the wrapper (the wrapper forwards SIGTERM to the child's
        session — otherwise the workload survives in its own pgid)."""
        import subprocess as sp

        from cook_tpu.config import Config
        from cook_tpu.sched import Scheduler
        from cook_tpu.state import Job, Resources, Store, new_uuid

        store = Store()
        cluster = RemoteComputeCluster(
            "remote-1", [("127.0.0.1", agent.port)], store=store,
            kill_grace_ms=6000)
        cfg = Config()
        cfg.default_matcher.backend = "cpu"
        sched = Scheduler(store, cfg, [cluster], rank_backend="cpu")
        pidfile = tmp_path / "workload.pid"
        job = Job(uuid=new_uuid(), user="alice",
                  command=f"echo $$ > {pidfile}; sleep 300",
                  executor="cook", pool="default",
                  resources=Resources(cpus=1.0, mem=128.0))
        store.create_jobs([job])
        sched.step_rank()
        sched.step_match()
        # wait for CONTENT, not existence: the shell's `>` redirect
        # creates the file empty before echo writes the pid (a loaded
        # box can observe the gap and int("") here)
        assert wait_for(
            lambda: pidfile.exists() and pidfile.read_text().strip(),
            timeout=10)
        workload_pid = int(pidfile.read_text())
        store.kill_job(job.uuid)

        def done():
            sched.flush_status_updates()
            return store.job(job.uuid).state is JobState.COMPLETED
        assert wait_for(done, timeout=20)

        def workload_gone():
            try:
                import os
                os.kill(workload_pid, 0)
                return False
            except ProcessLookupError:
                return True
        assert wait_for(workload_gone, timeout=10), \
            "user command survived the kill"
        cluster.shutdown()


class TestMemoryLimit:
    """The agent's memory watchdog (reference integration tier:
    test_basic.py memory-limit scenarios — 'Container memory limit
    exceeded'): a task whose session RSS exceeds its requested mem is
    hard-killed and reported with the distinct memlimit reason."""

    def test_over_limit_killed_under_limit_survives(self, tmp_path):
        import time as _time

        from cook_tpu.cluster.remote import (LocalAgentProcess,
                                             RemoteComputeCluster)
        from cook_tpu.cluster.base import LaunchSpec
        from cook_tpu.state import (InstanceStatus, Job, Reasons,
                                    Resources, Store)

        agent = LocalAgentProcess("memnode", cpus=4, mem=4096,
                                  workdir=str(tmp_path))
        store = Store()
        # hog: a python process growing well past its 32 MiB budget;
        # the task command comes from the store's Job (task compilation)
        hog = ("python3 -c \"import time\nx=[]\n"
               "for i in range(400): x.append(' '*1048576)\n"
               "time.sleep(60)\"")
        store.create_jobs([
            Job(uuid="00000000-0000-0000-0000-00000000f00d", user="u",
                command=hog, resources=Resources(cpus=1.0, mem=32.0)),
            Job(uuid="00000000-0000-0000-0000-00000000beef", user="u",
                command="sleep 2",
                resources=Resources(cpus=1.0, mem=256.0))])
        cluster = RemoteComputeCluster(
            "mem-test", [("127.0.0.1", agent.port)], store=store)
        updates = []
        cluster.initialize(
            lambda tid, status, reason, **kw:
            updates.append((tid, status, reason)))
        try:
            cluster.launch_tasks("default", [LaunchSpec(
                task_id="mem-hog",
                job_uuid="00000000-0000-0000-0000-00000000f00d",
                hostname="memnode", slave_id="memnode",
                resources=Resources(cpus=1.0, mem=32.0), env={})])
            # well-behaved neighbor under the same agent
            cluster.launch_tasks("default", [LaunchSpec(
                task_id="mem-ok",
                job_uuid="00000000-0000-0000-0000-00000000beef",
                hostname="memnode", slave_id="memnode",
                resources=Resources(cpus=1.0, mem=256.0), env={})])
            deadline = _time.time() + 30
            while _time.time() < deadline:
                if any(t == "mem-hog" and s is InstanceStatus.FAILED
                       for t, s, _ in updates) and \
                   any(t == "mem-ok" and s is InstanceStatus.SUCCESS
                       for t, s, _ in updates):
                    break
                _time.sleep(0.2)
            hog_final = [r for t, s, r in updates
                         if t == "mem-hog" and s is InstanceStatus.FAILED]
            assert hog_final, f"hog not killed: {updates}"
            assert hog_final[0] == Reasons.MEMORY_LIMIT_EXCEEDED.code, \
                updates
            ok_final = [s for t, s, _ in updates if t == "mem-ok"
                        and s is not InstanceStatus.RUNNING]
            assert ok_final == [InstanceStatus.SUCCESS], updates
        finally:
            cluster.shutdown()
            agent.stop()


class TestTaskEnvironment:
    """The COOK_* task identity environment (reference: mesos/task.clj:
    114-135; integration test_job_environment_cook_job_and_instance_uuid_
    only / _and_group_uuid): every task sees its job/instance uuids and
    resource grant; the group uuid appears only for grouped jobs."""

    def test_cook_env_vars_visible_to_task(self, agent, tmp_path):
        from cook_tpu.config import Config
        from cook_tpu.sched import Scheduler
        from cook_tpu.state import (Group, Job, Resources, Store, new_uuid)

        store = Store()
        cluster = RemoteComputeCluster(
            "remote-1", [("127.0.0.1", agent.port)], store=store)
        cfg = Config()
        cfg.default_matcher.backend = "cpu"
        sched = Scheduler(store, cfg, [cluster], rank_backend="cpu")
        out_plain = tmp_path / "plain.env"
        out_grp = tmp_path / "grouped.env"
        dump = ("env | grep ^COOK_ | sort > {out}")
        plain = Job(uuid=new_uuid(), user="alice",
                    command=dump.format(out=out_plain),
                    pool="default", resources=Resources(cpus=1.0, mem=128.0))
        guuid = new_uuid()
        grouped = Job(uuid=new_uuid(), user="alice", group=guuid,
                      command=dump.format(out=out_grp),
                      pool="default",
                      resources=Resources(cpus=2.0, mem=256.0))
        store.create_jobs([plain, grouped],
                          groups=[Group(uuid=guuid, name="g1")])
        try:
            sched.step_rank()
            assert len(sched.step_match()["default"].launched_task_ids) == 2

            def settled():
                sched.flush_status_updates()
                return all(store.job(u).state is JobState.COMPLETED
                           for u in (plain.uuid, grouped.uuid))
            assert wait_for(settled, timeout=15)

            def env_of(path):
                return dict(line.split("=", 1) for line in
                            path.read_text().strip().splitlines())
            e1 = env_of(out_plain)
            assert e1["COOK_JOB_UUID"] == plain.uuid
            assert e1["COOK_INSTANCE_UUID"] == \
                store.job(plain.uuid).instances[-1]
            # first attempt: zero PRIOR instances (mesos/task.clj counts
            # the pre-transaction snapshot)
            assert e1["COOK_INSTANCE_NUM"] == "0"
            assert e1["COOK_JOB_CPUS"] == "1.0"
            assert e1["COOK_JOB_MEM_MB"] == "128.0"
            assert "COOK_JOB_GROUP_UUID" not in e1  # ungrouped: no group var
            assert "COOK_JOB_GPUS" not in e1
            e2 = env_of(out_grp)
            assert e2["COOK_JOB_GROUP_UUID"] == guuid
            assert e2["COOK_JOB_CPUS"] == "2.0"
        finally:
            cluster.shutdown()


class TestDockerParameters:
    """Docker parameters compile to --key value container-runtime flags
    (reference: mesos/task.clj docker parameter passthrough; integration
    test_docker_env_param / test_docker_workdir), and the reference's
    NESTED container form ({"type": "docker", "docker": {...}}) launches
    with the right image after REST normalization."""

    def test_parameters_reach_runtime_argv(self, tmp_path):
        from cook_tpu.config import Config
        from cook_tpu.sched import Scheduler
        from cook_tpu.state import Job, Resources, Store, new_uuid

        record = tmp_path / "runtime-args.txt"
        fake_rt = tmp_path / "fake-docker"
        fake_rt.write_text(
            "#!/bin/sh\n"
            f'echo "$@" > {record}\n'
            'while [ "$1" != "/bin/sh" ] && [ $# -gt 0 ]; do shift; done\n'
            'exec "$@"\n')
        fake_rt.chmod(0o755)
        agent = LocalAgentProcess("nodeP", workdir=str(tmp_path / "w"),
                                  container_runtime=str(fake_rt))
        try:
            store = Store()
            cluster = RemoteComputeCluster(
                "remote-1", [("127.0.0.1", agent.port)], store=store)
            cfg = Config()
            cfg.default_matcher.backend = "cpu"
            sched = Scheduler(store, cfg, [cluster], rank_backend="cpu")
            job = Job(uuid=new_uuid(), user="alice", command="true",
                      container={"image": "busybox:1.36",
                                 "parameters": [
                                     {"key": "workdir", "value": "/tmp"},
                                     {"key": "env", "value": "FOO=bar"}]},
                      pool="default",
                      resources=Resources(cpus=1.0, mem=64.0))
            store.create_jobs([job])
            sched.step_rank(); sched.step_match()

            def done():
                sched.flush_status_updates()
                return store.job(job.uuid).state is JobState.COMPLETED
            assert wait_for(done, timeout=15)
            args = record.read_text()
            assert "--workdir /tmp" in args, args
            assert "--env FOO=bar" in args, args
            # parameters precede the image (docker flag ordering)
            assert args.index("--workdir") < args.index("busybox:1.36")
            cluster.shutdown()
        finally:
            agent.stop()

    def test_nested_container_form_over_rest(self, tmp_path, agent):
        from cook_tpu.config import Config
        from cook_tpu.rest import ApiServer, CookApi
        from cook_tpu.sched import Scheduler
        from cook_tpu.state import Store
        from cook_tpu.client import JobClient

        store = Store()
        cluster = RemoteComputeCluster(
            "remote-1", [("127.0.0.1", agent.port)], store=store)
        cfg = Config()
        cfg.default_matcher.backend = "cpu"
        sched = Scheduler(store, cfg, [cluster], rank_backend="cpu")
        srv = ApiServer(CookApi(store, scheduler=sched))
        srv.start()
        try:
            client = JobClient(srv.url, user="alice")
            uuid = client.submit([{
                "command": "true", "cpus": 1, "mem": 64,
                "container": {"type": "docker",
                              "docker": {"image": "busybox:nested",
                                         "parameters": [
                                             {"key": "workdir",
                                              "value": "/x"}]}}}])[0]
            job = store.job(uuid)
            # normalized flat fields alongside the preserved nested form
            assert job.container["image"] == "busybox:nested"
            assert job.container["parameters"] == [
                {"key": "workdir", "value": "/x"}]
            assert job.container["docker"]["image"] == "busybox:nested"
            # and the REST echo keeps what was submitted
            shown = client.job(uuid)
            assert shown["container"]["docker"]["image"] == "busybox:nested"
        finally:
            srv.stop()
            cluster.shutdown()
