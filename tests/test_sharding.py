"""Pool-sharded cycle tests on the virtual 8-device CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cook_tpu.ops import host_prep, reference_impl
from cook_tpu.ops.reference_impl import UserTasks
from cook_tpu.parallel import PoolCycleInputs, make_pool_cycle, pool_mesh

F32 = np.float32
INF = float("inf")


def build_pool(rng, T_bucket=64, H_bucket=16, n_users=4):
    """One random pool's arrays + golden rank/match results."""
    users, shares, quotas = [], {}, {}
    tid = 0
    for u in range(n_users):
        name = f"user{u:02d}"
        n = int(rng.integers(1, 8))
        rows = [(float(rng.integers(1, 4)), float(rng.integers(32, 512)),
                 0.0) for _ in range(n)]
        pend = [bool(rng.random() < 0.7) for _ in range(n)]
        users.append(UserTasks(name, list(range(tid, tid + n)),
                               np.array([[c, m, g, 1.0] for c, m, g in rows],
                                        dtype=F32), pend))
        tid += n
        shares[name] = (16.0, 4096.0, 1.0)
        quotas[name] = np.full(4, INF, dtype=F32)
    arrays, task_ids = host_prep.pack_rank_inputs(users, shares, quotas)
    # grow to the common bucket
    from cook_tpu.ops.padding import pad_to
    T = T_bucket
    for k, fill in (("usage", 0), ("quota", np.inf), ("shares", np.inf),
                    ("first_idx", 0), ("user_rank", 2**31 - 1),
                    ("pending", False), ("valid", False)):
        arrays[k] = pad_to(arrays[k], T, fill=fill)

    H = int(rng.integers(2, 6))
    capacity = np.stack([rng.integers(8, 32, H).astype(F32),
                         rng.integers(1024, 8192, H).astype(F32),
                         np.zeros(H, dtype=F32),
                         np.full(H, 1e6, dtype=F32)], axis=1)
    avail = capacity * 0.8
    job_res = np.concatenate(
        [arrays["usage"][:, :3], np.zeros((T, 1), dtype=F32)], axis=1)
    cmask = np.ones((T, H), dtype=bool)
    avail_p = pad_to(avail, H_bucket)
    cap_p = pad_to(capacity, H_bucket)
    cmask_p = np.zeros((T, H_bucket), dtype=bool)
    cmask_p[:, :H] = cmask

    # golden: rank then greedy match of pending survivors
    golden_rank = reference_impl.rank_by_dru(users, shares, quotas)
    ranked_ids = [t for t, _ in golden_rank]
    id_pos = {t: i for i, t in enumerate(task_ids)}
    g_res = np.array([job_res[id_pos[t]] for t in ranked_ids],
                     dtype=F32).reshape(-1, 4)
    g_cmask = np.ones((len(ranked_ids), H), dtype=bool)
    golden_assign = reference_impl.greedy_match(g_res, g_cmask, avail, capacity)

    return {
        "arrays": arrays, "task_ids": task_ids, "job_res": job_res,
        "cmask": cmask_p, "avail": avail_p, "capacity": cap_p,
        "golden_ranked_ids": ranked_ids, "golden_assign": golden_assign,
        "num_hosts": H,
    }


class TestPoolShardedCycle:
    def test_eight_pools_match_golden(self):
        assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
        mesh = pool_mesh()
        rng = np.random.default_rng(42)
        pools = [build_pool(rng) for _ in range(8)]

        stack = lambda key: jnp.asarray(np.stack(
            [p["arrays"][key] if key in p["arrays"] else p[key]
             for p in pools]))
        inp = PoolCycleInputs.build(
            usage=stack("usage"), quota=stack("quota"), shares=stack("shares"),
            first_idx=stack("first_idx"), user_rank=stack("user_rank"),
            pending=stack("pending"), valid=stack("valid"),
            job_res=jnp.asarray(np.stack([p["job_res"] for p in pools])),
            cmask=jnp.asarray(np.stack([p["cmask"] for p in pools])),
            avail=jnp.asarray(np.stack([p["avail"] for p in pools])),
            capacity=jnp.asarray(np.stack([p["capacity"] for p in pools])))
        cycle = make_pool_cycle(mesh)
        res = cycle(inp)

        total_matched_expected = 0
        for pi, pool in enumerate(pools):
            n = int(res.num_ranked[pi])
            order = np.asarray(res.order[pi])[:n]
            kernel_ids = [pool["task_ids"][i] for i in order]
            assert kernel_ids == pool["golden_ranked_ids"], f"pool {pi} rank"
            assign = np.asarray(res.assign[pi])[:n]
            np.testing.assert_array_equal(
                assign, pool["golden_assign"], err_msg=f"pool {pi} match")
            total_matched_expected += int((pool["golden_assign"] >= 0).sum())
        assert int(res.total_matched) == total_matched_expected
        # all_gather'd usage covers every pool on every device
        assert res.matched_usage.shape == (8, 4)

    def test_uneven_pools_and_empty_pool(self):
        mesh = pool_mesh()
        rng = np.random.default_rng(7)
        pools = [build_pool(rng, n_users=(0 if i == 3 else 3))
                 for i in range(8)]
        stack = lambda key: jnp.asarray(np.stack(
            [p["arrays"][key] for p in pools]))
        inp = PoolCycleInputs.build(
            usage=stack("usage"), quota=stack("quota"), shares=stack("shares"),
            first_idx=stack("first_idx"), user_rank=stack("user_rank"),
            pending=stack("pending"), valid=stack("valid"),
            job_res=jnp.asarray(np.stack([p["job_res"] for p in pools])),
            cmask=jnp.asarray(np.stack([p["cmask"] for p in pools])),
            avail=jnp.asarray(np.stack([p["avail"] for p in pools])),
            capacity=jnp.asarray(np.stack([p["capacity"] for p in pools])))
        cycle = make_pool_cycle(mesh)
        res = cycle(inp)
        assert int(res.num_ranked[3]) == 0
        assert bool(np.all(np.asarray(res.assign[3]) == -1))


class TestStructuredMask:
    def test_structured_equals_dense_on_8_pools(self):
        """The production structured-mask cycle (per-host vectors +
        exception rows composed on device) must produce bit-identical
        decisions to the dense bool[T, H] mask on a NON-TRIVIAL mask:
        random gpu hosts, gpu jobs, blocked hosts, and exception rows."""
        from cook_tpu.parallel.sharded import StructuredPoolCycleInputs
        mesh = pool_mesh()
        rng = np.random.default_rng(13)
        pools = [build_pool(rng) for _ in range(8)]
        T = pools[0]["arrays"]["pending"].shape[0]
        Hb = pools[0]["avail"].shape[0]
        E = 4

        host_gpu = np.zeros((8, Hb), dtype=bool)
        host_blocked = np.zeros((8, Hb), dtype=bool)
        exc_id = np.full((8, T), -1, dtype=np.int32)
        exc_mask = np.zeros((8, E, Hb), dtype=bool)
        dense = np.zeros((8, T, Hb), dtype=bool)
        job_res = np.stack([p["job_res"] for p in pools])
        for pi, pool in enumerate(pools):
            H = pool["num_hosts"]
            # random gpu hosts + gpu-demanding rows
            host_gpu[pi, :H] = rng.random(H) < 0.3
            gpu_rows = rng.random(T) < 0.2
            job_res[pi, gpu_rows, 2] = 1.0
            # padding hosts blocked, plus one random real block
            host_blocked[pi, H:] = True
            if H > 1:
                host_blocked[pi, int(rng.integers(0, H))] = True
            # a few exception rows with arbitrary masks
            rows = rng.choice(T, size=E, replace=False)
            exc_id[pi, rows] = np.arange(E, dtype=np.int32)
            exc_mask[pi, :, :H] = rng.random((E, H)) < 0.5
            # dense equivalent
            base = np.where(job_res[pi, :, 2:3] > 0, host_gpu[pi][None, :],
                            ~host_gpu[pi][None, :]) & ~host_blocked[pi][None, :]
            dense[pi] = base
            for k, r in enumerate(rows):
                dense[pi, r] = exc_mask[pi, k]

        stack = lambda key: jnp.asarray(np.stack(
            [p["arrays"][key] for p in pools]))
        common = dict(
            usage=stack("usage"), quota=stack("quota"), shares=stack("shares"),
            first_idx=stack("first_idx"), user_rank=stack("user_rank"),
            pending=stack("pending"), valid=stack("valid"),
            job_res=jnp.asarray(job_res))
        dense_inp = PoolCycleInputs.build(
            **common, cmask=jnp.asarray(dense),
            avail=jnp.asarray(np.stack([p["avail"] for p in pools])),
            capacity=jnp.asarray(np.stack([p["capacity"] for p in pools])))
        res_d = make_pool_cycle(mesh, considerable_cap=32)(dense_inp)

        sinp = StructuredPoolCycleInputs(
            **{k: dense_inp._asdict()[k]
               for k in StructuredPoolCycleInputs._fields
               if k in PoolCycleInputs._fields and k != "cmask"},
            host_gpu=jnp.asarray(host_gpu),
            host_blocked=jnp.asarray(host_blocked),
            exc_id=jnp.asarray(exc_id), exc_mask=jnp.asarray(exc_mask))
        res_s = make_pool_cycle(mesh, considerable_cap=32,
                                structured=True)(sinp)

        np.testing.assert_array_equal(np.asarray(res_d.order),
                                      np.asarray(res_s.order))
        np.testing.assert_array_equal(np.asarray(res_d.assign),
                                      np.asarray(res_s.assign))
        assert int(res_d.total_matched) == int(res_s.total_matched)
        assert int(res_d.total_matched) > 0, "trivial scenario"


class TestMultisliceMesh:
    def test_dcn_pool_mesh_matches_1d(self):
        """2-D ("dcn", "pool") mesh produces identical placements to the 1-D
        mesh — sharding must not change scheduling decisions."""
        from cook_tpu.parallel.mesh import multislice_pool_mesh

        rng = np.random.default_rng(5)
        pools = [build_pool(rng) for _ in range(8)]
        stack = lambda key: jnp.asarray(np.stack(
            [p["arrays"][key] if key in p["arrays"] else p[key]
             for p in pools]))
        inp = PoolCycleInputs.build(
            usage=stack("usage"), quota=stack("quota"), shares=stack("shares"),
            first_idx=stack("first_idx"), user_rank=stack("user_rank"),
            pending=stack("pending"), valid=stack("valid"),
            job_res=jnp.asarray(np.stack([p["job_res"] for p in pools])),
            cmask=jnp.asarray(np.stack([p["cmask"] for p in pools])),
            avail=jnp.asarray(np.stack([p["avail"] for p in pools])),
            capacity=jnp.asarray(np.stack([p["capacity"] for p in pools])))
        res1 = make_pool_cycle(pool_mesh())(inp)
        mesh2 = multislice_pool_mesh(2, 4)
        assert mesh2.axis_names == ("dcn", "pool")
        res2 = make_pool_cycle(mesh2)(inp)
        np.testing.assert_array_equal(np.asarray(res1.assign),
                                      np.asarray(res2.assign))
        np.testing.assert_array_equal(np.asarray(res1.order),
                                      np.asarray(res2.order))
        assert int(res1.total_matched) == int(res2.total_matched)
        np.testing.assert_allclose(np.asarray(res1.matched_usage),
                                   np.asarray(res2.matched_usage))
