"""Pipelined optimistic match cycles (sched/pipeline.py): depth-0
sync-path preservation, conflict-injection reconciliation (no double
launch, queue stays consistent), boot-warmup zero-recompile steady state,
and the deterministic pipelined-vs-sync parity harness — including the
chaos run with pipeline_depth=2 (zero duplicate live instances)."""

import numpy as np
import pytest

from cook_tpu.cluster import FakeCluster, FakeHost
from cook_tpu.config import Config, PipelineConfig
from cook_tpu.sched import Scheduler
from cook_tpu.state import (
    InstanceStatus,
    Job,
    JobState,
    Pool,
    Resources,
    Store,
)


def build_world(n_jobs=10, n_hosts=4, depth=2, host_cpus=16.0,
                warmup=False, seed=5):
    rng = np.random.default_rng(seed)
    cfg = Config()
    cfg.pipeline.depth = depth
    if warmup:
        cfg.pipeline.warmup_tasks = 64
        cfg.pipeline.warmup_hosts = 64
        cfg.pipeline.warmup_users = 8
    store = Store()
    store.put_pool(Pool(name="default"))
    hosts = [FakeHost(hostname=f"h{i}",
                      capacity=Resources(cpus=host_cpus, mem=16384.0))
             for i in range(n_hosts)]
    cluster = FakeCluster("fake-1", hosts)
    sched = Scheduler(store, cfg, [cluster], rank_backend="tpu")
    jobs = [Job(uuid=f"00000000-0000-0000-0000-{i:012d}",
                user=f"user{i % 3}", command="true", pool="default",
                priority=int(rng.integers(0, 100)),
                resources=Resources(cpus=1.0, mem=128.0),
                submit_time_ms=1000 + i)
            for i in range(n_jobs)]
    store.create_jobs(jobs)
    return store, sched, cluster, jobs


def live_counts(store):
    out = {}
    for job, _inst in store.running_instances():
        out[job.uuid] = out.get(job.uuid, 0) + 1
    return out


class TestConfig:
    def test_boot_validation(self):
        assert PipelineConfig.from_conf({"depth": 0}).depth == 0
        assert PipelineConfig.from_conf({}).depth == 2  # issue default
        with pytest.raises(ValueError, match="unknown pipeline key"):
            PipelineConfig.from_conf({"detph": 2})
        with pytest.raises(ValueError, match="depth"):
            PipelineConfig.from_conf({"depth": -1})
        with pytest.raises(ValueError, match="boolean"):
            PipelineConfig.from_conf({"warmup_sweep": "true"})

    def test_daemon_section_routes_through_from_conf(self):
        from cook_tpu.daemon import build_scheduler_config
        cfg = build_scheduler_config({"pipeline": {"depth": 0}})
        assert cfg.pipeline.depth == 0
        with pytest.raises(ValueError):
            build_scheduler_config({"pipeline": {"depht": 3}})


class TestDepthZeroSyncPath:
    def test_depth0_is_sync_driver(self):
        _store, sched, _c, _jobs = build_world(depth=0)
        sched.step_cycle()
        assert sched._pipeline is None  # the wrapper is never constructed

    def test_depth0_and_depth2_same_decisions(self):
        """One seeded world per driver; the launched set after draining
        the queue must be identical (depth 2's first step already applies
        its first cycle, so a single-step world matches too)."""

        def run(depth):
            store, sched, _c, jobs = build_world(depth=depth)
            sched.step_cycle()
            return store, {j.uuid: (store.job(j.uuid).state.value,
                                    tuple(sorted(
                                        store.instance(t).hostname
                                        for t in store.job(j.uuid).instances
                                        if store.instance(t) is not None)))
                           for j in jobs}

        _s0, dec0 = run(0)
        _s2, dec2 = run(2)
        assert dec0 == dec2


class TestReconciliation:
    def test_candidate_killed_between_pack_and_apply(self):
        """A job killed while it sits in an in-flight optimistic dispatch
        is dropped by reconciliation: no instance, no crash, conflict
        counted, and the published queue no longer contains it."""
        # capacity 1 task/host and more jobs than slots: step 1 launches
        # some jobs and leaves the rest as live candidates of the
        # in-flight speculative cycle
        store, sched, _c, jobs = build_world(
            n_jobs=8, n_hosts=3, depth=2, host_cpus=1.0)
        sched.step_cycle()
        launched_1 = {u for u, n in live_counts(store).items()}
        waiting = [j for j in jobs if j.uuid not in launched_1]
        assert waiting, "need an unlaunched candidate to kill"
        victim = waiting[0]
        store.kill_job(victim.uuid)
        # free the hosts so the speculative cycle's surviving candidates
        # can launch (completion also advances the store tx watermark)
        for tid in [i.task_id for _j, i in store.running_instances()]:
            store.update_instance_status(tid, InstanceStatus.SUCCESS)
        sched.step_cycle()
        job = store.job(victim.uuid)
        assert job.state is not JobState.RUNNING
        assert not job.instances, "killed candidate must never launch"
        drv = sched._pipeline
        assert drv is not None
        # queue stays consistent: the victim is not in the published queue
        q = sched.pending_queues.get("default", [])
        qu = set(q.uuids) if hasattr(q, "uuids") else {j.uuid for j in q}
        assert victim.uuid not in qu

    def test_candidate_launched_by_overlapped_actor_not_double_launched(
            self):
        """A candidate the store already launched (another actor raced the
        in-flight dispatch) is conflict-dropped: exactly one instance
        ever exists."""
        store, sched, cluster, jobs = build_world(
            n_jobs=8, n_hosts=3, depth=2, host_cpus=1.0)
        sched.step_cycle()
        launched_1 = set(live_counts(store))
        waiting = [j for j in jobs if j.uuid not in launched_1]
        assert waiting
        victim = waiting[0]
        # the "overlapped cycle": a direct store launch behind the
        # pipeline's back
        store.launch_instance(victim.uuid, "race-task-1", hostname="h0",
                              compute_cluster="fake-1")
        sched.step_cycle()
        sched.step_cycle()
        job = store.job(victim.uuid)
        assert job.instances == ["race-task-1"], \
            "overlap-launched candidate must not double launch"
        assert max(live_counts(store).values(), default=0) <= 1

    def test_launch_rate_budget_not_doubled_by_overlap(self):
        """The per-user launch-rate budget must hold across overlapped
        cycles: the speculative cycle is staged before the applied
        cycle's spend() lands, so its staged token budget carries the
        in-flight spends as a delta (same budget as the sync driver)."""
        from cook_tpu.policy import RateLimits
        from cook_tpu.policy.rate_limit import TokenBucketRateLimiter

        def run(depth):
            rl = RateLimits(job_launch=TokenBucketRateLimiter(
                tokens_per_minute=0.0, bucket_size=2.0))
            cfg = Config()
            cfg.pipeline.depth = depth
            store = Store()
            store.put_pool(Pool(name="default"))
            hosts = [FakeHost(hostname=f"h{i}",
                              capacity=Resources(cpus=16.0, mem=16384.0))
                     for i in range(4)]
            sched = Scheduler(store, cfg, [FakeCluster("fake-1", hosts)],
                              rank_backend="tpu", rate_limits=rl)
            jobs = [Job(uuid=f"00000000-0000-0000-0001-{i:012d}",
                        user="one-user", command="true", pool="default",
                        resources=Resources(cpus=1.0, mem=64.0),
                        submit_time_ms=1000 + i)
                    for i in range(6)]
            store.create_jobs(jobs)
            launched = 0
            for _ in range(3):
                for r in sched.step_cycle().values():
                    launched += len(r.launched_task_ids)
            return launched

        assert run(0) == 2
        assert run(2) == 2, "overlap must not hand the user extra tokens"

    def test_quiet_store_zero_conflict_drops(self):
        """On a quiet store (no writers besides the driver) the
        speculation mask makes back-to-back cycles disjoint: zero
        reconciliation drops across a full drain."""
        store, sched, _c, jobs = build_world(n_jobs=12, n_hosts=4, depth=2)
        for _ in range(4):
            sched.step_cycle()
        drv = sched._pipeline
        assert drv is not None
        assert drv.conflicts_state == 0
        assert drv.conflicts_resources == 0
        assert max(live_counts(store).values(), default=0) <= 1
        # everything schedulable launched exactly once
        for j in jobs:
            assert len(store.job(j.uuid).instances) == 1


class TestWarmup:
    def test_zero_recompiles_after_boot_warmup(self):
        """Boot warmup at the world's bucket grid: N steady-state cycles
        (including the very first) trace/compile nothing."""
        from cook_tpu.utils.flight import recorder
        store, sched, _c, _jobs = build_world(
            n_jobs=10, n_hosts=4, depth=2, warmup=True)
        seq0 = recorder.last_seq()
        for _ in range(3):
            sched.step_cycle()
        flight = recorder.summary(since_seq=seq0)
        assert flight.get("recompiles", {}) == {}, \
            f"steady-state recompiles after warmup: {flight['recompiles']}"

    def test_warmup_counts_executions(self):
        _store, sched, _c, _jobs = build_world(warmup=True)
        # __init__ already warmed; an explicit call re-executes (cached)
        assert sched.warmup_kernels() == 1
        sched.config.pipeline.warmup_sweep = True
        assert sched.warmup_kernels() >= 1


class TestObservability:
    def test_cycle_record_carries_pipeline_fields(self):
        from cook_tpu.utils.flight import recorder
        _store, sched, _c, _jobs = build_world(depth=2)
        seq0 = recorder.last_seq()
        sched.step_cycle()
        recs = [r for r in recorder.recent(10) if r["seq"] > seq0]
        assert recs
        doc = recs[-1]
        assert doc["pipeline_depth"] == 2
        assert "pipeline_inflight" in doc
        assert "pipeline_conflicts" in doc

    def test_pipeline_metrics_exposed(self):
        from cook_tpu.utils.metrics import registry
        _store, sched, _c, _jobs = build_world(depth=2)
        sched.step_cycle()
        text = registry.expose()
        assert "cook_pipeline_depth 2.0" in text

    def test_depth0_gauge_reads_zero(self):
        """A sync deployment must be distinguishable from a broken
        scrape: the depth gauge reads 0, it is not absent."""
        from cook_tpu.utils.metrics import registry
        _store, sched, _c, _jobs = build_world(depth=0)
        sched.step_cycle()
        assert "cook_pipeline_depth 0.0" in registry.expose()


class TestParityHarness:
    def test_seeded_parity_smoke(self):
        """Tier-1 smoke of the deterministic parity harness: same
        launched job set, all jobs complete, zero conflicts, no
        duplicate live instances."""
        from cook_tpu.sim.simulator import run_pipeline_parity
        result = run_pipeline_parity(seed=3, n_jobs=14, n_hosts=5,
                                     depth=2, span_ms=5000,
                                     duration_ms=1500)
        assert result["ok"], result
        assert result["pipelined_conflicts"] == 0
        assert result["duplicate_live"] == []

    @pytest.mark.slow
    def test_seeded_parity_full(self):
        from cook_tpu.sim.simulator import run_pipeline_parity
        for seed in (0, 1):
            result = run_pipeline_parity(seed=seed, n_jobs=60, n_hosts=10,
                                         depth=2)
            assert result["ok"], result


@pytest.mark.chaos
class TestPipelinedChaos:
    def test_chaos_no_duplicate_live_with_pipeline(self):
        """sim --chaos --pipeline-depth 2: the per-tick duplicate-live
        check holds under node loss + RPC faults + a leader kill landing
        inside the overlapped match->ack window."""
        from cook_tpu.sim.chaos import ChaosConfig, run_chaos
        cc = ChaosConfig(seed=7, n_jobs=14, n_hosts=6,
                         submit_span_ms=12_000, job_duration_ms=3_000,
                         node_loss_every_ms=6_000, node_loss_max=2,
                         rpc_fault_probability=0.1, rpc_fault_max=3,
                         leader_kill_at_ms=8_000, pipeline_depth=2)
        result = run_chaos(cc)
        assert result.ok, result.violations
        assert result.completed == result.total
