"""Crash-point recovery matrix wiring (sim/crashpoint.py; the
CrashMonkey/ALICE-style harness behind ``python -m cook_tpu.sim
--crashpoints``, docs/ROBUSTNESS.md "WAL v2").

Tier-1 smokes a reduced matrix — every leg runs, fault sites are
strided and intra-frame cuts reduced to boundaries — and asserts zero
violations plus the coverage floor (each leg actually produced cases).
The full matrix at default scale, including the peer-repair path over
real socket replication, soaks under ``-m slow``."""

import json
import subprocess
import sys

import pytest

from cook_tpu.sim.crashpoint import (
    DISK_FAULT_POINTS,
    build_ops,
    run_crashpoints,
)


class TestSmoke:
    def test_reduced_matrix_recovers_everywhere(self, tmp_path):
        res = run_crashpoints(n_jobs=2, stride=2, cuts_per_line=1,
                              use_replication=False,
                              workdir=str(tmp_path))
        assert res.ok, res.summary()
        # coverage floor: every leg ran real cases
        legs = res.summary()["legs"]
        n_ops = len(build_ops(2))
        assert legs["fault-site"] == len(DISK_FAULT_POINTS) * (
            (n_ops + 1) // 2)
        assert legs["byte-boundary"] > 0
        assert legs["corruption"] > 0
        assert legs["checkpoint"] >= 3

    def test_workload_script_is_deterministic(self):
        assert build_ops(3) == build_ops(3)


class TestCli:
    def test_sim_crashpoints_exit_zero_and_summary(self):
        proc = subprocess.run(
            [sys.executable, "-m", "cook_tpu.sim", "--crashpoints",
             "--jobs", "2", "--crashpoint-stride", "3"],
            capture_output=True, text=True, timeout=300,
            env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 0, proc.stdout + proc.stderr
        summary = json.loads(proc.stdout)
        assert summary["ok"] and summary["violations"] == []


@pytest.mark.slow
class TestSoak:
    def test_full_matrix_with_peer_repair(self, tmp_path):
        res = run_crashpoints(n_jobs=5, stride=1, cuts_per_line=3,
                              use_replication=True,
                              workdir=str(tmp_path))
        assert res.ok, res.summary()
