"""Constraint-compiler unit tests: balanced / attribute-equals group
placement and the estimated-completion constraint (reference:
constraints.clj:385-432, 600-676)."""

import numpy as np

from cook_tpu.cluster.base import Offer
from cook_tpu.sched.constraints import (
    ConstraintContext,
    build_constraint_mask,
    validate_group_placement,
)
from cook_tpu.state.schema import (
    Group,
    GroupPlacementType,
    Job,
    Resources,
    new_uuid,
)


def mk_offer(i, **attrs):
    return Offer(id=f"o{i}", hostname=f"h{i}", slave_id=f"s{i}",
                 pool="default", available=Resources(cpus=8, mem=8192),
                 capacity=Resources(cpus=8, mem=8192),
                 attributes={k.replace("_", "-"): v for k, v in attrs.items()})


def mk_job(group=None, **kw):
    return Job(uuid=new_uuid(), user="u", command="true", pool="default",
               resources=Resources(cpus=1, mem=100), group=group, **kw)


class TestBalanced:
    def _group(self, jobs, minimum=2):
        g = Group(uuid=new_uuid(), placement_type=GroupPlacementType.BALANCED,
                  placement_attribute="rack", placement_minimum=minimum,
                  jobs=[j.uuid for j in jobs])
        for j in jobs:
            j.group = g.uuid
        return g

    def test_mask_blocks_overloaded_attribute_value(self):
        # racks a,a,b running -> a has 2, b has 1: placing on a (freq 2 ==
        # max) is blocked, b (freq 1 < max) and fresh rack c are fine
        offers = [mk_offer(0, rack="a"), mk_offer(1, rack="b"),
                  mk_offer(2, rack="c")]
        job = mk_job()
        g = self._group([job])
        ctx = ConstraintContext(
            groups={g.uuid: g},
            group_running_hosts={g.uuid: {"r0", "r1", "r2"}},
            host_attributes={"r0": {"rack": "a"}, "r1": {"rack": "a"},
                             "r2": {"rack": "b"}})
        mask = build_constraint_mask([job], offers, ctx)
        assert mask.tolist() == [[False, True, True]]

    def test_mask_even_spread_allows_any(self):
        offers = [mk_offer(0, rack="a"), mk_offer(1, rack="b")]
        job = mk_job()
        g = self._group([job])
        ctx = ConstraintContext(
            groups={g.uuid: g},
            group_running_hosts={g.uuid: {"r0", "r1"}},
            host_attributes={"r0": {"rack": "a"}, "r1": {"rack": "b"}})
        mask = build_constraint_mask([job], offers, ctx)
        assert mask.all()

    def test_minimum_spread_forces_new_values(self):
        # one rack used, minimum=3 distinct -> minim forced to 0, so the
        # used rack (freq == max) is blocked until more racks are used
        offers = [mk_offer(0, rack="a"), mk_offer(1, rack="b")]
        job = mk_job()
        g = self._group([job], minimum=3)
        ctx = ConstraintContext(
            groups={g.uuid: g},
            group_running_hosts={g.uuid: {"r0"}},
            host_attributes={"r0": {"rack": "a"}})
        mask = build_constraint_mask([job], offers, ctx)
        assert mask.tolist() == [[False, True]]

    def test_within_batch_validation_spreads(self):
        # 4 cotasks, 2 racks with 2 hosts each; greedy might pile onto one
        # rack — the validator must keep the spread balanced (skew <= 1)
        offers = [mk_offer(0, rack="a"), mk_offer(1, rack="a"),
                  mk_offer(2, rack="b"), mk_offer(3, rack="b")]
        jobs = [mk_job() for _ in range(4)]
        g = self._group(jobs)
        ctx = ConstraintContext(groups={g.uuid: g})
        # all four land on rack a hosts 0,1 then rack b 2: a=2 before b has 1
        assign = np.array([0, 1, 2, 3])
        out = validate_group_placement(jobs, assign, offers, ctx)
        # job0 -> a(1); job1 -> a would make a=2 while b=0 -> blocked;
        # job2 -> b(1); job3 -> b=2 while a=1 -> allowed? freqs {a:1,b:1}
        # -> minim==maxim -> allowed
        assert out.tolist() == [0, -1, 2, 3]


class TestAttributeEqualsFromRunning:
    def test_allowed_values_derived_from_running_cotasks(self):
        offers = [mk_offer(0, zone="z1"), mk_offer(1, zone="z2")]
        job = mk_job()
        g = Group(uuid=new_uuid(),
                  placement_type=GroupPlacementType.ATTRIBUTE_EQUALS,
                  placement_attribute="zone", jobs=[job.uuid])
        job.group = g.uuid
        ctx = ConstraintContext(
            groups={g.uuid: g},
            group_running_hosts={g.uuid: {"r0"}},
            host_attributes={"r0": {"zone": "z2"}})
        mask = build_constraint_mask([job], offers, ctx)
        assert mask.tolist() == [[False, True]]


class TestEstimatedCompletion:
    def test_blocks_dying_hosts_only(self):
        # host 0 started 50 min ago with 60-min lifetime -> dies in 10 min;
        # host 1 is fresh; host 2 has no start-time attr -> always ok
        import time
        now_s = time.time()
        offers = [mk_offer(0, host_start_time=str(now_s - 50 * 60)),
                  mk_offer(1, host_start_time=str(now_s)),
                  mk_offer(2)]
        job = mk_job()
        ctx = ConstraintContext(
            host_lifetime_mins=60,
            estimated_end_ms={job.uuid: int((now_s + 30 * 60) * 1000)})
        mask = build_constraint_mask([job], offers, ctx)
        assert mask.tolist() == [[False, True, True]]

    def test_job_without_estimate_unconstrained(self):
        import time
        now_s = time.time()
        offers = [mk_offer(0, host_start_time=str(now_s - 59 * 60))]
        job = mk_job()
        ctx = ConstraintContext(host_lifetime_mins=60)
        mask = build_constraint_mask([job], offers, ctx)
        assert mask.all()


class TestMatcherEstimatedCompletionWiring:
    def test_expected_runtime_blocks_old_hosts_e2e(self):
        """A job with a long expected runtime only matches young hosts when
        estimated-completion is configured."""
        import time

        from cook_tpu.cluster import FakeCluster, FakeHost
        from cook_tpu.config import Config
        from cook_tpu.sched import Scheduler
        from cook_tpu.state import Store

        now_s = time.time()
        old = FakeHost(hostname="old", capacity=Resources(cpus=8, mem=8192),
                       attributes={"host-start-time": str(now_s - 50 * 60)})
        young = FakeHost(hostname="young",
                         capacity=Resources(cpus=8, mem=8192),
                         attributes={"host-start-time": str(now_s)})
        cluster = FakeCluster("fake-1", [old, young])
        config = Config()
        config.default_matcher.backend = "cpu"
        config.estimated_completion.expected_runtime_multiplier = 1.0
        config.estimated_completion.host_lifetime_mins = 60
        store = Store()
        sched = Scheduler(store, config, [cluster])
        job = mk_job(expected_runtime_ms=30 * 60 * 1000)
        store.create_jobs([job])
        sched.step_rank()
        res = sched.step_match()["default"]
        assert len(res.matched) == 1
        assert res.matched[0][1].hostname == "young"


class TestCotaskHostAttributeFill:
    def test_attrs_resolved_for_offerless_cotask_hosts(self):
        """A cotask running on a host absent from the offer set still pins
        its attribute-equals group: the matcher resolves that host's
        attributes from cluster.hosts()."""
        from cook_tpu.config import Config
        from cook_tpu.sched.matcher import Matcher
        from cook_tpu.state import Store

        class StubCluster:
            def hosts(self, pool):
                return [mk_offer(9, zone="z1")]  # hostname h9, the full host

        job = mk_job()
        g = Group(uuid=new_uuid(),
                  placement_type=GroupPlacementType.ATTRIBUTE_EQUALS,
                  placement_attribute="zone", jobs=[job.uuid])
        job.group = g.uuid
        ctx = ConstraintContext(groups={g.uuid: g},
                                group_running_hosts={g.uuid: ["h9"]})
        offers = [mk_offer(0, zone="z1"), mk_offer(1, zone="z2")]
        matcher = Matcher.__new__(Matcher)  # only needs the fill helper
        matcher._fill_cotask_host_attributes(
            ctx, "default", offers, {"c": StubCluster()})
        assert ctx.host_attributes["h9"]["zone"] == "z1"
        mask = build_constraint_mask([job], offers, ctx)
        assert mask.tolist() == [[True, False]]
