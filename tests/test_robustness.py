"""Robustness layer: fault injection (utils/faults.py), retry/backoff +
circuit breakers (utils/retry.py), crash-consistent launch intents, the
degraded kernel/fused fallbacks, and the NODE_LOST reaper's grace re-arm
across leader restart (docs/ROBUSTNESS.md)."""

import json
import random

import pytest

from cook_tpu.cluster.fake import FakeCluster, FakeHost
from cook_tpu.config import Config
from cook_tpu.daemon import build_scheduler_config
from cook_tpu.rest.api import CookApi
from cook_tpu.sched.scheduler import Scheduler
from cook_tpu.state.schema import (
    InstanceStatus,
    Job,
    JobState,
    Reasons,
    Resources,
    new_uuid,
)
from cook_tpu.state.store import Store
from cook_tpu.utils.faults import FaultInjected, FaultInjector, injector
from cook_tpu.utils.metrics import registry
from cook_tpu.utils.retry import (
    Backoff,
    CircuitBreaker,
    RetryPolicy,
    breakers,
    retry_call,
)


@pytest.fixture(autouse=True)
def _clean_global_planes():
    """The injector and breaker registry are process-global (like the
    metrics registry); every test starts and ends disarmed."""
    injector.clear()
    breakers.reset()
    yield
    injector.clear()
    breakers.reset()


def make_job(user="alice", pool="default", cpus=1.0, mem=100.0,
             max_retries=1, **kw) -> Job:
    return Job(uuid=new_uuid(), user=user, command="echo hi", pool=pool,
               resources=Resources(cpus=cpus, mem=mem),
               max_retries=max_retries, **kw)


def cpu_config() -> Config:
    cfg = Config()
    cfg.cycle_mode = "split"
    cfg.default_matcher.backend = "cpu"
    cfg.columnar_index = False
    return cfg


def make_cluster(name="c1", n_hosts=1, cpus=8.0, mem=8192.0):
    return FakeCluster(name, [
        FakeHost(hostname=f"{name}-h{i}",
                 capacity=Resources(cpus=cpus, mem=mem))
        for i in range(n_hosts)])


# --------------------------------------------------------------- injector
class TestFaultInjector:
    def test_disarmed_point_never_fires(self):
        fi = FaultInjector(seed=1)
        assert not fi.should_fire("store.journal.append")
        fi.fire("store.journal.append")  # no raise

    def test_schedule_fires_exact_call_indices(self):
        fi = FaultInjector()
        fi.arm("p", schedule=[0, 2])
        assert [fi.should_fire("p") for _ in range(4)] == \
            [True, False, True, False]

    def test_seeded_probability_replays(self):
        a = FaultInjector(seed=42)
        b = FaultInjector(seed=42)
        a.arm("p", probability=0.5)
        b.arm("p", probability=0.5)
        seq_a = [a.should_fire("p") for _ in range(32)]
        seq_b = [b.should_fire("p") for _ in range(32)]
        assert seq_a == seq_b and any(seq_a) and not all(seq_a)

    def test_max_fires_caps_triggers(self):
        fi = FaultInjector()
        fi.arm("p", probability=1.0, max_fires=2)
        assert sum(fi.should_fire("p") for _ in range(10)) == 2

    def test_fire_raises_and_counts(self):
        injector.arm("p", schedule=[0])
        before = registry.snapshot()["counters"].get(
            'cook_faults_injected{point="p"}', 0.0)
        with pytest.raises(FaultInjected):
            injector.fire("p")
        after = registry.snapshot()["counters"][
            'cook_faults_injected{point="p"}']
        assert after == before + 1
        # Prometheus exposition carries the conventional _total suffix
        assert 'cook_faults_injected_total{point="p"}' in registry.expose()
        doc = injector.active()["p"]
        assert doc["fires"] == 1 and doc["calls"] == 1

    def test_configure_from_config_document(self):
        fi = FaultInjector()
        fi.configure({"seed": 9, "points": {
            "remote.rpc": {"probability": 0.25},
            "store.journal.append": {"schedule": [3], "max_fires": 1}}})
        assert fi.seed == 9
        active = fi.active()
        assert active["remote.rpc"]["probability"] == 0.25
        assert active["store.journal.append"]["schedule"] == [3]


# ----------------------------------------------------------- retry/backoff
class TestBackoffAndRetry:
    def test_full_jitter_bounds_and_growth(self):
        bo = Backoff(base_s=0.1, cap_s=5.0, rng=random.Random(7))
        for attempt in range(12):
            d = bo.next_delay()
            assert 0.0 <= d <= min(5.0, 0.1 * 2 ** attempt)
        bo.reset()
        assert bo.next_delay() <= 0.1

    def test_jitter_desynchronizes_two_reconnectors(self):
        a = Backoff(base_s=1.0, cap_s=60.0, rng=random.Random(1))
        b = Backoff(base_s=1.0, cap_s=60.0, rng=random.Random(2))
        assert [a.next_delay() for _ in range(5)] != \
            [b.next_delay() for _ in range(5)]

    def test_retry_call_retries_then_succeeds(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise ConnectionError("nope")
            return "ok"

        slept = []
        assert retry_call(flaky, policy=RetryPolicy(max_attempts=5),
                          retry_on=(ConnectionError,),
                          sleep=slept.append,
                          rng=random.Random(0)) == "ok"
        assert len(calls) == 3 and len(slept) == 2

    def test_retry_call_exhausts_and_raises(self):
        def always():
            raise ConnectionError("down")

        with pytest.raises(ConnectionError):
            retry_call(always, policy=RetryPolicy(max_attempts=3),
                       retry_on=(ConnectionError,), sleep=lambda _s: None)


# --------------------------------------------------------- circuit breaker
class TestCircuitBreaker:
    def test_trips_after_threshold_heals_via_half_open(self):
        t = [0.0]
        b = CircuitBreaker("c", failure_threshold=3, reset_timeout_s=30.0,
                           clock=lambda: t[0])
        for _ in range(2):
            b.record_failure()
        assert b.state == "closed" and b.allow()
        b.record_failure()
        assert b.state == "open" and not b.allow()
        t[0] = 31.0
        assert b.state == "half-open" and b.allow()
        b.record_success()
        assert b.state == "closed"

    def test_half_open_probe_failure_reopens(self):
        t = [0.0]
        b = CircuitBreaker("c", failure_threshold=1, reset_timeout_s=10.0,
                           clock=lambda: t[0])
        b.record_failure()
        t[0] = 11.0
        assert b.allow()          # the probe
        b.record_failure()        # probe failed
        assert b.state == "open"
        t[0] = 20.0               # heal timer restarted at t=11
        assert b.state == "open"
        t[0] = 21.5
        assert b.state == "half-open"

    def test_success_resets_consecutive_count(self):
        b = CircuitBreaker("c", failure_threshold=3)
        b.record_failure()
        b.record_failure()
        b.record_success()
        b.record_failure()
        b.record_failure()
        assert b.state == "closed"

    def test_state_gauge_exported(self):
        breakers.get("gauge-cluster").trip()
        assert 'cook_circuit_breaker_state{cluster="gauge-cluster"} 2.0' \
            in registry.expose()

    def test_registry_configure_applies_to_existing(self):
        b = breakers.get("x")
        breakers.configure(failure_threshold=1)
        b.record_failure()
        assert b.state == "open"


# ---------------------------------------------------- breaker-aware routing
class TestBreakerRouting:
    def test_tripped_cluster_rerouted_to_healthy(self):
        store = Store()
        c1, c2 = make_cluster("c1", n_hosts=2), make_cluster("c2", n_hosts=2)
        sched = Scheduler(store, cpu_config(), [c1, c2],
                          rank_backend="cpu")
        breakers.get("c1").trip()
        store.create_jobs([make_job() for _ in range(4)])
        sched.step_rank()
        results = sched.step_match()
        launched = results["default"].launched_task_ids
        assert launched, "healthy cluster should still take the launches"
        for tid in launched:
            assert store.instance(tid).compute_cluster == "c2"
        # breaker healed -> c1 serves offers again
        breakers.get("c1").reset()
        assert {c.name for c in sched.launchable_clusters("default")} == \
            {"c1", "c2"}

    def test_consecutive_backend_rejects_trip_breaker(self):
        store = Store()
        cluster = make_cluster("flaky")
        cfg = cpu_config()
        cfg.circuit_breaker.failure_threshold = 3
        sched = Scheduler(store, cfg, [cluster], rank_backend="cpu")
        injector.arm("cluster.launch", probability=1.0)
        store.create_jobs([make_job() for _ in range(3)])
        sched.step_rank()
        sched.step_match()
        assert breakers.get("flaky").state == "open"
        # next cycle routes around the tripped cluster entirely
        assert sched.launchable_clusters("default") == []

    def test_direct_pool_backlog_visible_when_all_breakers_open(self):
        """A direct (Kenzo) pool with every backend's breaker open must
        still report the real demand — a capacity-of-zero truncation
        would show considered=0/unmatched=0 and hide the whole backlog
        for the outage."""
        from cook_tpu.state.schema import Pool, SchedulerKind
        store = Store()
        store.put_pool(Pool(name="default",
                            scheduler=SchedulerKind.DIRECT))
        cluster = make_cluster("c1")
        sched = Scheduler(store, cpu_config(), [cluster],
                          rank_backend="cpu")
        store.create_jobs([make_job() for _ in range(3)])
        breakers.get("c1").trip()
        sched.step_rank()
        res = sched.step_match()["default"]
        assert res.considered == 3
        assert len(res.unmatched) == 3
        assert res.launched_task_ids == []

    def test_debug_faults_surface(self):
        store = Store()
        cluster = make_cluster("c1")
        sched = Scheduler(store, cpu_config(), [cluster],
                          rank_backend="cpu")
        breakers.get("c1").trip()
        injector.arm("remote.rpc", probability=0.5)
        api = CookApi(store, scheduler=sched)
        doc = api.debug_faults()
        assert doc["breakers"]["c1"]["state"] == "open"
        assert doc["fault_points"]["remote.rpc"]["probability"] == 0.5
        assert doc["launch_intents"] == []


# ------------------------------------------------------------ launch intents
class TestLaunchIntents:
    def test_intent_written_with_instance_and_cleared_by_status(self):
        store = Store()
        [uuid] = store.create_jobs([make_job()])
        store.launch_instance(uuid, "t1", hostname="h",
                              compute_cluster="c1")
        [intent] = store.launch_intents()
        assert intent["task_id"] == "t1" and \
            intent["compute_cluster"] == "c1"
        store.update_instance_status("t1", InstanceStatus.RUNNING)
        assert store.launch_intents() == []

    def test_explicit_clear_is_idempotent(self):
        store = Store()
        [uuid] = store.create_jobs([make_job()])
        store.launch_instance(uuid, "t1", hostname="h")
        assert store.clear_launch_intents(["t1"]) == 1
        assert store.clear_launch_intents(["t1", "missing"]) == 0

    def test_crash_between_match_and_ack_relaunches_exactly_once(
            self, tmp_path, monkeypatch):
        """The acceptance scenario: kill the scheduler between the match
        transaction and the backend launch-ack, restart, and the task is
        exactly-once relaunched — never duplicated, never lost, and the
        refund is mea-culpa (zero user retries consumed)."""
        d = str(tmp_path / "state")
        store = Store.open(d)
        cluster = make_cluster("c1")
        cfg = cpu_config()
        sched = Scheduler(store, cfg, [cluster], rank_backend="cpu")
        [uuid] = store.create_jobs([make_job(max_retries=1)])

        def crash(pool, specs):
            raise RuntimeError("simulated process death mid-dispatch")

        monkeypatch.setattr(cluster, "launch_tasks", crash)
        sched.step_rank()
        with pytest.raises(RuntimeError):
            sched.step_match()
        monkeypatch.undo()
        # the guard transaction committed: instance + intent journaled
        assert len(store.launch_intents()) == 1
        tid1 = store.job(uuid).instances[0]
        store.close()

        # leader restart: replay journal, sweep intents in the constructor
        store2 = Store.open(d)
        sched2 = Scheduler(store2, cfg, [cluster], rank_backend="cpu")
        assert store2.launch_intents() == []
        inst1 = store2.instance(tid1)
        assert inst1.status is InstanceStatus.FAILED
        assert inst1.reason_code == Reasons.CANCELLED_DURING_LAUNCH.code
        job = store2.job(uuid)
        assert job.state is JobState.WAITING

        # exactly-once relaunch on the next cycle
        sched2.step_rank()
        results = sched2.step_match()
        assert len(results["default"].launched_task_ids) == 1
        job = store2.job(uuid)
        assert job.state is JobState.RUNNING
        assert len(job.instances) == 2
        insts = {t: store2.instance(t) for t in job.instances}
        live = [i for i in insts.values()
                if i.status in (InstanceStatus.UNKNOWN,
                                InstanceStatus.RUNNING)]
        assert len(live) == 1
        # mea-culpa refund: the crash consumed zero user retries
        assert job.attempts_used(insts) == 0
        store2.close()

    def test_sweep_adopts_task_the_cluster_knows(self, monkeypatch):
        store = Store()
        cluster = make_cluster("c1")
        [uuid] = store.create_jobs([make_job()])
        store.launch_instance(uuid, "t1", hostname="c1-h0",
                              compute_cluster="c1")
        monkeypatch.setattr(cluster, "running_task_ids", lambda: ["t1"])
        Scheduler(store, cpu_config(), [cluster], rank_backend="cpu")
        # adopted: intent dropped, instance NOT failed
        assert store.launch_intents() == []
        assert store.instance("t1").status is InstanceStatus.UNKNOWN

    def test_sweep_defers_when_enumeration_incomplete(self, monkeypatch):
        """running_task_ids() -> None means the backend cannot
        positively enumerate (an agent unreachable at startup): absence
        proves nothing, so the sweep must NOT refund — the task may be
        running on the unreachable agent (refunding would double-run)."""
        store = Store()
        cluster = make_cluster("c1")
        [uuid] = store.create_jobs([make_job()])
        store.launch_instance(uuid, "t1", hostname="c1-h0",
                              compute_cluster="c1")
        monkeypatch.setattr(cluster, "running_task_ids", lambda: None)
        Scheduler(store, cpu_config(), [cluster], rank_backend="cpu")
        assert store.launch_intents() == []
        assert store.instance("t1").status is InstanceStatus.UNKNOWN

    def test_sweep_refunds_when_cluster_is_gone(self):
        store = Store()
        [uuid] = store.create_jobs([make_job()])
        store.launch_instance(uuid, "t1", hostname="h",
                              compute_cluster="vanished")
        Scheduler(store, cpu_config(), [], rank_backend="cpu")
        inst = store.instance("t1")
        assert inst.status is InstanceStatus.FAILED
        assert inst.reason_code == Reasons.CANCELLED_DURING_LAUNCH.code
        assert store.launch_intents() == []

    def test_intents_survive_snapshot_restore(self):
        store = Store()
        [uuid] = store.create_jobs([make_job()])
        store.launch_instance(uuid, "t1", hostname="h",
                              compute_cluster="c1")
        restored = Store.restore(store.snapshot())
        [intent] = restored.launch_intents()
        assert intent["task_id"] == "t1"


# --------------------------------------------------- store fault injection
class TestStoreFaults:
    def test_journal_append_fault_aborts_txn_and_recovers(self, tmp_path):
        d = str(tmp_path / "state")
        store = Store.open(d)
        [u1] = store.create_jobs([make_job()])
        injector.arm("store.journal.append", schedule=[0])
        with pytest.raises(OSError):
            store.create_jobs([make_job()])
        # the failed append was excised; the store keeps accepting writes
        [u3] = store.create_jobs([make_job()])
        store.close()
        reopened = Store.open(d)
        assert reopened.job(u1) is not None
        assert reopened.job(u3) is not None
        assert len(reopened.jobs_where(lambda j: True)) == 2

    def test_fsync_fault_aborts_when_fsync_enabled(self, tmp_path):
        d = str(tmp_path / "state")
        store = Store.open(d, fsync=True)
        injector.arm("store.journal.fsync", schedule=[0])
        with pytest.raises(OSError):
            store.create_jobs([make_job()])
        [u] = store.create_jobs([make_job()])
        assert Store.replay_only(d).job(u) is not None


# -------------------------------------------------- degraded kernel paths
class TestKernelFallback:
    def test_kernel_dispatch_fault_falls_back_to_host_greedy(self):
        from cook_tpu.config import MatcherConfig
        from cook_tpu.sched.matcher import Matcher
        injector.arm("kernel.dispatch", probability=1.0)
        m = Matcher(Store(), Config())
        mc = MatcherConfig(backend="tpu-greedy")
        assign = m._dispatch(mc, [[1.0, 100.0, 0.0, 0.0]], [[True]],
                             [[8.0, 8192.0, 0.0, 0.0]],
                             [[8.0, 8192.0, 0.0, 0.0]])
        assert int(assign[0]) == 0
        counters = registry.snapshot()["counters"]
        assert counters.get(
            'cook_kernel_fallback{kernel="match"}', 0) >= 1

    def test_fused_dispatch_fault_degrades_to_split_cycle(self):
        store = Store()
        cluster = make_cluster("c1", n_hosts=2)
        cfg = Config()  # fused production mode, device kernels
        sched = Scheduler(store, cfg, [cluster], rank_backend="tpu")
        store.create_jobs([make_job() for _ in range(3)])
        injector.arm("fused.dispatch", probability=1.0)
        results = sched.step_cycle()
        assert results["default"].launched_task_ids, \
            "degraded cycle must still schedule via the host path"
        from cook_tpu.utils.flight import recorder
        rec = recorder.recent(limit=1)[0]
        assert rec["faults"].get("fused.dispatch-fallback") == 1


# ------------------------------------------- NODE_LOST reaper grace re-arm
class TestOrphanReaperAcrossRestart:
    def _store_with_running_orphan(self):
        store = Store()
        [uuid] = store.create_jobs([make_job()])
        store.launch_instance(uuid, "t1", hostname="h",
                              compute_cluster="gone-cluster")
        # RUNNING (confirms dispatch, clears the intent): the orphan
        # reaper, not the intent sweep, owns this case
        store.update_instance_status("t1", InstanceStatus.RUNNING)
        return store

    def test_grace_window_respected_then_reaped(self):
        store = self._store_with_running_orphan()
        cfg = cpu_config()
        cfg.orphaned_cluster_grace_seconds = 30.0
        t0 = store.clock()
        sched = Scheduler(store, cfg, [], rank_backend="cpu")
        assert sched.step_reapers(current_ms=t0) == []
        assert sched.step_reapers(current_ms=t0 + 29_000) == []
        assert sched.step_reapers(current_ms=t0 + 31_000) == ["t1"]
        inst = store.instance("t1")
        assert inst.reason_code == Reasons.NODE_LOST.code

    def test_new_leader_rearms_grace_instead_of_instant_reap(self):
        """The first-seen map is in-memory; a new leader must NOT treat
        'first time I see this orphan' as 'orphaned since forever'."""
        store = self._store_with_running_orphan()
        cfg = cpu_config()
        cfg.orphaned_cluster_grace_seconds = 30.0
        t0 = store.clock()
        old_leader = Scheduler(store, cfg, [], rank_backend="cpu")
        assert old_leader.step_reapers(current_ms=t0) == []
        # leader dies at t0+20s; successor starts mid-grace
        new_leader = Scheduler(store, cfg, [], rank_backend="cpu")
        # WELL past the original grace deadline: a leader that inherited
        # (or guessed) the old first-seen stamp would reap instantly
        assert new_leader.step_reapers(current_ms=t0 + 45_000) == []
        # the fresh grace window runs from the new leader's first sweep
        assert new_leader.step_reapers(
            current_ms=t0 + 45_000 + 29_000) == []
        assert new_leader.step_reapers(
            current_ms=t0 + 45_000 + 31_000) == ["t1"]


# ------------------------------------------------------------ config plumbing
class TestConfigPlumbing:
    def test_daemon_faults_section(self):
        cfg = build_scheduler_config({
            "faults": {"seed": 5, "points": {
                "remote.rpc": {"probability": 0.1}}},
            "circuit_breaker": {"failure_threshold": 2,
                                "reset_timeout_s": 7.5}})
        assert cfg.faults.enabled  # points configured => armed
        assert cfg.faults.seed == 5
        assert cfg.circuit_breaker.failure_threshold == 2
        assert cfg.circuit_breaker.reset_timeout_s == 7.5

    def test_daemon_rejects_typoed_fault_key(self):
        with pytest.raises(ValueError):
            build_scheduler_config({"faults": {"probabilty": 1}})

    def test_scheduler_applies_armed_config(self):
        cfg = cpu_config()
        cfg.faults.enabled = True
        cfg.faults.seed = 11
        cfg.faults.points = {"agent.heartbeat": {"probability": 1.0}}
        sched = Scheduler(Store(), cfg, [], rank_backend="cpu")
        assert injector.active()["agent.heartbeat"]["probability"] == 1.0
        # the armed point actually drops heartbeat delivery
        sched.heartbeats.watch("t1", 0)
        sched.heartbeat("t1")
        assert sched.heartbeats.last_beat("t1") == 0

    def test_cli_debug_faults_json(self, capsys):
        """`cs debug faults` shape (client stubbed; the HTTP round trip
        is covered by the REST surface tests)."""
        import importlib
        cli_main = importlib.import_module("cook_tpu.cli.main")

        class FakeClient:
            def debug_faults(self):
                return {"fault_points": {}, "breakers": {},
                        "launch_intents": []}

        class Args:
            debug_cmd = "faults"
            url = user = None

        old = cli_main.clients
        cli_main.clients = lambda args: [FakeClient()]
        try:
            assert cli_main.cmd_debug(Args()) == 0
        finally:
            cli_main.clients = old
        assert json.loads(capsys.readouterr().out)["launch_intents"] == []
