"""Serving-plane scale-out: the follower read fleet (live journal-applied
read replicas with the bounded-staleness / read-your-writes contract) and
the leader's group-commit admission batching.

Layered like test_failover.py:

- group commit at the store layer (stub replication, no native lib);
- the FollowerReadView apply loop over plain directories (no sockets);
- the REST serving contract (staleness headers, min-offset waits and
  redirects, fenced-token refusal) over stub wiring;
- end-to-end over REAL socket replication behind the native marker.
"""

import json
import os
import threading
import time
import urllib.request

import pytest

from cook_tpu.state import replication as repl
from cook_tpu.state.read_replica import FollowerReadView
from cook_tpu.state.schema import Job, Resources
from cook_tpu.state.store import (
    ReplicationIndeterminate,
    ReplicationTimeout,
    Store,
)


def make_job(i, user="alice"):
    return Job(uuid=f"00000000-0000-0000-0000-{i:012d}", user=user,
               command=f"echo {i}", resources=Resources(cpus=1, mem=64))


def wait_for(pred, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.005)
    return bool(pred())


class _StubRepl:
    """attach_replication target with scriptable acks (test_failover)."""

    def __init__(self, acks=(), synced=1):
        self.acks = list(acks)
        self.synced = synced
        self.directory = ""
        self.port = 0
        self.pokes = 0

    def poke(self):
        self.pokes += 1

    def wait_acked(self, offset, timeout_s=0.0):
        return self.acks.pop(0) if self.acks else True

    @property
    def synced_follower_count(self):
        return self.synced

    def min_acked(self):
        return -1

    def status(self):
        return []


# --------------------------------------------------------------------------
# Group commit at the store layer
# --------------------------------------------------------------------------

class TestGroupCommit:
    def test_concurrent_commits_share_durability_rounds(self, tmp_path):
        store = Store.open(str(tmp_path / "d"), fsync=True)
        assert store.enable_group_commit(window_ms=5.0)
        errs = []

        def submit(i):
            try:
                store.create_jobs([make_job(i)])
            except Exception as e:  # pragma: no cover - failure detail
                errs.append(e)

        threads = [threading.Thread(target=submit, args=(i,))
                   for i in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errs
        stats = store.group_commit_stats()
        assert stats["commits"] == 12
        assert stats["batches"] < 12, stats  # amortization happened
        assert stats["max_batch"] >= 2
        store.close()
        # every batched commit is a real journaled commit
        replayed = Store.replay_only(str(tmp_path / "d"))
        assert len(replayed.jobs_where(lambda j: True)) == 12

    def test_batch_ack_loss_demuxes_indeterminate_to_every_waiter(
            self, tmp_path):
        store = Store.open(str(tmp_path / "d"))
        store.attach_replication(_StubRepl(acks=[False, False, False]),
                                 sync=True, timeout_s=0.01)
        store.enable_group_commit(window_ms=5.0)
        outcomes = []

        def submit(i):
            try:
                store.create_jobs([make_job(i)])
                outcomes.append("committed")
            except ReplicationIndeterminate:
                outcomes.append("indeterminate")

        threads = [threading.Thread(target=submit, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert outcomes == ["indeterminate"] * 4
        # applied locally — the PR 3 contract holds through the demux
        assert store.job(make_job(0).uuid) is not None
        store.close()

    def test_quorum_gate_still_aborts_cleanly_under_group_commit(
            self, tmp_path):
        store = Store.open(str(tmp_path / "d"))
        store.attach_replication(_StubRepl(synced=0), sync=True,
                                 timeout_s=0.01, min_followers=1)
        store.enable_group_commit(window_ms=1.0)
        with pytest.raises(ReplicationTimeout):
            store.create_jobs([make_job(1)])
        # the CP gate fires BEFORE the write: nothing installed anywhere
        assert store.job(make_job(1).uuid) is None
        store.close()
        assert Store.replay_only(str(tmp_path / "d")).job(
            make_job(1).uuid) is None

    def test_fsync_fault_is_indeterminate_for_the_batch(self, tmp_path):
        from cook_tpu.utils.faults import injector
        store = Store.open(str(tmp_path / "d"), fsync=True)
        store.enable_group_commit(window_ms=1.0)
        injector.arm("store.journal.fsync", probability=1.0, max_fires=1)
        try:
            with pytest.raises(ReplicationIndeterminate):
                store.create_jobs([make_job(1)])
        finally:
            injector.disarm("store.journal.fsync")
        # flushed + installed: replay keeps it (never excised — later
        # transactions may already have built on it)
        assert store.job(make_job(1).uuid) is not None
        store.close()
        assert Store.replay_only(str(tmp_path / "d")).job(
            make_job(1).uuid) is not None

    def test_noop_without_journal_and_commit_offset_tracking(
            self, tmp_path):
        assert Store().enable_group_commit() is False
        store = Store.open(str(tmp_path / "d"))
        assert store.commit_offset() == 0
        store.create_jobs([make_job(1)])
        off1 = store.commit_offset()
        assert off1 > 0
        store.create_jobs([make_job(2)])
        assert store.commit_offset() > off1
        store.close()


# --------------------------------------------------------------------------
# FollowerReadView apply loop (plain directories — the mirror is just a
# journal the leader's store happens to write locally)
# --------------------------------------------------------------------------

class TestFollowerReadView:
    def test_incremental_apply_and_staleness(self, tmp_path):
        d = str(tmp_path / "m")
        leader = Store.open(d)
        leader.create_jobs([make_job(1)])
        view = FollowerReadView(d, start=False)
        assert view.store.job(make_job(1).uuid) is not None
        assert view.rebuilds == 1
        # incremental: new records apply through the replay path without
        # a rebuild
        leader.create_jobs([make_job(2)])
        applied = view.poll()
        assert applied == 1 and view.rebuilds == 1
        assert view.store.job(make_job(2).uuid) is not None
        assert view.offset == leader.commit_offset()
        assert view.lag_bytes() == 0
        leader.close()

    def test_rebase_detection_rebuilds_and_swaps(self, tmp_path):
        d = str(tmp_path / "m")
        leader = Store.open(d)
        leader.create_jobs([make_job(1)])
        swaps = []
        view = FollowerReadView(d, start=False, on_swap=swaps.append)
        assert len(swaps) == 1
        # leader checkpoint = snapshot + truncated journal: the byte
        # space re-based, incremental offsets are meaningless
        leader.create_jobs([make_job(2)])
        leader.checkpoint()
        view.poll()
        assert view.rebuilds == 2
        assert len(swaps) == 2
        assert swaps[-1] is view.store
        assert view.store.job(make_job(2).uuid) is not None
        leader.close()

    def test_wait_offset_read_your_writes_gate(self, tmp_path):
        d = str(tmp_path / "m")
        leader = Store.open(d)
        view = FollowerReadView(d, interval_s=0.005)
        try:
            leader.create_jobs([make_job(1)])
            want = leader.commit_offset()
            assert view.wait_offset(want, timeout_s=5.0)
            assert view.store.job(make_job(1).uuid) is not None
            # an offset beyond anything mirrored times out honestly
            assert not view.wait_offset(want + 10_000, timeout_s=0.05)
        finally:
            view.stop()
            leader.close()

    def test_epoch_fence_skipping_matches_replay(self, tmp_path):
        """A deposed leader's lower-epoch records interleaved after a
        higher epoch are skipped by the view exactly as Store.replay
        would skip them."""
        d = tmp_path / "m"
        d.mkdir()
        journal = d / "journal.jsonl"
        # build two real records via a scratch store for valid wire form
        scratch = Store.open(str(tmp_path / "scratch"))
        scratch.create_jobs([make_job(1)])
        scratch.create_jobs([make_job(2)])
        scratch.close()
        from cook_tpu.state.integrity import scan_journal, seal_record
        (rec_a, rec_b), _good, _size = scan_journal(
            str(tmp_path / "scratch" / "journal.jsonl"))
        rec_a["ep"] = 2
        rec_b["ep"] = 1  # deposed leader's late append
        journal.write_text(seal_record(rec_a) + seal_record(rec_b))
        view = FollowerReadView(str(d), start=False)
        assert view.store.job(make_job(1).uuid) is not None
        assert view.store.job(make_job(2).uuid) is None


# --------------------------------------------------------------------------
# REST serving contract over stub wiring
# --------------------------------------------------------------------------

@pytest.fixture()
def follower_rest(tmp_path):
    """A 'leader' journaled store + a follower REST node whose read view
    tails the same directory (stub topology: what matters is the serving
    contract, not the socket)."""
    from cook_tpu.rest.api import ApiServer, CookApi

    d = str(tmp_path / "m")
    leader_store = Store.open(d)
    leader_api = CookApi(leader_store)
    leader = ApiServer(leader_api)
    leader.start()

    view = FollowerReadView(d, interval_s=0.005)

    class StubElector:
        def leader_url(self):
            return leader.url

    api = CookApi(view.store, elector=StubElector(),
                  node_url="http://follower-node")
    api.read_view = view
    view.on_swap(lambda s: setattr(api, "store", s))
    server = ApiServer(api)
    server.start()
    yield leader_store, leader, view, api, server
    server.stop()
    leader.stop()
    view.stop()
    leader_store.close()


class TestFollowerRest:
    def _get(self, url, headers=None, redirect=False):
        class NoRedirect(urllib.request.HTTPRedirectHandler):
            def redirect_request(self, *a, **kw):
                return None

        opener = urllib.request.build_opener() if redirect else \
            urllib.request.build_opener(NoRedirect)
        req = urllib.request.Request(
            url, headers={"X-Cook-User": "alice", **(headers or {})})
        return opener.open(req, timeout=10)

    def test_follower_serves_reads_with_staleness_headers(
            self, follower_rest):
        leader_store, _leader, view, api, server = follower_rest
        leader_store.create_jobs([make_job(1)])
        assert wait_for(
            lambda: view.offset >= leader_store.commit_offset())
        resp = self._get(server.url + f"/jobs/{make_job(1).uuid}")
        assert resp.status == 200
        assert int(resp.headers["X-Cook-Replication-Offset"]) \
            == view.offset
        assert float(resp.headers["X-Cook-Replication-Age-Ms"]) >= 0
        assert json.load(resp)["uuid"] == make_job(1).uuid
        assert api.follower_reads == 1
        # the timeline surface serves from the replicated audit lane
        resp = self._get(server.url
                         + f"/debug/job/{make_job(1).uuid}/timeline")
        kinds = [e["kind"] for e in json.load(resp)["timeline"]]
        assert "submitted" in kinds

    def test_writes_still_redirect_to_leader(self, follower_rest):
        _store, leader, _view, _api, server = follower_rest
        import urllib.error
        req = urllib.request.Request(
            server.url + "/jobs", method="POST",
            data=json.dumps({"jobs": [{"command": "x"}]}).encode(),
            headers={"Content-Type": "application/json",
                     "X-Cook-User": "alice"})

        class NoRedirect(urllib.request.HTTPRedirectHandler):
            def redirect_request(self, *a, **kw):
                return None

        opener = urllib.request.build_opener(NoRedirect)
        with pytest.raises(urllib.error.HTTPError) as e:
            opener.open(req, timeout=10)
        assert e.value.code == 307
        assert e.value.headers["Location"].startswith(leader.url)

    def test_min_offset_satisfied_after_wait(self, follower_rest):
        leader_store, _leader, view, _api, server = follower_rest
        leader_store.create_jobs([make_job(5)])
        want = leader_store.commit_offset()
        # the apply loop races this request: the server-side wait gate
        # must hold the read until the view catches up
        resp = self._get(server.url + f"/jobs/{make_job(5).uuid}",
                         headers={"X-Cook-Min-Offset": str(want)})
        assert resp.status == 200
        assert int(resp.headers["X-Cook-Replication-Offset"]) >= want

    def test_min_offset_beyond_mirror_redirects_to_leader(
            self, follower_rest):
        leader_store, leader, _view, api, server = follower_rest
        api.config.serving.min_offset_wait_seconds = 0.05
        leader_store.create_jobs([make_job(6)])
        import urllib.error
        with pytest.raises(urllib.error.HTTPError) as e:
            self._get(server.url + f"/jobs/{make_job(6).uuid}",
                      headers={"X-Cook-Min-Offset": str(10 ** 12)})
        assert e.value.code == 307
        assert e.value.headers["Location"].startswith(leader.url)

    def test_client_reads_its_own_writes_through_the_fleet(
            self, follower_rest):
        from cook_tpu.client import JobClient
        _store, leader, view, _api, server = follower_rest
        writer = JobClient(leader.url, user="alice")
        uuids = writer.submit([{"command": "x"}])
        assert writer.last_commit_offset  # X-Cook-Commit-Offset landed
        reader = JobClient(server.url, user="alice")
        reader.last_commit_offset = writer.last_commit_offset
        [job] = reader.query(uuids)
        assert job["uuid"] == uuids[0]
        # served by the follower (staleness headers present) once caught
        # up, or by the leader after the redirect — either way the read
        # observed the write.  The token is opaque "<epoch>:<offset>" or
        # bare "<offset>" (this stub leader has no epoch).
        token_off = int(writer.last_commit_offset.split(":")[-1])
        if reader.last_replication_offset is not None:
            assert reader.last_replication_offset >= token_off

    def test_follower_keeps_serving_stale_after_leader_death(
            self, follower_rest):
        leader_store, leader, view, _api, server = follower_rest
        leader_store.create_jobs([make_job(7)])
        assert wait_for(
            lambda: view.offset >= leader_store.commit_offset())
        leader.stop()  # the leader is gone; the view has no new bytes
        time.sleep(0.05)
        resp = self._get(server.url + f"/jobs/{make_job(7).uuid}")
        assert resp.status == 200  # stale, honestly labeled
        assert "X-Cook-Replication-Offset" in resp.headers

    def test_follower_queue_approximation(self, follower_rest):
        leader_store, _leader, view, _api, server = follower_rest
        leader_store.create_jobs([make_job(8), make_job(9)])
        assert wait_for(
            lambda: view.offset >= leader_store.commit_offset())
        resp = self._get(server.url + "/queue")
        queues = json.load(resp)
        assert {j["uuid"] for j in queues.get("default", [])} \
            >= {make_job(8).uuid, make_job(9).uuid}

    def test_debug_replication_serving_block(self, follower_rest):
        leader_store, _leader, view, _api, server = follower_rest
        leader_store.create_jobs([make_job(1)])
        assert wait_for(
            lambda: view.offset >= leader_store.commit_offset())
        self._get(server.url + f"/jobs/{make_job(1).uuid}")
        resp = self._get(server.url + "/debug/replication")
        doc = json.load(resp)
        assert doc["serving"]["reads_served"] >= 1
        assert doc["serving"]["offset"] == view.offset
        assert "lag_bytes" in doc["serving"]
        assert "age_ms" in doc["serving"]


class TestFencedReadToken:
    def test_deposed_leader_refuses_reads_with_token(self, tmp_path):
        """A fenced deposed leader cannot honor read-your-writes tokens
        (the successor holds commits beyond its fence epoch): plain
        reads stay served, token-bearing reads are refused/redirected."""
        from cook_tpu.rest.api import ApiServer, CookApi
        import urllib.error
        store = Store.open(str(tmp_path / "d"))
        store.create_jobs([make_job(1)])
        api = CookApi(store)
        api.fence_guard = lambda: True  # a successor minted a higher epoch
        server = ApiServer(api)
        server.start()
        try:
            # plain read: still answered (clients re-resolve the leader)
            with urllib.request.urlopen(
                    server.url + f"/jobs/{make_job(1).uuid}",
                    timeout=10) as resp:
                assert resp.status == 200
            # token-bearing read: refused (no successor published)
            req = urllib.request.Request(
                server.url + f"/jobs/{make_job(1).uuid}",
                headers={"X-Cook-Min-Offset": "1"})
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=10)
            assert e.value.code == 503
        finally:
            server.stop()
            store.close()


class TestOffsetSpaceTokens:
    def test_epoch_qualified_token_semantics(self, tmp_path):
        """A token from a NEWER leadership is never satisfied by an
        old-space mirror's numerically-larger byte count; a view that
        applied a higher epoch covers any lower-epoch token."""
        d = str(tmp_path / "m")
        leader = Store.open(d)
        leader.create_jobs([make_job(1)])
        view = FollowerReadView(d, start=False)
        # plain-offset token: ordinary compare
        assert view._satisfies(None, view.offset)
        assert not view._satisfies(None, view.offset + 1)
        # un-epoched mirror (max_ep 0) must NOT satisfy an epoch-2
        # token regardless of its byte count
        assert not view._satisfies(2, 1)
        assert not view.wait_token(2, 1, timeout_s=0.05)
        # a view that applied epoch 3 covers any epoch-2 token
        view._max_ep = 3
        assert view._satisfies(2, 10 ** 12)
        assert view._satisfies(3, view.offset)
        assert not view._satisfies(3, view.offset + 1)
        leader.close()

    def test_commit_token_forms(self, tmp_path):
        plain = Store.open(str(tmp_path / "p"))
        plain.create_jobs([make_job(1)])
        assert plain.commit_token() == str(plain.commit_offset())
        plain.close()
        fenced = Store.open(str(tmp_path / "f"), epoch=4, shared=False)
        fenced.create_jobs([make_job(1)])
        assert fenced.commit_token() == f"4:{fenced.commit_offset()}"
        fenced.close()

    def test_malformed_min_offset_is_400(self, follower_rest):
        import urllib.error
        _store, _leader, _view, _api, server = follower_rest
        req = urllib.request.Request(
            server.url + "/jobs?user=alice",
            headers={"X-Cook-User": "alice",
                     "X-Cook-Min-Offset": "not-a-token"})
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=10)
        assert e.value.code == 400


class TestServingConfig:
    def test_boot_validation(self):
        from cook_tpu.config import ServingConfig
        cfg = ServingConfig.from_conf({"group_commit_window_ms": 2,
                                       "follower_reads": False})
        assert cfg.group_commit_window_ms == 2.0
        assert cfg.follower_reads is False
        with pytest.raises(ValueError, match="unknown serving key"):
            ServingConfig.from_conf({"folower_reads": True})
        with pytest.raises(ValueError, match="JSON boolean"):
            ServingConfig.from_conf({"group_commit": "true"})
        with pytest.raises(ValueError, match="max_batch"):
            ServingConfig.from_conf({"group_commit_max_batch": 0})

    def test_daemon_scheduler_section_parses_serving(self):
        from cook_tpu.daemon import build_scheduler_config
        cfg = build_scheduler_config(
            {"serving": {"group_commit_window_ms": 1.5}})
        assert cfg.serving.group_commit_window_ms == 1.5
        with pytest.raises(ValueError):
            build_scheduler_config({"serving": {"nope": 1}})


# --------------------------------------------------------------------------
# Keep-alive connection reuse (the 4->8 reader regression satellite)
# --------------------------------------------------------------------------

class TestKeepAlive:
    def test_jobclient_reuses_one_connection(self, tmp_path):
        from cook_tpu.client import JobClient
        from cook_tpu.rest.api import ApiServer, CookApi
        store = Store.open(str(tmp_path / "d"))
        server = ApiServer(CookApi(store))
        server.start()
        try:
            client = JobClient(server.url, user="alice")
            uuids = client.submit([{"command": "x"}])
            for _ in range(3):
                client.query(uuids)
            import urllib.parse
            netloc = urllib.parse.urlsplit(server.url).netloc
            conn = client._pool.conns[("http", netloc)]
            assert conn._cook_served == 4  # one socket served them all
            client.close()
            assert not client._pool.conns
        finally:
            server.stop()
            store.close()

    def test_stale_pooled_connection_retries_fresh(self, tmp_path):
        from cook_tpu.client import JobClient
        from cook_tpu.rest.api import ApiServer, CookApi
        store = Store.open(str(tmp_path / "d"))
        server = ApiServer(CookApi(store))
        server.start()
        try:
            client = JobClient(server.url, user="alice")
            uuids = client.submit([{"command": "x"}])
            import urllib.parse
            netloc = urllib.parse.urlsplit(server.url).netloc
            # simulate the server idling out the keep-alive socket
            client._pool.conns[("http", netloc)].sock.close()
            [job] = client.query(uuids)  # retried on a fresh socket
            assert job["uuid"] == uuids[0]
        finally:
            server.stop()
            store.close()


# --------------------------------------------------------------------------
# End-to-end over real socket replication
# --------------------------------------------------------------------------

needs_native = pytest.mark.skipif(not repl.replication_available(),
                                  reason="C++ toolchain unavailable")


@needs_native
def test_read_fleet_over_socket_replication(tmp_path):
    """Leader + native follower: the mirrored bytes feed the read view
    through the store's replay path; group commit serves the write side;
    the follower answers queries including the replicated audit lane."""
    root = str(tmp_path)
    d_leader, d_f = os.path.join(root, "l"), os.path.join(root, "f")
    store = Store.open(d_leader)
    srv = repl.ReplicationServer(d_leader, 0)
    store.attach_replication(srv, sync=True)
    store.enable_group_commit(window_ms=2.0)
    follower = repl.ReplicationFollower("127.0.0.1", srv.port, d_f)
    view = None
    try:
        assert wait_for(lambda: srv.synced_follower_count >= 1)
        view = FollowerReadView(d_f, interval_s=0.005)
        threads = [threading.Thread(
            target=lambda i=i: store.create_jobs([make_job(i)]))
            for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert wait_for(lambda: view.offset >= store.commit_offset())
        assert len(view.store.jobs_where(lambda j: True)) == 8
        # the audit lane rode the mirrored journal bytes
        assert any(e["kind"] == "submitted"
                   for e in view.store.audit.timeline(make_job(3).uuid))
        stats = store.group_commit_stats()
        assert stats["commits"] == 8
    finally:
        if view is not None:
            view.stop()
        follower.stop()
        srv.stop()
        store.close()
