"""Fleet observability plane (docs/OBSERVABILITY.md "debugging the
fleet"): cross-process trace stitching into one Perfetto export,
metrics federation over the candidate-registry topology, and the
normalized saturation-signal layer — plus the two satellite contracts
(the follower health roll-up's read-view block, request-id continuity
across the 307 redirect hop).
"""

import http.server
import json
import math
import os
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from cook_tpu.client import JobClient, JobClientError
from cook_tpu.cluster import FakeCluster, FakeHost
from cook_tpu.config import Config, FleetConfig
from cook_tpu.policy.rate_limit import RateLimits, TokenBucketRateLimiter
from cook_tpu.rest import ApiServer, CookApi
from cook_tpu.sched import Scheduler
from cook_tpu.sched.election import FileLeaderElector
from cook_tpu.sched.fleet import (FleetScraper, collect_trace,
                                  compute_saturation, publish_saturation)
from cook_tpu.state import Resources, Store
from cook_tpu.state.replication import known_members
from cook_tpu.utils.metrics import (MetricsRegistry, format_sample,
                                    parse_exposition, registry)
from cook_tpu.utils import tracing
from cook_tpu.utils.tracing import (export_fleet_trace, make_traceparent,
                                    scoped_identity, tracer)

pytestmark = pytest.mark.fleet


@pytest.fixture(autouse=True)
def _clean_observability():
    registry.reset()
    tracer.reset()
    tracer.enabled = True
    yield
    registry.reset()
    tracer.reset()


def wait_until(cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        result = cond()
        if result:
            return result
        time.sleep(0.01)
    return cond()


# ---------------------------------------------------------------------------
# saturation signals
# ---------------------------------------------------------------------------

class _FakeGroupCommitStore:
    """The store surface compute_saturation touches: group-commit stats,
    journal offset, audit queue."""

    def __init__(self, pending=0, offset=0, audit_pending=0):
        self._pending = pending
        self._offset = offset
        self.audit = type("A", (), {
            "pending_durable_count": staticmethod(lambda: audit_pending),
            "stats": staticmethod(lambda: {})})()

    def group_commit_stats(self):
        return {"pending": self._pending, "batches": 0}

    def commit_offset(self):
        return self._offset


class _FakeReadView:
    def __init__(self, age_ms=0.0):
        self._age_ms = age_ms

    def age_ms(self):
        return self._age_ms

    def stats(self):
        return {"offset": 10, "mirror_offset": 10, "lag_bytes": 0,
                "age_ms": self._age_ms, "applied_records": 1,
                "rebuilds": 1}


class TestSaturation:
    def test_all_keys_present_and_zero_on_empty_process(self):
        from cook_tpu.utils.flight import recorder
        recorder.reset()  # cycle_p99 reads the process-global recorder
        values = compute_saturation(Config())
        assert set(values) == {"group_commit_queue", "follower_staleness",
                               "cycle_p99", "audit_queue", "launch_tokens",
                               "journal_head"}
        assert all(v == 0.0 for v in values.values())

    def test_group_commit_formula(self):
        cfg = Config()
        cfg.serving.group_commit_max_batch = 256
        store = _FakeGroupCommitStore(pending=128)
        values = compute_saturation(cfg, store=store)
        assert values["group_commit_queue"] == pytest.approx(0.5)
        # over-full queue clamps, never exceeds 1
        store = _FakeGroupCommitStore(pending=10_000)
        assert compute_saturation(cfg, store=store)[
            "group_commit_queue"] == 1.0

    def test_follower_staleness_formula_and_clamp(self):
        cfg = Config()
        cfg.fleet.staleness_red_line_seconds = 5.0
        values = compute_saturation(cfg, read_view=_FakeReadView(2500.0))
        assert values["follower_staleness"] == pytest.approx(0.5)
        # past the red line clamps to 1.0 (and flips healthy elsewhere)
        values = compute_saturation(cfg, read_view=_FakeReadView(60_000.0))
        assert values["follower_staleness"] == 1.0

    def test_launch_tokens_worst_key(self):
        limiter = TokenBucketRateLimiter(tokens_per_minute=0.0001,
                                         bucket_size=10)
        limiter.spend("pool/alice", 5)
        limiter.spend("pool/bob", 1)
        rl = RateLimits(job_launch=limiter)
        values = compute_saturation(Config(), rate_limits=rl)
        # worst key (alice, 5/10 spent) defines the signal
        assert values["launch_tokens"] == pytest.approx(0.5, abs=0.01)
        limiter.spend("pool/alice", 20)  # deep in debt: clamps
        assert compute_saturation(
            Config(), rate_limits=rl)["launch_tokens"] == 1.0

    def test_audit_and_journal_formulas(self):
        cfg = Config()
        cfg.fleet.audit_queue_red_line = 100
        cfg.fleet.journal_head_red_line_bytes = 1000
        store = _FakeGroupCommitStore(offset=250, audit_pending=25)
        values = compute_saturation(cfg, store=store)
        assert values["audit_queue"] == pytest.approx(0.25)
        assert values["journal_head"] == pytest.approx(0.25)

    def test_publish_pins_gauges_into_unit_interval(self):
        reg = MetricsRegistry()
        publish_saturation({"cycle_p99": 3.7, "audit_queue": -2.0,
                            "launch_tokens": float("nan")}, reg)
        got = {labels["resource"]: value
               for labels, value in reg.series("cook_saturation")}
        assert got == {"cycle_p99": 1.0, "audit_queue": 0.0,
                       "launch_tokens": 0.0}
        assert all(0.0 <= v <= 1.0 and not math.isnan(v)
                   for v in got.values())


# ---------------------------------------------------------------------------
# exposition round trip (the federation wire format)
# ---------------------------------------------------------------------------

class TestExpositionRoundTrip:
    def test_parse_inverts_format(self):
        labels = {"pool": 'we"ird\\pool', "user": "a\nb"}
        line = format_sample("cook_x", labels, 1.25)
        [(name, parsed, value)] = parse_exposition(line)
        assert name == "cook_x" and value == 1.25 and parsed == labels

    def test_parse_real_exposition(self):
        reg = MetricsRegistry()
        reg.counter_inc("cook_things", 3, labels={"kind": "a"})
        reg.gauge_set("cook_level", 0.5)
        reg.observe("cook_lat_seconds", 0.2, labels={"p": "x"})
        samples = parse_exposition(reg.expose())
        names = {n for n, _l, _v in samples}
        assert "cook_things_total" in names
        assert "cook_level" in names
        assert "cook_lat_seconds_bucket" in names  # histograms survive
        assert all(isinstance(v, float) for _n, _l, v in samples)


# ---------------------------------------------------------------------------
# metrics federation
# ---------------------------------------------------------------------------

def _fleet_cfg(**kw):
    kw.setdefault("scrape_interval_seconds", 0.01)
    return FleetConfig(**kw)


def _fake_fetch(expositions):
    """url -> exposition text; raising entries simulate dead members."""

    def fetch(url, timeout_s):
        base = url.split("/metrics")[0].split("/debug")[0]
        body = expositions[base]
        if isinstance(body, Exception):
            raise body
        return body

    return fetch


class TestFederation:
    def _members(self, *urls, roles=None):
        return {f"m{i}": {"url": u,
                          "role": (roles or {}).get(f"m{i}", "member")}
                for i, u in enumerate(urls)}

    def test_merged_view_relabels_with_instance_and_role(self):
        reg = MetricsRegistry()
        scraper = FleetScraper(
            _fleet_cfg(), lambda: self._members(
                "http://a", "http://b",
                roles={"m0": "leader", "m1": "follower"}),
            fetch=_fake_fetch({
                "http://a": 'cook_jobs_waiting 3\n',
                "http://b": 'cook_jobs_waiting 7\n'}),
            registry=reg)
        scraper.scrape(now=100.0)
        samples = parse_exposition(scraper.merged_exposition(now=100.0))
        waiting = {l["instance"]: (l["role"], v)
                   for n, l, v in samples if n == "cook_jobs_waiting"}
        assert waiting == {"m0": ("leader", 3.0), "m1": ("follower", 7.0)}

    def test_label_collision_renames_to_exported(self):
        reg = MetricsRegistry()
        scraper = FleetScraper(
            _fleet_cfg(), lambda: self._members("http://a"),
            fetch=_fake_fetch({"http://a": format_sample(
                "cook_remote", {"instance": "z9", "role": "leader"},
                1.0) + "\n"}),
            registry=reg)
        scraper.scrape(now=100.0)
        [(_, labels, _v)] = [s for s in parse_exposition(
            scraper.merged_exposition(now=100.0))
            if s[0] == "cook_remote"]
        # the member identity wins; the member's own labels survive
        assert labels["instance"] == "m0"
        assert labels["exported_instance"] == "z9"
        assert labels["exported_role"] == "leader"

    def test_unreachable_member_is_data_not_a_gap(self):
        reg = MetricsRegistry()
        scraper = FleetScraper(
            _fleet_cfg(), lambda: self._members("http://up", "http://down"),
            fetch=_fake_fetch({"http://up": "cook_x 1\n",
                               "http://down": ConnectionError("refused")}),
            registry=reg)
        scraper.scrape(now=100.0)
        up = {l["instance"]: v for n, l, v in parse_exposition(
            scraper.merged_exposition(now=100.0))
            if n == "cook_fleet_member_up"}
        assert up == {"m0": 1.0, "m1": 0.0}
        doc = scraper.fleet_doc(now=100.0)
        down = next(m for m in doc["members"] if m["instance"] == "m1")
        assert down["up"] is False
        assert "refused" in down["error"]

    def test_per_member_series_cap_reports_drops(self):
        reg = MetricsRegistry()
        body = "".join(f'cook_s{{i="{i}"}} 1\n' for i in range(50))
        scraper = FleetScraper(
            _fleet_cfg(max_series_per_member=10),
            lambda: self._members("http://a"),
            fetch=_fake_fetch({"http://a": body}), registry=reg)
        scraper.scrape(now=100.0)
        member = scraper.fleet_doc(now=100.0)["members"][0]
        assert member["series"] == 10
        assert member["dropped_series"] == 40
        dropped = {l["instance"]: v for l, v in reg.series(
            "cook_fleet_dropped_series")}
        assert dropped == {"m0": 40.0}

    def test_instance_cardinality_guard_folds_churning_members(self):
        # a churning registry minting a new instance name every sweep
        # must fold past the cap (max_members*2+16) instead of growing
        # the local registry without bound
        reg = MetricsRegistry()
        current = {}
        scraper = FleetScraper(
            _fleet_cfg(max_members=1), lambda: dict(current),
            fetch=_fake_fetch({"http://a": "cook_x 1\n"}), registry=reg)
        for i in range(40):
            current.clear()
            current[f"churn-{i:03d}"] = {"url": "http://a"}
            scraper.scrape(now=100.0 + i)
        instances = {l["instance"]
                     for l, _v in reg.series("cook_fleet_member_up")}
        assert len(instances) <= 18 + 1  # cap + the "other" fold
        assert "other" in instances
        folds = list(reg.series("cook_metrics_dropped_labels"))
        assert folds  # the folds are themselves observable
        assert any(l.get("metric") == "cook_fleet_member_up"
                   for l, _ in folds)

    def test_fleet_burn_is_max_over_members(self):
        reg = MetricsRegistry()
        mk = lambda v: format_sample(
            "cook_slo_burn_rate",
            {"slo": "queue-latency", "pool": "default"}, v) + "\n"
        scraper = FleetScraper(
            _fleet_cfg(), lambda: self._members("http://a", "http://b"),
            fetch=_fake_fetch({"http://a": mk(0.5), "http://b": mk(2.0)}),
            registry=reg)
        scraper.scrape(now=100.0)
        doc = scraper.fleet_doc(now=100.0)
        [burn] = doc["fleet_burn"]
        assert burn["burn"] == 2.0  # the worst member pages, not the mean
        assert burn["pool"] == "default"
        [(labels, value)] = reg.series("cook_fleet_slo_burn_rate")
        assert value == 2.0

    def test_max_members_cap_is_loud(self):
        reg = MetricsRegistry()
        members = self._members(*[f"http://h{i}" for i in range(5)])
        scraper = FleetScraper(
            _fleet_cfg(max_members=2), lambda: members,
            fetch=_fake_fetch({f"http://h{i}": "cook_x 1\n"
                               for i in range(5)}),
            registry=reg)
        scraper.scrape(now=100.0)
        assert len(scraper.fleet_doc(now=100.0)["members"]) == 2
        assert sum(v for _l, v in reg.series(
            "cook_fleet_members_skipped")) == 3.0

    def test_maybe_scrape_self_gates(self):
        reg = MetricsRegistry()
        calls = []
        scraper = FleetScraper(
            FleetConfig(scrape_interval_seconds=100.0),
            lambda: calls.append(1) or {}, registry=reg)
        assert scraper.maybe_scrape(now=1000.0) is True
        assert scraper.maybe_scrape(now=1001.0) is False  # inside window
        assert scraper.maybe_scrape(now=1101.0) is True
        assert len(calls) == 2


# ---------------------------------------------------------------------------
# config boot validation
# ---------------------------------------------------------------------------

class TestFleetConfig:
    def test_unknown_key_fails_boot(self):
        with pytest.raises(ValueError, match="scrape_intervall"):
            FleetConfig.from_conf({"scrape_intervall_seconds": 5})

    def test_member_entries_validated(self):
        with pytest.raises(ValueError, match="url"):
            FleetConfig(members=[{"instance": "x"}])
        cfg = FleetConfig.from_conf({"members": [
            {"instance": "a1", "url": "http://a1:7776", "role": "agent"}]})
        assert cfg.members[0]["role"] == "agent"

    def test_daemon_section_wires_through(self):
        from cook_tpu.daemon import build_scheduler_config
        cfg = build_scheduler_config({"fleet": {
            "scrape_interval_seconds": 3.5, "max_members": 8}})
        assert cfg.fleet.scrape_interval_seconds == 3.5
        assert cfg.fleet.max_members == 8
        with pytest.raises(ValueError):
            build_scheduler_config({"fleet": {"bogus_knob": 1}})


# ---------------------------------------------------------------------------
# topology discovery (the ONE source all three layers share)
# ---------------------------------------------------------------------------

class TestKnownMembers:
    def test_candidates_plus_self_plus_static(self, tmp_path):
        elector = FileLeaderElector(tmp_path / "lock", "http://me")
        elector.publish_candidate("peer-1", {"url": "http://peer-1",
                                             "ts": time.time()})
        members = known_members(elector, self_id="me",
                                self_url="http://me", leader=True,
                                extra=[{"instance": "agent-a",
                                        "url": "http://agent-a",
                                        "role": "agent"}])
        assert members["me"]["role"] == "leader"
        assert members["me"]["self"] is True
        assert members["peer-1"]["role"] == "follower"
        assert members["agent-a"]["role"] == "agent"

    def test_urlless_candidates_skipped_stale_kept(self, tmp_path):
        elector = FileLeaderElector(tmp_path / "lock", "http://me")
        elector.publish_candidate("old", {"url": "http://old", "ts": 1.0})
        elector.publish_candidate("no-url", {"ts": time.time()})
        members = known_members(elector)
        assert "old" in members  # stale = unreachable = data, kept
        assert "no-url" not in members


# ---------------------------------------------------------------------------
# cross-process trace stitching
# ---------------------------------------------------------------------------

class TestFleetTraceExport:
    def test_per_process_tracks_and_dedupe(self):
        docs = [
            {"span": "client.submit", "trace_id": "t1", "span_id": "s1",
             "parent_id": None, "proc": "client-cli", "start": 1.0,
             "duration_ms": 30.0, "error": None},
            {"span": "http.request", "trace_id": "t1", "span_id": "s2",
             "parent_id": "s1", "proc": "leader-1", "start": 1.001,
             "duration_ms": 20.0, "error": None},
            # the same span arriving from two members' rings dedupes
            {"span": "http.request", "trace_id": "t1", "span_id": "s2",
             "parent_id": "s1", "proc": "leader-1", "start": 1.001,
             "duration_ms": 20.0, "error": None},
            {"span": "agent.exec", "trace_id": "t1", "span_id": "s3",
             "parent_id": "s1", "proc": "agent-h0", "start": 1.01,
             "duration_ms": 5.0, "error": None},
        ]
        trace = export_fleet_trace(docs, "t1")
        events = trace["traceEvents"]
        names = {e["args"]["name"]: e["pid"] for e in events
                 if e["ph"] == "M" and e["name"] == "process_name"}
        assert set(names) == {"client-cli", "leader-1", "agent-h0"}
        assert len(set(names.values())) == 3  # one pid track per process
        xs = [e for e in events if e["ph"] == "X"]
        assert len(xs) == 3  # the duplicate leader span folded
        # client sorts first in the Perfetto track order
        sort = {e["pid"]: e["args"]["sort_index"] for e in events
                if e["ph"] == "M" and e["name"] == "process_sort_index"}
        assert sort[names["client-cli"]] < sort[names["leader-1"]]
        assert trace["otherData"]["fleet"] is True

    def test_collect_trace_merges_and_records_provenance(self):
        local = [{"span": "a", "trace_id": "t", "span_id": "l1",
                  "proc": "leader", "start": 1.0, "duration_ms": 1.0}]
        remote = {"spans": [{"span": "b", "trace_id": "t", "span_id": "r1",
                             "proc": "follower", "start": 1.0,
                             "duration_ms": 1.0}]}

        def fetch(url, timeout_s):
            if "dead" in url:
                raise OSError("down")
            return json.dumps(remote)

        spans, provenance = collect_trace(
            "t", {"f1": {"url": "http://f1"}, "f2": {"url": "http://dead"}},
            fetch=fetch, local_spans=local)
        assert {d["span_id"] for d in spans} == {"l1", "r1"}
        by_instance = {p["instance"]: p for p in provenance}
        assert by_instance["f1"]["ok"] and by_instance["f1"]["spans"] == 1
        assert not by_instance["f2"]["ok"]
        assert "down" in by_instance["f2"]["error"]


class TestStitchedTopology:
    """The acceptance topology: client -> follower (redirect) -> leader,
    plus a REAL agent-executor subprocess, all under ONE client-minted
    trace — one Perfetto export, >=3 distinct process tracks."""

    @pytest.fixture()
    def topology(self, tmp_path):
        store = Store()
        cluster = FakeCluster("c", [FakeHost("h0",
                                             Resources(cpus=8, mem=8192))])
        cfg = Config()
        cfg.default_matcher.backend = "cpu"
        cfg.fleet.scrape_interval_seconds = 0.01
        sched = Scheduler(store, cfg, [cluster], rank_backend="cpu")
        leader_api = CookApi(store, scheduler=sched, config=cfg)
        leader_api.instance = "leader-1"
        leader_srv = ApiServer(leader_api)
        leader_srv.start()
        elector = FileLeaderElector(tmp_path / "lock", leader_srv.url)
        elector.campaign()
        wait_until(lambda: elector.is_leader)

        follower_api = CookApi(Store(), scheduler=None, config=cfg,
                               elector=elector, node_url="http://follower")
        follower_api.instance = "follower-1"
        follower_srv = ApiServer(follower_api)
        follower_srv.start()

        members = {
            "leader-1": {"url": leader_srv.url, "role": "leader",
                         "self": True},
            "follower-1": {"url": follower_srv.url, "role": "follower"},
        }
        leader_api.fleet = FleetScraper(cfg.fleet, lambda: dict(members))
        yield leader_srv, follower_srv, store
        follower_srv.stop()
        leader_srv.stop()
        elector.resign()

    def test_single_export_stitches_three_processes(self, topology,
                                                    tmp_path):
        leader_srv, follower_srv, store = topology
        with scoped_identity("client-cli"):
            with tracer.span("client.submit") as root:
                trace_id = root.trace_id
                client = JobClient(follower_srv.url, user="alice")
                uuid = client.submit_one("echo hi")  # 307 -> leader
        assert store.job(uuid) is not None
        assert client.last_trace_id == trace_id

        # the agent leg: the REAL executor wrapper in its own process,
        # adopting the propagated traceparent (sched/matcher.py stamps
        # COOK_TRACEPARENT into the task env; here we play launch path)
        sandbox = tmp_path / "sandbox"
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        env = dict(os.environ)
        env.update(COOK_SANDBOX=str(sandbox), COOK_TASK_ID="task-1",
                   COOK_TRACEPARENT=make_traceparent(trace_id),
                   COOK_HOSTNAME="h0",
                   PYTHONPATH=os.pathsep.join(
                       p for p in (repo_root, env.get("PYTHONPATH")) if p))
        proc = subprocess.run(
            [sys.executable, "-m", "cook_tpu.agent.executor",
             "echo", "ran"],
            env=env, cwd=str(tmp_path), timeout=60,
            capture_output=True)
        assert proc.returncode == 0, proc.stderr.decode()
        agent_docs = [json.loads(line) for line in
                      (sandbox / "trace_spans.jsonl").read_text()
                      .splitlines()]
        assert agent_docs, "executor retained no spans for the trace"
        exec_doc = next(d for d in agent_docs if d["span"] == "agent.exec")
        assert exec_doc["trace_id"] == trace_id
        assert exec_doc["proc"] == "agent-h0"
        assert exec_doc["exit_code"] == 0
        # the agent's ring died with its process; its sandbox-retained
        # spans re-enter the leader's ring the way an agent-side
        # collector would hand them over
        tracer.finished.extend(agent_docs)

        # ONE stitched export off the leader, fanned out to the fleet
        wait_until(lambda: tracer.traces(trace_id))
        with urllib.request.urlopen(
                f"{leader_srv.url}/debug/trace?trace_id={trace_id}",
                timeout=10) as resp:
            trace = json.loads(resp.read())
        assert trace["otherData"]["fleet"] is True
        assert trace["otherData"]["trace_id"] == trace_id
        events = trace["traceEvents"]
        tracks = {e["args"]["name"]: e["pid"] for e in events
                  if e["ph"] == "M" and e["name"] == "process_name"}
        # >=3 distinct processes on distinct pid tracks: the client,
        # the leader (adopted via 307), the agent subprocess — plus the
        # follower's redirect leg recorded under ITS identity
        assert {"client-cli", "leader-1", "agent-h0"} <= set(tracks)
        assert "follower-1" in tracks
        assert len({tracks[n] for n in tracks}) == len(tracks)
        by_pid = {}
        for e in events:
            if e["ph"] == "X":
                by_pid.setdefault(e["pid"], []).append(e)
        for name in ("client-cli", "leader-1", "agent-h0"):
            assert by_pid.get(tracks[name]), f"no spans on {name}'s track"
        # fan-out provenance names the follower's contribution
        members = {m["instance"]: m
                   for m in trace["otherData"]["members"]}
        assert members["follower-1"]["ok"] is True

    def test_debug_fleet_and_metrics_fleet_serve(self, topology):
        leader_srv, follower_srv, _store = topology
        client = JobClient(leader_srv.url, user="alice")
        doc = client.debug_fleet()
        assert doc["enabled"] is True
        by_instance = {m["instance"]: m for m in doc["members"]}
        assert by_instance["follower-1"]["up"] is True
        assert by_instance["follower-1"]["role"] == "follower"
        assert doc["local"]["role"] == "leader"
        assert set(doc["local"]["saturation"]) >= {"cycle_p99",
                                                   "launch_tokens"}
        text = client.metrics_fleet()
        samples = parse_exposition(text)
        up = {l["instance"] for n, l, _v in samples
              if n == "cook_fleet_member_up"}
        assert up == {"leader-1", "follower-1"}
        # every federated series carries the member identity
        assert all("instance" in l for n, l, _v in samples)


# ---------------------------------------------------------------------------
# satellite: request-id continuity across the 307 hop
# ---------------------------------------------------------------------------

class _RedirectingHandler(http.server.BaseHTTPRequestHandler):
    """A fake follower that mints an id, 307s, pointing at a fake
    leader that either adopts the forwarded id or breaks the chain."""
    leader_url = None
    adopt = True
    seen_forwarded = []

    def do_GET(self):
        if self.server.role == "follower":
            self.send_response(307)
            self.send_header("X-Cook-Request-Id", "follower-minted-id")
            self.send_header("Location", self.leader_url + self.path)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        forwarded = self.headers.get("X-Cook-Request-Id")
        type(self).seen_forwarded.append(forwarded)
        echoed = forwarded if self.adopt and forwarded \
            else "leader-minted-id"
        body = json.dumps({"jobs": []}).encode()
        self.send_response(200)
        self.send_header("X-Cook-Request-Id", echoed)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):
        pass


def _serve(role):
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                          _RedirectingHandler)
    srv.role = role
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f"http://127.0.0.1:{srv.server_address[1]}"


class TestRequestIdAcrossRedirect:
    @pytest.fixture(autouse=True)
    def _servers(self):
        _RedirectingHandler.seen_forwarded = []
        leader, leader_url = _serve("leader")
        follower, follower_url = _serve("follower")
        _RedirectingHandler.leader_url = leader_url
        self.follower_url = follower_url
        yield
        leader.shutdown()
        follower.shutdown()

    def test_follower_minted_id_is_forwarded_and_adopted(self):
        _RedirectingHandler.adopt = True
        client = JobClient(self.follower_url, user="alice")
        client.query([])
        # the redirect hop FORWARDED the follower's id...
        assert _RedirectingHandler.seen_forwarded == ["follower-minted-id"]
        # ...and the chain settles on that single id
        assert client.last_request_id == "follower-minted-id"

    def test_echo_mismatch_fails_loudly(self):
        _RedirectingHandler.adopt = False  # leader mints its own id
        client = JobClient(self.follower_url, user="alice")
        with pytest.raises(JobClientError) as exc:
            client.query([])
        assert exc.value.status == 502
        assert "echo mismatch" in str(exc.value)

    def test_real_servers_keep_one_id_across_redirect(self, tmp_path):
        store = Store()
        leader_api = CookApi(store)
        leader_srv = ApiServer(leader_api)
        leader_srv.start()
        elector = FileLeaderElector(tmp_path / "lock", leader_srv.url)
        elector.campaign()
        wait_until(lambda: elector.is_leader)
        follower_srv = ApiServer(CookApi(Store(), elector=elector,
                                         node_url="http://f"))
        follower_srv.start()
        try:
            client = JobClient(follower_srv.url, user="alice")
            uuid = client.submit_one("echo hi")
            assert store.job(uuid) is not None
            assert client.last_request_id
        finally:
            follower_srv.stop()
            leader_srv.stop()
            elector.resign()


# ---------------------------------------------------------------------------
# satellite: the follower health roll-up carries its read-view block
# ---------------------------------------------------------------------------

class TestFollowerHealth:
    def _api(self, age_ms):
        cfg = Config()
        cfg.fleet.staleness_red_line_seconds = 5.0
        api = CookApi(Store(), config=cfg)
        api.read_view = _FakeReadView(age_ms)
        api.follower_reads = 12
        return api

    def test_fresh_follower_reports_role_and_read_view(self):
        health = self._api(age_ms=100.0).debug_health()
        assert health["role"] == "follower"
        assert health["leader"] is False  # back-compat bool kept
        assert health["read_view"]["reads_served"] == 12
        assert health["read_view"]["age_ms"] == 100.0
        assert health["healthy"] is True
        assert 0.0 < health["saturation"]["follower_staleness"] < 1.0

    def test_stale_follower_is_unhealthy(self):
        health = self._api(age_ms=60_000.0).debug_health()
        assert health["saturation"]["follower_staleness"] == 1.0
        assert health["healthy"] is False
        assert "follower_staleness" in health["saturation_hot"]

    def test_leader_health_has_role_and_saturation(self):
        api = CookApi(Store())
        health = api.debug_health()
        assert health["role"] == "standby"  # no scheduler attached here
        assert set(health["saturation"]) == {
            "group_commit_queue", "follower_staleness", "cycle_p99",
            "audit_queue", "launch_tokens", "journal_head"}


# ---------------------------------------------------------------------------
# the endpoint registry lint (docs/OBSERVABILITY.md endpoint table)
# ---------------------------------------------------------------------------

class TestEndpointRegistry:
    def test_every_observability_route_is_documented(self):
        from pathlib import Path
        from cook_tpu.analysis.registry import (documented_endpoints,
                                                harvest_endpoints)
        root = Path(__file__).resolve().parent.parent
        harvested = harvest_endpoints(root / "cook_tpu")
        assert harvested  # the extractor actually sees API_ROUTES
        assert {"/debug/fleet", "/debug/trace/spans",
                "/metrics/fleet"} <= harvested
        doc = (root / "docs" / "OBSERVABILITY.md").read_text()
        missing = harvested - documented_endpoints(doc)
        assert not missing, (
            f"/debug endpoints missing from the OBSERVABILITY.md "
            f"endpoint table: {sorted(missing)}")
