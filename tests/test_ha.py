"""HA tests: leader election, follower redirect, dynamic cluster config,
incremental config rollouts."""

import json
import time
import urllib.request

import pytest

from cook_tpu.client import JobClient, JobClientError
from cook_tpu.cluster import FakeCluster, FakeHost
from cook_tpu.config import Config
from cook_tpu.policy.incremental import IncrementalConfig
from cook_tpu.rest import ApiServer, CookApi
from cook_tpu.sched import Scheduler
from cook_tpu.sched.election import FileLeaderElector
from cook_tpu.state import Resources, Store


class TestFileLeaderElector:
    def test_single_candidate_wins(self, tmp_path):
        events = []
        elector = FileLeaderElector(
            tmp_path / "lock", "http://node-a",
            on_leadership=lambda: events.append("lead"))
        elector.campaign()
        deadline = time.time() + 5
        while time.time() < deadline and not elector.is_leader:
            time.sleep(0.05)
        assert elector.is_leader
        assert elector.leader_url() == "http://node-a"
        assert events == ["lead"]
        elector.resign()

    def test_second_candidate_takes_over_on_resign(self, tmp_path):
        a = FileLeaderElector(tmp_path / "lock", "http://node-a")
        b = FileLeaderElector(tmp_path / "lock", "http://node-b",
                              poll_interval_s=0.05)
        a.campaign()
        deadline = time.time() + 5
        while time.time() < deadline and not a.is_leader:
            time.sleep(0.05)
        b.campaign()
        time.sleep(0.3)
        assert not b.is_leader  # a holds the lock
        losses = []
        a.on_loss = lambda: losses.append(True)
        a.resign()
        assert losses == [True]
        deadline = time.time() + 5
        while time.time() < deadline and not b.is_leader:
            time.sleep(0.05)
        assert b.is_leader
        assert b.leader_url() == "http://node-b"
        b.resign()


class TestFollowerRedirect:
    def test_follower_redirects_to_leader(self, tmp_path):
        # leader node: full scheduler + api
        store = Store()
        cluster = FakeCluster("c", [FakeHost("h0", Resources(cpus=8, mem=8192))])
        cfg = Config()
        cfg.default_matcher.backend = "cpu"
        sched = Scheduler(store, cfg, [cluster], rank_backend="cpu")
        leader_api = CookApi(store, scheduler=sched)
        leader_srv = ApiServer(leader_api)
        leader_srv.start()

        elector = FileLeaderElector(tmp_path / "lock", leader_srv.url)
        elector.campaign()
        deadline = time.time() + 5
        while time.time() < deadline and not elector.is_leader:
            time.sleep(0.05)

        # follower node: api-only (no scheduler), knows the elector
        follower_api = CookApi(Store(), scheduler=None, elector=elector,
                               node_url="http://follower")
        follower_srv = ApiServer(follower_api)
        follower_srv.start()
        try:
            # urllib follows 307 automatically incl. method preservation
            client = JobClient(follower_srv.url, user="alice")
            uuid = client.submit_one("echo hi")
            # job landed on the leader's store
            assert store.job(uuid) is not None
            # redirected GETs keep their query string (regression)
            assert client.query([uuid])[0]["uuid"] == uuid
            # keep-alive survives a redirected POST (body drained)
            uuid2 = client.submit_one("echo again")
            assert store.job(uuid2) is not None
            # local-only endpoints answer without redirect
            req = urllib.request.Request(follower_srv.url + "/info")
            with urllib.request.urlopen(req) as resp:
                assert resp.status == 200
        finally:
            follower_srv.stop()
            leader_srv.stop()
            elector.resign()


@pytest.fixture()
def admin_system():
    store = Store()
    c1 = FakeCluster("east", [FakeHost("e0", Resources(cpus=8, mem=8192))])
    c2 = FakeCluster("west", [FakeHost("w0", Resources(cpus=8, mem=8192))])
    cfg = Config()
    cfg.default_matcher.backend = "cpu"
    sched = Scheduler(store, cfg, [c1, c2], rank_backend="cpu")
    api = CookApi(store, scheduler=sched, admins=["admin"])
    server = ApiServer(api)
    server.start()
    yield store, sched, server
    server.stop()


def _post(url, path, body, user="admin"):
    req = urllib.request.Request(
        url + path, method="POST", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", "X-Cook-User": user})
    with urllib.request.urlopen(req) as resp:
        return json.loads(resp.read())


def _get(url, path, user="admin"):
    req = urllib.request.Request(url + path,
                                 headers={"X-Cook-User": user})
    with urllib.request.urlopen(req) as resp:
        return json.loads(resp.read())


class TestDynamicClusterConfig:
    def test_drain_and_delete_lifecycle(self, admin_system):
        store, sched, server = admin_system
        clusters = _get(server.url, "/compute-clusters")
        assert {c["name"] for c in clusters} == {"east", "west"}
        # drain east: it stops offering
        _post(server.url, "/compute-clusters/east", {"state": "draining"})
        from cook_tpu.state import Job, new_uuid
        store.create_jobs([Job(uuid=new_uuid(), user="u", command="x",
                               resources=Resources(cpus=1, mem=10))])
        sched.step_rank()
        res = sched.step_match()["default"]
        [tid] = res.launched_task_ids
        assert store.instance(tid).compute_cluster == "west"
        # illegal transition rejected
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(server.url, "/compute-clusters/east", {"state": "deleted2"})
        assert e.value.code == 422
        # draining -> deleted removes it
        _post(server.url, "/compute-clusters/east", {"state": "deleted"})
        assert {c["name"] for c in _get(server.url, "/compute-clusters")} \
            == {"west"}

    def test_requires_admin(self, admin_system):
        _store, _sched, server = admin_system
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(server.url, "/compute-clusters/west",
                  {"state": "draining"}, user="peon")
        assert e.value.code == 403


class TestIncrementalConfig:
    def test_portion_resolution_is_stable_and_proportional(self):
        cfg = IncrementalConfig()
        cfg.set("image-version", [{"value": "v1", "portion": 0.7},
                                  {"value": "v2", "portion": 0.3}])
        counts = {"v1": 0, "v2": 0}
        for i in range(2000):
            v = cfg.resolve("image-version", f"job-{i}")
            counts[v] += 1
            # stability: same uuid -> same value
            assert cfg.resolve("image-version", f"job-{i}") == v
        assert 0.6 < counts["v1"] / 2000 < 0.8

    def test_portions_must_sum_to_one(self):
        cfg = IncrementalConfig()
        with pytest.raises(ValueError):
            cfg.set("k", [{"value": 1, "portion": 0.5}])

    def test_rest_roundtrip(self, admin_system):
        _store, _sched, server = admin_system
        _post(server.url, "/incremental-config",
              {"sidecar-version": [{"value": "1.0", "portion": 1.0}]})
        got = _get(server.url, "/incremental-config")
        assert got["sidecar-version"][0]["value"] == "1.0"


class TestLeaseElection:
    """Distributed (k8s-Lease-style) election: TTL lease with CAS acquire,
    fencing epochs via leaseTransitions, failover after expiry (the
    reference's ZooKeeper slot, mesos.clj:153-328)."""

    def _pair(self):
        from cook_tpu.cluster.k8s.fake_api import FakeKubernetesApi
        from cook_tpu.sched.election import LeaseLeaderElector

        api = FakeKubernetesApi()
        clock = {"t": 0.0}
        mk = lambda ident, url, events: LeaseLeaderElector(  # noqa: E731
            api, identity=ident, node_url=url, duration_s=10.0,
            clock=lambda: clock["t"],
            on_leadership=lambda: events.append("lead"),
            on_loss=lambda: events.append("loss"))
        return api, clock, mk

    def test_single_winner_and_renewal(self):
        api, clock, mk = self._pair()
        ev_a, ev_b = [], []
        a = mk("node-a", "http://a:1", ev_a)
        b = mk("node-b", "http://b:2", ev_b)
        assert a.try_once() and not b.try_once()
        assert a.is_leader and not b.is_leader
        assert a.leader_url() == "http://a:1" == b.leader_url()
        assert ev_a == ["lead"] and ev_b == []
        # renewal keeps the hold past the original TTL
        for _ in range(5):
            clock["t"] += 5.0
            assert a.try_once() and not b.try_once()
        assert a.epoch == 1

    def test_failover_after_ttl_with_epoch_bump(self):
        api, clock, mk = self._pair()
        ev_a, ev_b = [], []
        a = mk("node-a", "http://a:1", ev_a)
        b = mk("node-b", "http://b:2", ev_b)
        assert a.try_once()
        # leader dies (stops renewing); follower can't take over early...
        clock["t"] += 5.0
        assert not b.try_once()
        assert b.leader_url() == "http://a:1"
        # ...but wins after the TTL lapses, with a fencing-epoch bump
        clock["t"] += 6.0
        assert b.try_once()
        assert b.is_leader and b.epoch == 2
        assert b.leader_url() == "http://b:2"
        # the deposed leader's next renewal discovers the loss
        assert not a.try_once()
        assert not a.is_leader and ev_a == ["lead", "loss"]

    def test_resign_releases_immediately(self):
        api, clock, mk = self._pair()
        ev_a, ev_b = [], []
        a = mk("node-a", "http://a:1", ev_a)
        b = mk("node-b", "http://b:2", ev_b)
        assert a.try_once()
        a.resign()
        assert ev_a == ["lead", "loss"]
        assert b.try_once() and b.is_leader
        # stale-hold guard: no live leader -> no redirect target
        b.resign()
        assert b.leader_url() is None

    def test_renewal_errors_do_not_split_brain(self):
        """A flaky lease API must not kill the renewal loop while the node
        still believes it leads; persistent failures past the TTL step the
        leader down pre-emptively instead of double-leading."""
        from cook_tpu.cluster.k8s.fake_api import FakeKubernetesApi
        from cook_tpu.sched.election import LeaseLeaderElector

        api = FakeKubernetesApi()
        clock = {"t": 0.0}
        fail = {"on": False}
        real_try = api.try_acquire_lease

        def flaky(*a, **kw):
            if fail["on"]:
                raise ConnectionError("apiserver 500")
            return real_try(*a, **kw)
        api.try_acquire_lease = flaky

        events = []
        a = LeaseLeaderElector(api, "node-a", "http://a:1", duration_s=10.0,
                               renew_interval_s=0.01,
                               clock=lambda: clock["t"],
                               on_leadership=lambda: events.append("lead"),
                               on_loss=lambda: events.append("loss"))
        a.campaign()
        deadline = time.time() + 5
        while not a.is_leader and time.time() < deadline:
            time.sleep(0.01)
        assert a.is_leader
        fail["on"] = True           # apiserver goes dark
        clock["t"] += 5.0           # under the TTL: stays leader, retrying
        time.sleep(0.1)
        assert a.is_leader
        clock["t"] += 6.0           # renewals failing past the TTL
        deadline = time.time() + 5
        while a.is_leader and time.time() < deadline:
            time.sleep(0.01)
        assert not a.is_leader      # stepped down, no split brain
        assert events == ["lead", "loss"]
        a.resign()


class TestJournalEpochFencing:
    """Cross-host failover over a SHARED journal directory: appends carry
    the election epoch, a successor's claim fences the directory, and a
    deposed-but-alive leader's late writes are rejected instead of
    corrupting the journal the successor replays (the Datomic-as-shared-
    store semantics of the reference, datomic.clj:79, mesos.clj:153-328)."""

    def _job(self, user="alice"):
        from cook_tpu.state import Job, Resources, new_uuid
        return Job(uuid=new_uuid(), user=user, command="x",
                   resources=Resources(cpus=1.0, mem=64.0))

    def test_contested_failover_rejects_stale_leader(self, tmp_path):
        from cook_tpu.state import StaleEpochError, Store
        d = str(tmp_path / "shared")
        # leader A claims the dir and commits real work
        a = Store.open(d, epoch="auto")
        assert a._journal_epoch == 1
        j1 = self._job()
        a.create_jobs([j1])
        # A pauses (NOT killed: its fd stays open, its lock is still held);
        # B takes over from the shared dir at the next epoch
        b = Store.open(d, epoch="auto")
        assert b._journal_epoch == 2
        assert b.job(j1.uuid) is not None  # replayed A's committed work
        j2 = self._job("bob")
        b.create_jobs([j2])
        # A wakes and tries to write: rejected, nothing installed
        import pytest as _pytest
        with _pytest.raises(StaleEpochError):
            a.create_jobs([self._job("late")])
        assert a._journal_poisoned
        with _pytest.raises(RuntimeError):  # poisoned: every later tx too
            a.create_jobs([self._job("later")])
        # B is unaffected and keeps committing
        j3 = self._job("bob")
        b.create_jobs([j3])
        # a third leader replays everything A and B legitimately committed
        c = Store.open(d, epoch="auto")
        assert c._journal_epoch == 3
        assert c.job(j1.uuid) is not None
        assert c.job(j2.uuid) is not None
        assert c.job(j3.uuid) is not None

    def test_stale_interleaved_record_skipped_on_replay(self, tmp_path):
        """The O_APPEND race: a deposed leader's record that lands in the
        file AFTER the successor fenced must be dropped by replay."""
        import json
        from cook_tpu.state import Store
        d = str(tmp_path / "shared")
        a = Store.open(d, epoch="auto")
        j1 = self._job()
        a.create_jobs([j1])
        b = Store.open(d, epoch="auto")
        j2 = self._job("bob")
        b.create_jobs([j2])
        # simulate A's in-flight write landing after B's: an epoch-1 record
        # appended at the tail of the shared journal
        ghost = self._job("ghost")
        with open(d + "/journal.jsonl", "a", encoding="utf-8") as f:
            f.write(json.dumps({
                "tx": 999, "ep": 1,
                "w": {f"jobs/{ghost.uuid}": {
                    "uuid": ghost.uuid, "user": "ghost", "command": "x"}},
            }) + "\n")
        c = Store.open(d, epoch="auto")
        assert c.job(j1.uuid) is not None
        assert c.job(j2.uuid) is not None
        assert c.job(ghost.uuid) is None  # stale write never committed

    def test_stale_claim_refused_at_open(self, tmp_path):
        from cook_tpu.state import StaleEpochError, Store
        d = str(tmp_path / "shared")
        Store.open(d, epoch=5)
        import pytest as _pytest
        with _pytest.raises(StaleEpochError):
            Store.open(d, epoch=3)

    def test_unfenced_open_still_works(self, tmp_path):
        """epoch=None keeps the single-host behavior: no fence file, no
        epoch stamps, reopen replays everything."""
        from cook_tpu.state import Store
        d = str(tmp_path / "solo")
        a = Store.open(d)
        j = self._job()
        a.create_jobs([j])
        a.close()
        b = Store.open(d)
        assert b.job(j.uuid) is not None
        import os
        assert not os.path.exists(d + "/epoch")

    def test_deposed_leader_checkpoint_refused(self, tmp_path):
        """A deposed leader's graceful-shutdown checkpoint must not
        overwrite the shared snapshot/journal with stale state."""
        from cook_tpu.state import StaleEpochError, Store
        d = str(tmp_path / "shared")
        a = Store.open(d, epoch="auto")
        j1 = self._job()
        a.create_jobs([j1])
        b = Store.open(d, epoch="auto")
        j2 = self._job("bob")
        b.create_jobs([j2])
        import pytest as _pytest
        with _pytest.raises(StaleEpochError):
            a.checkpoint()  # deposed: refused
        # replay_only = a follower's read view (claims no epoch)
        c = Store.replay_only(d)
        assert c.job(j2.uuid) is not None  # successor's commit survived
        b.checkpoint()  # the live leader may compact
        c2 = Store.replay_only(d)
        assert c2.job(j1.uuid) is not None
        assert c2.job(j2.uuid) is not None

    def test_takeover_writes_epoch_barrier(self, tmp_path):
        from cook_tpu.state import Store
        from cook_tpu.state.integrity import scan_journal
        d = str(tmp_path / "shared")
        Store.open(d, epoch="auto")
        Store.open(d, epoch="auto")
        recs, _good, _size = scan_journal(d + "/journal.jsonl")
        barriers = [r for r in recs if r.get("barrier")]
        assert [b["ep"] for b in barriers] == [1, 2]
