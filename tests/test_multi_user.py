"""Multi-user integration tier (reference:
integration/tests/cook/test_multi_user.py — quota/share/preemption across
users driven through the REST API), plus a statistical-workload simulator
run at 50k jobs asserting wait-time metrics (reference: simulator/ system
simulator, simulator/README.md).

The REST scenarios run against the in-process HTTP server with a
resource-constrained fake cluster and explicit scheduler stepping so the
fairness outcomes are deterministic; the final scenario drives three users
through REST against a real cook_agentd process (the native transport).
"""

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from cook_tpu.cluster import FakeCluster, FakeHost
from cook_tpu.config import Config
from cook_tpu.rest.api import ApiServer, CookApi
from cook_tpu.sched import Scheduler
from cook_tpu.state import InstanceStatus, JobState, Resources, Store


def hosts(n, cpus=8.0, mem=8192.0):
    return [FakeHost(hostname=f"h{i}", capacity=Resources(cpus=cpus, mem=mem))
            for i in range(n)]


class RestHarness:
    """REST server + scheduler + fake cluster with explicit stepping."""

    def __init__(self, n_hosts=4, cpus=8.0, mem=8192.0, config=None):
        self.store = Store()
        self.cluster = FakeCluster("fake-1", hosts(n_hosts, cpus, mem),
                                   default_task_duration_ms=10**9)
        cfg = config or Config()
        cfg.default_matcher.backend = "cpu"
        self.sched = Scheduler(self.store, cfg, [self.cluster],
                               rank_backend="cpu")
        self.srv = ApiServer(CookApi(self.store, scheduler=self.sched,
                                     admins=["admin"]))
        self.srv.start()
        self.base = f"http://127.0.0.1:{self.srv.port}"

    def rq(self, method, path, user, body=None, ok=True):
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self.base + path, data=data, method=method,
            headers={"Content-Type": "application/json",
                     "X-Cook-User": user})
        try:
            return json.loads(urllib.request.urlopen(req).read())
        except urllib.error.HTTPError as e:
            if ok:
                raise AssertionError(
                    f"{method} {path} -> {e.code}: {e.read()[:300]}")
            return {"_status": e.code, **json.loads(e.read() or b"{}")}

    def submit(self, user, n, cpus=1.0, mem=128.0, **extra):
        jobs = [{"command": "sleep 3600", "cpus": cpus, "mem": mem, **extra}
                for _ in range(n)]
        return self.rq("POST", "/jobs", user, {"jobs": jobs})["jobs"]

    def cycle(self, rebalance=False):
        self.sched.step_rank()
        self.sched.step_match()
        if rebalance:
            self.sched.step_rank()
            self.sched.step_rebalance()
        self.sched.flush_status_updates()

    def running_by_user(self):
        counts = {}
        for job, inst in self.store.running_instances():
            counts[job.user] = counts.get(job.user, 0) + 1
        return counts

    def stop(self):
        self.srv.stop()


@pytest.fixture
def harness():
    h = RestHarness()
    yield h
    h.stop()


class TestShareFairness:
    def test_higher_share_user_gets_proportionally_more(self, harness):
        """DRU fairness: share is the DRU divisor (share.clj:105), so a user
        with 4x the share packs ~4x the tasks before reaching the same DRU."""
        h = harness  # 4 hosts x 8 cpus = 32 slots
        for user, share_cpus in [("alice", 32.0), ("bob", 8.0),
                                 ("carol", 8.0)]:
            h.rq("POST", "/share", "admin",
                 {"user": user,
                  "pools": {"default": {"cpus": share_cpus, "mem": 1e9}}})
        for user in ("alice", "bob", "carol"):
            h.submit(user, 30)
        h.cycle()
        counts = h.running_by_user()
        assert sum(counts.values()) == 32  # cluster saturated
        # alice's 4x share => roughly 4x bob's slots (exact split depends on
        # the interleave; the invariant is a clear dominance, not a formula)
        assert counts["alice"] >= 2 * counts["bob"]
        assert counts["alice"] >= 2 * counts["carol"]
        assert counts["bob"] > 0 and counts["carol"] > 0
        # /usage reflects the live split per user
        usage = h.rq("GET", "/usage?user=alice", "alice")
        assert usage["total_usage"]["jobs"] == counts["alice"]

    def test_share_delete_restores_default(self, harness):
        h = harness
        h.rq("POST", "/share", "admin",
             {"user": "alice", "pools": {"default": {"cpus": 1.0}}})
        got = h.rq("GET", "/share?user=alice", "alice")
        assert got["default"]["cpus"] == 1.0
        h.rq("DELETE", "/share?user=alice", "admin")
        got = h.rq("GET", "/share?user=alice", "alice")
        assert got["default"]["cpus"] != 1.0


class TestQuotaEnforcement:
    def test_count_quota_caps_one_user_not_others(self, harness):
        h = harness
        h.rq("POST", "/quota", "admin",
             {"user": "bob", "pools": {"default": {"count": 2}}})
        h.submit("alice", 10)
        h.submit("bob", 10)
        h.cycle()
        counts = h.running_by_user()
        assert counts["bob"] == 2          # capped by count quota
        assert counts["alice"] >= 10       # unaffected
        # raising the quota releases more of bob's queue next cycle
        h.rq("POST", "/quota", "admin",
             {"user": "bob", "pools": {"default": {"count": 5}}})
        h.cycle()
        assert h.running_by_user()["bob"] == 5

    def test_resource_quota_caps_cpus(self, harness):
        h = harness
        h.rq("POST", "/quota", "admin",
             {"user": "bob", "pools": {"default": {"cpus": 3.0}}})
        h.submit("bob", 10, cpus=1.0)
        h.cycle()
        assert h.running_by_user()["bob"] == 3

    def test_non_admin_cannot_set_quota(self, harness):
        r = harness.rq("POST", "/quota", "mallory",
                       {"user": "mallory",
                        "pools": {"default": {"count": 100}}}, ok=False)
        assert r["_status"] == 403


class TestPreemptionAcrossUsers:
    def test_rebalancer_preempts_hog_for_starved_user(self):
        """User A saturates the cluster; equal-share user B arrives; the
        rebalancer preempts A's highest-DRU tasks mea-culpa so B runs
        (rebalancer.clj:434-533)."""
        cfg = Config()
        cfg.rebalancer.enabled = True
        cfg.rebalancer.safe_dru_threshold = 0.0
        cfg.rebalancer.min_dru_diff = 0.0
        h = RestHarness(n_hosts=2, cpus=4.0, config=cfg)
        try:
            # finite default share: with the infinite default every DRU is 0
            # and no preemption can ever look justified
            h.rq("POST", "/share", "admin",
                 {"user": "default",
                  "pools": {"default": {"cpus": 4.0, "mem": 4096.0}}})
            h.submit("alice", 8)           # 8 slots: cluster full
            h.cycle()
            assert h.running_by_user() == {"alice": 8}
            bob_uuids = h.submit("bob", 4)
            h.cycle(rebalance=True)        # decide victims + reserve hosts
            h.cycle()                      # launch bob onto freed slots
            counts = h.running_by_user()
            assert counts.get("bob", 0) >= 2
            assert counts["alice"] < 8
            # preempted instances are mea-culpa: retries not consumed, jobs
            # back to waiting (not completed-failed)
            mea_culpa = 0
            for j_uuid in {j.uuid for j in h.store.jobs_where(
                    lambda j: j.user == "alice")}:
                job = h.store.job(j_uuid)
                assert job.state is not JobState.COMPLETED
                for tid in job.instances:
                    inst = h.store.instance(tid)
                    if inst is not None and inst.preempted:
                        mea_culpa += 1
                        assert inst.status is InstanceStatus.FAILED
            assert mea_culpa >= 2
            # bob's jobs actually run
            running_bob = sum(
                1 for u in bob_uuids
                for tid in h.store.job(u).instances
                if h.store.instance(tid).status is InstanceStatus.RUNNING)
            assert running_bob >= 2
        finally:
            h.stop()


class TestRealProcessesMultiUser:
    def test_three_users_through_rest_and_native_agent(self, tmp_path):
        from cook_tpu.cluster.remote import (LocalAgentProcess,
                                             RemoteComputeCluster,
                                             native_available)
        if not native_available():
            pytest.skip("C++ toolchain unavailable")
        agent = LocalAgentProcess("mu-node", cpus=8.0, mem=8192.0,
                                  workdir=str(tmp_path))
        store = Store()
        cluster = RemoteComputeCluster(
            "native-1", [("127.0.0.1", agent.port)], store=store)
        cfg = Config()
        cfg.default_matcher.backend = "cpu"
        sched = Scheduler(store, cfg, [cluster], rank_backend="cpu")
        srv = ApiServer(CookApi(store, scheduler=sched, admins=["admin"]))
        srv.start()
        base = f"http://127.0.0.1:{srv.port}"

        def rq(method, path, user, body=None):
            data = json.dumps(body).encode() if body is not None else None
            req = urllib.request.Request(
                base + path, data=data, method=method,
                headers={"Content-Type": "application/json",
                         "X-Cook-User": user})
            return json.loads(urllib.request.urlopen(req).read())

        try:
            uuids = {}
            for i, user in enumerate(("alice", "bob", "carol")):
                marker = tmp_path / f"{user}.out"
                uuids[user] = rq("POST", "/jobs", user, {"jobs": [
                    {"command": f"echo {user} > {marker}",
                     "cpus": 1.0, "mem": 128.0}]})["jobs"][0]
            sched.step_rank()
            sched.step_match()
            deadline = time.time() + 20
            while time.time() < deadline:
                sched.flush_status_updates()
                states = {u: rq("GET", f"/jobs/{uid}", u)["state"]
                          for u, uid in uuids.items()}
                if all(s == "success" for s in states.values()):
                    break
                time.sleep(0.1)
            assert all(s == "success" for s in states.values()), states
            for user, uid in uuids.items():
                j = rq("GET", f"/jobs/{uid}", user)
                assert any(i["status"] == "success" for i in j["instances"])
                assert (tmp_path / f"{user}.out").read_text().strip() == user
        finally:
            srv.stop()
            cluster.shutdown()
            agent.stop()


@pytest.mark.slow
class TestStatisticalWorkloadAtScale:
    def test_50k_jobs_wait_time_metrics(self):
        """Statistical workload (Poisson arrivals, lognormal durations) at
        50k jobs through the faster-than-real-time simulator; asserts the
        wait-time metrics the reference's system simulator reports
        (simulator/README.md) and that high-priority interactive work waits
        no longer than batch work."""
        from cook_tpu.sim.simulator import Simulator, load_hosts, load_trace
        from cook_tpu.sim.workload import generate_hosts, generate_trace

        spec = {
            "seed": 11,
            "horizon_ms": 600_000,  # 10 virtual minutes of arrivals
            "user_classes": [
                {"name": "batch", "users": 40,
                 "arrival_rate_per_min": 120.0,   # 40*120*10 = 48k jobs
                 "duration_ms": {"dist": "lognormal", "mu": 9.8,
                                 "sigma": 0.4},
                 "cpus": {"dist": "choice", "values": [1, 2],
                          "weights": [0.8, 0.2]},
                 "mem": {"dist": "uniform", "low": 128, "high": 512},
                 "priority": {"dist": "constant", "value": 50}},
                {"name": "interactive", "users": 10,
                 "arrival_rate_per_min": 30.0,    # +3k jobs
                 "duration_ms": {"dist": "exponential", "scale": 10_000},
                 "cpus": 1.0, "mem": 128.0,
                 "priority": {"dist": "constant", "value": 90}},
            ],
        }
        trace_entries = generate_trace(spec)
        assert len(trace_entries) >= 50_000
        trace = load_trace(trace_entries)
        sim_hosts = load_hosts(generate_hosts(400, cpus=64.0, mem=262144.0))
        sim = Simulator(trace, sim_hosts, backend="tpu",
                        rank_interval_ms=10_000, match_interval_ms=5_000,
                        rebalance_interval_ms=10**9)
        res = sim.run()
        s = res.summary()
        assert s["jobs_completed"] == s["jobs_total"] >= 50_000
        assert s["wait_time_p50_s"] >= 0.0
        assert np.isfinite(s["wait_time_p99_s"])
        assert s["placements"] >= 50_000
        # per-class wait comparison from task records (priority 90 class
        # sorts ahead within a user's queue AND its users run less, so its
        # median wait must not exceed batch's)
        waits = {"batch": [], "interactive": []}
        for rec in res.task_records:
            cls = "interactive" if rec["user"].startswith("interactive") \
                else "batch"
            job = sim.store.job(rec["job"])
            if rec["start"]:
                waits[cls].append(rec["start"] - job.submit_time_ms)
        assert waits["batch"] and waits["interactive"]
        p50 = {k: float(np.percentile(np.asarray(v), 50))
               for k, v in waits.items()}
        assert p50["interactive"] <= p50["batch"] + 1e-9, p50


@pytest.mark.slow
class TestRebalancerChurn:
    def test_preemption_churn_at_thousands_of_jobs(self):
        """Tight capacity + an over-share user + periodic rebalancing at
        a few thousand jobs: preemptions happen, preempted work is mea-culpa retried,
        and every job still completes (the reference's multi-user
        preemption scenarios, test_multi_user.py, at simulator scale)."""
        from cook_tpu.config import Config, RebalancerConfig
        from cook_tpu.sim.simulator import Simulator, load_hosts, load_trace
        from cook_tpu.sim.workload import generate_hosts, generate_trace

        spec = {
            "seed": 23,
            "horizon_ms": 300_000,
            "user_classes": [
                # one hog class front-loads the cluster
                {"name": "hog", "users": 2, "arrival_rate_per_min": 120.0,
                 "duration_ms": {"dist": "constant", "value": 120_000},
                 "cpus": 4.0, "mem": 512.0,
                 "priority": {"dist": "constant", "value": 50}},
                {"name": "fair", "users": 20,
                 "arrival_rate_per_min": 18.0,
                 "duration_ms": {"dist": "exponential", "scale": 15_000},
                 "cpus": 1.0, "mem": 128.0,
                 "priority": {"dist": "constant", "value": 50}},
            ],
        }
        trace_entries = generate_trace(spec)
        assert len(trace_entries) >= 2_000
        cfg = Config(rebalancer=RebalancerConfig(
            enabled=True, safe_dru_threshold=0.0, min_dru_diff=0.0,
            max_preemption=32))
        sim = Simulator(load_trace(trace_entries),
                        load_hosts(generate_hosts(40, cpus=16.0,
                                                  mem=16384.0)),
                        config=cfg, backend="tpu",
                        rank_interval_ms=10_000, match_interval_ms=5_000,
                        rebalance_interval_ms=30_000)
        # finite default share so DRU comparisons are meaningful
        sim.store.set_share("default", "default",
                            {"cpus": 32.0, "mem": 32768.0})
        res = sim.run()
        s = res.summary()
        assert s["jobs_completed"] == s["jobs_total"]
        assert s["preemptions"] > 0, "churn scenario produced no preemptions"
        # preempted instances are mea-culpa (never consume retries), so
        # preempted jobs completed anyway — which jobs_completed proves;
        # spot-check a preempted record exists and is marked
        preempted = [r for r in res.task_records if r["preempted"]]
        assert preempted
