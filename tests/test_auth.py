"""Pluggable auth chain (reference: spnego/basic/open composition,
components.clj:266-284; rest/spnego.clj; rest/basic_auth.clj)."""

import time
import urllib.error
import urllib.request

import pytest

from cook_tpu.client import JobClient, JobClientError
from cook_tpu.rest.api import ApiServer, CookApi
from cook_tpu.rest.auth import (
    AuthChain,
    AuthError,
    BasicAuthenticator,
    HeaderTrustAuthenticator,
    HmacTokenAuthenticator,
)
from cook_tpu.state import Store


class TestSchemes:
    def test_header_trust(self):
        a = HeaderTrustAuthenticator()
        assert a.authenticate({"X-Cook-User": "alice"}) == "alice"
        assert a.authenticate({}) is None

    def test_basic(self):
        import base64
        a = BasicAuthenticator({"alice": "pw"})
        hdr = {"Authorization": "Basic "
               + base64.b64encode(b"alice:pw").decode()}
        assert a.authenticate(hdr) == "alice"
        bad = {"Authorization": "Basic "
               + base64.b64encode(b"alice:nope").decode()}
        with pytest.raises(AuthError):
            a.authenticate(bad)
        assert a.authenticate({}) is None  # no credentials -> chain moves on

    def test_token_roundtrip_and_expiry(self):
        a = HmacTokenAuthenticator("secret", default_ttl_s=3600)
        tok = a.mint("alice")
        assert a.authenticate({"Authorization": f"Bearer {tok}"}) == "alice"
        assert a.authenticate({"Authorization": f"Negotiate {tok}"}) == "alice"
        expired = a.mint("alice", ttl_s=-1)
        with pytest.raises(AuthError, match="expired"):
            a.authenticate({"Authorization": f"Bearer {expired}"})

    def test_token_tamper_and_wrong_secret(self):
        a = HmacTokenAuthenticator("secret")
        other = HmacTokenAuthenticator("other-secret")
        tok = other.mint("alice")
        with pytest.raises(AuthError, match="signature"):
            a.authenticate({"Authorization": f"Bearer {tok}"})
        with pytest.raises(AuthError):
            a.authenticate({"Authorization": "Bearer not-base64!!"})

    def test_username_with_colons_survives(self):
        a = HmacTokenAuthenticator("s")
        tok = a.mint("svc:job:runner")
        assert a.authenticate({"Authorization": f"Bearer {tok}"}) \
            == "svc:job:runner"

    def test_chain_order_and_mandatory(self):
        chain = AuthChain([HmacTokenAuthenticator("s"),
                           HeaderTrustAuthenticator()])
        assert chain.authenticate({"X-Cook-User": "bob"}) == "bob"
        with pytest.raises(AuthError, match="authentication required"):
            chain.authenticate({})


class TestRestIntegration:
    def _serve(self, **kw):
        srv = ApiServer(CookApi(Store(), **kw))
        srv.start()
        return srv

    def test_token_auth_end_to_end(self):
        minter = HmacTokenAuthenticator("topsecret")
        srv = self._serve(authenticators=[minter])
        try:
            ok = JobClient(f"http://127.0.0.1:{srv.port}",
                           token=minter.mint("alice"))
            [u] = ok.submit([{"command": "true", "cpus": 1.0, "mem": 10.0}])
            assert ok.job(u)["user"] == "alice"
            # no credentials -> 401 with a challenge
            with pytest.raises(JobClientError) as ei:
                JobClient(f"http://127.0.0.1:{srv.port}").jobs()
            assert ei.value.status == 401
            # the spoofable header is NOT accepted when a chain is set
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/jobs",
                headers={"X-Cook-User": "mallory"})
            with pytest.raises(urllib.error.HTTPError) as he:
                urllib.request.urlopen(req)
            assert he.value.code == 401
            assert he.value.headers.get("WWW-Authenticate") == "Negotiate"
        finally:
            srv.stop()

    def test_mixed_chain_basic_fallback(self):
        chain = [HmacTokenAuthenticator("s"),
                 BasicAuthenticator({"bob": "hunter2"})]
        srv = self._serve(authenticators=chain)
        try:
            c = JobClient(f"http://127.0.0.1:{srv.port}",
                          basic_auth=("bob", "hunter2"))
            [u] = c.submit([{"command": "true", "cpus": 1.0, "mem": 10.0}])
            assert c.job(u)["user"] == "bob"
            bad = JobClient(f"http://127.0.0.1:{srv.port}",
                            basic_auth=("bob", "wrong"))
            with pytest.raises(JobClientError) as ei:
                bad.jobs()
            assert ei.value.status == 401
        finally:
            srv.stop()


class TestGssapiAuthenticator:
    """SPNEGO slot (reference: rest/spnego.clj) — the validator drives a
    GSSAPI module; tests inject a fake (no KDC in this image). GSS tokens
    are ASN.1-framed (first byte 0x60)."""

    VALID = b"\x60" + b"valid-krb-token"

    class FakeCtx:
        def __init__(self, creds, usage):
            assert usage == "accept"
            self.creds = creds
            self.complete = False
            self.initiator_name = None

        def step(self, token):
            if token != TestGssapiAuthenticator.VALID:
                raise ValueError("defective token")
            self.complete = True
            self.initiator_name = "alice@EXAMPLE.COM"
            return b"acceptor-final-token"

    def _fake_module(self, recorded):
        class NameType:
            hostbased_service = "hostbased"

        class Fake:
            pass
        fake = Fake()
        fake.NameType = NameType

        def name(service, name_type):
            recorded["spn"] = (service, name_type)
            return ("name", service)

        def credentials(name, usage):
            recorded["creds"] = (name, usage)
            return ("creds", name)
        fake.Name = name
        fake.Credentials = credentials
        fake.SecurityContext = \
            lambda creds, usage: self.FakeCtx(creds, usage)
        return fake

    def _auth(self, recorded=None):
        from cook_tpu.rest.auth import GssapiAuthenticator
        return GssapiAuthenticator(
            gssapi_module=self._fake_module(
                recorded if recorded is not None else {}))

    def test_valid_ticket_maps_principal_to_user(self):
        import base64
        recorded = {}
        a = self._auth(recorded)
        # acceptor creds acquired ONCE at construction, for the service SPN
        assert recorded["spn"] == ("HTTP", "hostbased")
        assert recorded["creds"][1] == "accept"
        tok = base64.b64encode(self.VALID).decode()
        assert a.authenticate({"Authorization": f"Negotiate {tok}"}) == \
            "alice"

    def test_mutual_auth_token_surfaces_in_response_headers(self):
        import base64
        a = self._auth()
        tok = base64.b64encode(self.VALID).decode()
        respond = {}
        assert a.authenticate({"Authorization": f"Negotiate {tok}"},
                              respond) == "alice"
        scheme, _, out = respond["WWW-Authenticate"].partition(" ")
        assert scheme == "Negotiate"
        assert base64.b64decode(out) == b"acceptor-final-token"

    def test_bad_gss_token_rejected_generically(self):
        import base64

        import pytest

        from cook_tpu.rest.auth import AuthError
        a = self._auth()
        tok = base64.b64encode(b"\x60forged").decode()
        with pytest.raises(AuthError) as e:
            a.authenticate({"Authorization": f"Negotiate {tok}"})
        assert e.value.challenge == "Negotiate"
        # GSS status detail is logged, not echoed to the caller
        assert "defective" not in e.value.message

    def test_non_negotiate_requests_pass_through(self):
        a = self._auth()
        assert a.authenticate({}) is None
        assert a.authenticate({"Authorization": "Basic xyz"}) is None

    def test_non_gss_negotiate_token_passes_to_later_schemes(self):
        """An HMAC ticket under the same Negotiate header is NOT ASN.1
        framed; the GSSAPI validator must pass it through so the chained
        HmacTokenAuthenticator (the KDC-free stand-in) can accept it."""
        from cook_tpu.rest.auth import AuthChain, HmacTokenAuthenticator
        hmac_auth = HmacTokenAuthenticator("secret")
        chain = AuthChain([self._auth(), hmac_auth])
        ticket = hmac_auth.mint("carol")
        assert chain.authenticate(
            {"Authorization": f"Negotiate {ticket}"}) == "carol"

    def test_chain_integration(self):
        """GSSAPI first, basic fallback — the reference's composed
        authorization middleware shape."""
        import base64

        from cook_tpu.rest.auth import AuthChain, BasicAuthenticator
        chain = AuthChain([self._auth(),
                           BasicAuthenticator({"bob": "pw"})])
        tok = base64.b64encode(self.VALID).decode()
        assert chain.authenticate(
            {"Authorization": f"Negotiate {tok}"}) == "alice"
        basic = base64.b64encode(b"bob:pw").decode()
        assert chain.authenticate(
            {"Authorization": f"Basic {basic}"}) == "bob"

    def test_missing_gssapi_package_fails_construction(self, monkeypatch):
        import sys

        import pytest

        from cook_tpu.rest.auth import GssapiAuthenticator
        # force the import to fail even where python-gssapi is installed
        monkeypatch.setitem(sys.modules, "gssapi", None)
        with pytest.raises(RuntimeError, match="gssapi"):
            GssapiAuthenticator()

    def test_daemon_config_builds_the_chain(self, monkeypatch):
        """The deployment path reaches the SPNEGO slot: gssapi_service in
        the daemon config constructs the validator (fail-fast at boot when
        the package/keytab are absent)."""
        import sys

        from cook_tpu.daemon import build_authenticators
        from cook_tpu.rest.auth import (BasicAuthenticator,
                                        GssapiAuthenticator,
                                        HmacTokenAuthenticator)
        fake = self._fake_module({})
        monkeypatch.setitem(sys.modules, "gssapi", fake)
        chain = build_authenticators({
            "gssapi_service": "HTTP",
            "hmac_ticket_secret": "s3cret",
            "basic_auth_users": {"bob": "pw"}})
        assert [type(a) for a in chain] == [
            GssapiAuthenticator, HmacTokenAuthenticator, BasicAuthenticator]
        assert build_authenticators({}) is None
