"""Pluggable auth chain (reference: spnego/basic/open composition,
components.clj:266-284; rest/spnego.clj; rest/basic_auth.clj)."""

import time
import urllib.error
import urllib.request

import pytest

from cook_tpu.client import JobClient, JobClientError
from cook_tpu.rest.api import ApiServer, CookApi
from cook_tpu.rest.auth import (
    AuthChain,
    AuthError,
    BasicAuthenticator,
    HeaderTrustAuthenticator,
    HmacTokenAuthenticator,
)
from cook_tpu.state import Store


class TestSchemes:
    def test_header_trust(self):
        a = HeaderTrustAuthenticator()
        assert a.authenticate({"X-Cook-User": "alice"}) == "alice"
        assert a.authenticate({}) is None

    def test_basic(self):
        import base64
        a = BasicAuthenticator({"alice": "pw"})
        hdr = {"Authorization": "Basic "
               + base64.b64encode(b"alice:pw").decode()}
        assert a.authenticate(hdr) == "alice"
        bad = {"Authorization": "Basic "
               + base64.b64encode(b"alice:nope").decode()}
        with pytest.raises(AuthError):
            a.authenticate(bad)
        assert a.authenticate({}) is None  # no credentials -> chain moves on

    def test_token_roundtrip_and_expiry(self):
        a = HmacTokenAuthenticator("secret", default_ttl_s=3600)
        tok = a.mint("alice")
        assert a.authenticate({"Authorization": f"Bearer {tok}"}) == "alice"
        assert a.authenticate({"Authorization": f"Negotiate {tok}"}) == "alice"
        expired = a.mint("alice", ttl_s=-1)
        with pytest.raises(AuthError, match="expired"):
            a.authenticate({"Authorization": f"Bearer {expired}"})

    def test_token_tamper_and_wrong_secret(self):
        a = HmacTokenAuthenticator("secret")
        other = HmacTokenAuthenticator("other-secret")
        tok = other.mint("alice")
        with pytest.raises(AuthError, match="signature"):
            a.authenticate({"Authorization": f"Bearer {tok}"})
        with pytest.raises(AuthError):
            a.authenticate({"Authorization": "Bearer not-base64!!"})

    def test_username_with_colons_survives(self):
        a = HmacTokenAuthenticator("s")
        tok = a.mint("svc:job:runner")
        assert a.authenticate({"Authorization": f"Bearer {tok}"}) \
            == "svc:job:runner"

    def test_chain_order_and_mandatory(self):
        chain = AuthChain([HmacTokenAuthenticator("s"),
                           HeaderTrustAuthenticator()])
        assert chain.authenticate({"X-Cook-User": "bob"}) == "bob"
        with pytest.raises(AuthError, match="authentication required"):
            chain.authenticate({})


class TestRestIntegration:
    def _serve(self, **kw):
        srv = ApiServer(CookApi(Store(), **kw))
        srv.start()
        return srv

    def test_token_auth_end_to_end(self):
        minter = HmacTokenAuthenticator("topsecret")
        srv = self._serve(authenticators=[minter])
        try:
            ok = JobClient(f"http://127.0.0.1:{srv.port}",
                           token=minter.mint("alice"))
            [u] = ok.submit([{"command": "true", "cpus": 1.0, "mem": 10.0}])
            assert ok.job(u)["user"] == "alice"
            # no credentials -> 401 with a challenge
            with pytest.raises(JobClientError) as ei:
                JobClient(f"http://127.0.0.1:{srv.port}").jobs()
            assert ei.value.status == 401
            # the spoofable header is NOT accepted when a chain is set
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/jobs",
                headers={"X-Cook-User": "mallory"})
            with pytest.raises(urllib.error.HTTPError) as he:
                urllib.request.urlopen(req)
            assert he.value.code == 401
            assert he.value.headers.get("WWW-Authenticate") == "Negotiate"
        finally:
            srv.stop()

    def test_mixed_chain_basic_fallback(self):
        chain = [HmacTokenAuthenticator("s"),
                 BasicAuthenticator({"bob": "hunter2"})]
        srv = self._serve(authenticators=chain)
        try:
            c = JobClient(f"http://127.0.0.1:{srv.port}",
                          basic_auth=("bob", "hunter2"))
            [u] = c.submit([{"command": "true", "cpus": 1.0, "mem": 10.0}])
            assert c.job(u)["user"] == "bob"
            bad = JobClient(f"http://127.0.0.1:{srv.port}",
                            basic_auth=("bob", "wrong"))
            with pytest.raises(JobClientError) as ei:
                bad.jobs()
            assert ei.value.status == 401
        finally:
            srv.stop()
