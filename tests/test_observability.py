"""Observability layer: flight recorder, Chrome trace export, device
telemetry (recompile/transfer counters), Prometheus exposition golden
parse, SLO burn rates, and the span-catalog doc check
(docs/OBSERVABILITY.md).

Ordering note: the ``system`` fixture (one fused-cycle simulator run +
live API server) is module-scoped — the classes that inspect its
recorder/tracer state (TestFlightRecorder, TestDebugCli) run before the
classes that reset global state for isolation (_reset at test start).
"""

import json
import re
import urllib.error
import urllib.request
from pathlib import Path

import pytest

from cook_tpu.utils.flight import recorder
from cook_tpu.utils.metrics import LATENCY_BUCKETS, registry
from cook_tpu.utils.tracing import span, tracer

REPO = Path(__file__).resolve().parent.parent


def _reset():
    tracer.reset()
    registry.reset()
    recorder.reset()


# ---------------------------------------------------------------------------
# Prometheus text-format golden parse
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>.*)\})?'
    r' (?P<value>[^ ]+)$')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(value: str) -> str:
    out, i = [], 0
    while i < len(value):
        c = value[i]
        if c == "\\" and i + 1 < len(value):
            nxt = value[i + 1]
            out.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt, c + nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def parse_prometheus(text: str):
    """Strict mini-parser for the exposition format: every line must be a
    well-formed sample; returns [(name, {label: value}, float)]."""
    samples = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"malformed exposition line: {line!r}"
        labels = {}
        raw = m.group("labels")
        if raw:
            consumed = _LABEL_RE.sub("", raw).replace(",", "").strip()
            assert consumed == "", f"unparsed label text {consumed!r} " \
                                   f"in line {line!r}"
            labels = {k: _unescape(v) for k, v in _LABEL_RE.findall(raw)}
        samples.append((m.group("name"), labels, float(m.group("value"))))
    return samples


# ---------------------------------------------------------------------------
# End-to-end: simulator -> flight recorder -> REST -> Chrome trace
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def system():
    """One small fused-cycle simulator run with a live API server over
    its store (module-scoped: the run compiles the fused cycle once)."""
    from cook_tpu.rest import ApiServer, CookApi
    from cook_tpu.sim.simulator import (
        Simulator,
        generate_example_hosts,
        generate_example_trace,
        load_hosts,
        load_trace,
    )
    _reset()
    sim = Simulator(load_trace(generate_example_trace(20, seed=3)),
                    load_hosts(generate_example_hosts(3)))
    result = sim.run()
    assert result.placements > 0
    sim.result = result
    api = CookApi(sim.store, scheduler=sim.scheduler)
    server = ApiServer(api)
    server.start()
    yield sim, server
    server.stop()


def _get_json(server, path):
    return json.load(urllib.request.urlopen(server.url + path))


class TestFlightRecorder:
    def test_every_cycle_recorded(self, system):
        sim, _server = system
        records = recorder.recent(limit=500)
        fused = [r for r in records if r["kind"] == "fused"]
        # one record per driven fused cycle
        assert len(fused) == len(sim.result.match_wall_ms)
        assert all(r["trace_id"] for r in fused)
        assert all(r["duration_ms"] > 0 for r in fused)

    def test_placed_cycle_has_phases_and_counts(self, system):
        _sim, _server = system
        placed = [r for r in recorder.recent(limit=500)
                  if r["kind"] == "fused" and r["jobs_placed"] > 0]
        assert placed
        r = placed[0]
        for phase in ("rank", "match", "launch"):
            assert r["phases_ms"].get(phase, 0.0) > 0.0, (phase, r)
        assert r["jobs_considered"] >= r["jobs_placed"] > 0
        assert r["h2d_bytes"] > 0 and r["d2h_bytes"] > 0

    def test_simulator_emits_flight_summary(self, system):
        sim, _server = system
        flight = sim.result.summary()["flight"]
        assert flight["cycles"] >= len(sim.result.match_wall_ms)
        assert flight["jobs_placed"] == sim.result.placements
        assert flight["by_kind"].get("fused")

    def test_debug_cycles_endpoint(self, system):
        _sim, server = system
        body = _get_json(server, "/debug/cycles?limit=5")
        assert len(body["cycles"]) == 5
        doc = body["cycles"][-1]
        for field in ("seq", "kind", "trace_id", "duration_ms", "phases_ms",
                      "skip_reasons", "recompiles", "h2d_bytes",
                      "d2h_bytes", "sync_wait_ms"):
            assert field in doc

    def test_debug_trace_is_valid_chrome_trace(self, system):
        _sim, server = system
        placed = [r for r in recorder.recent(limit=500)
                  if r["kind"] == "fused" and r["jobs_placed"] > 0]
        trace = _get_json(server,
                          "/debug/trace?trace_id=" + placed[0]["trace_id"])
        # schema check: the trace-event JSON Object Format
        assert isinstance(trace["traceEvents"], list) and trace["traceEvents"]
        assert trace["displayTimeUnit"] in ("ms", "ns")
        ts = []
        for ev in trace["traceEvents"]:
            assert set(("name", "cat", "ph", "ts", "dur", "pid",
                        "tid")) <= set(ev)
            assert ev["ph"] == "X"
            assert isinstance(ev["name"], str) and ev["name"]
            assert ev["dur"] > 0
            assert isinstance(ev.get("args", {}), dict)
            ts.append(ev["ts"])
        assert ts == sorted(ts)
        names = {ev["name"] for ev in trace["traceEvents"]}
        # the nested spans cover the rank, match, and launch phases
        assert {"cycle", "cycle.rank", "cycle.match",
                "cycle.launch"} <= names
        # valid JSON round trip
        json.loads(json.dumps(trace))

    def test_debug_trace_error_paths(self, system):
        _sim, server = system
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(server.url + "/debug/trace")
        assert e.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                server.url + "/debug/trace?trace_id=deadbeef00000000")
        assert e.value.code == 404

    def test_live_server_metrics_parse(self, system):
        _sim, server = system
        text = urllib.request.urlopen(server.url + "/metrics").read().decode()
        samples = parse_prometheus(text)
        names = {n for n, _l, _v in samples}
        assert any(n.startswith("cook_span_duration_seconds") for n in names)
        assert any(n.startswith("cook_cycle_duration_seconds")
                   for n in names)


class TestDebugCli:
    def test_cycles_and_trace_subcommands(self, system, capsys):
        from cook_tpu.cli.main import main as cli_main
        _sim, server = system
        assert cli_main(["--url", server.url, "debug", "cycles",
                         "--limit", "3"]) == 0
        body = json.loads(capsys.readouterr().out)
        assert len(body["cycles"]) == 3
        # trace with no id resolves to the newest cycle's trace
        assert cli_main(["--url", server.url, "debug", "trace"]) == 0
        trace = json.loads(capsys.readouterr().out)
        assert trace["traceEvents"]


# ---------------------------------------------------------------------------
# Prometheus exposition details (isolated registry state)
# ---------------------------------------------------------------------------

class TestPrometheusExposition:
    def test_label_escaping_round_trips(self):
        _reset()
        nasty = 'no "fit"\\ at all\nsecond line'
        registry.counter_inc("cook_test_skips", 2.0, {"reason": nasty})
        text = registry.expose()
        samples = parse_prometheus(text)
        hits = [(n, lbl, v) for n, lbl, v in samples
                if n == "cook_test_skips_total"]
        assert len(hits) == 1
        _n, labels, value = hits[0]
        assert labels["reason"] == nasty
        assert value == 2.0
        # raw text is single-line per sample: the newline was escaped
        assert "no \\\"fit\\\"" in text

    def test_histogram_buckets_monotone_and_inf_equals_count(self):
        _reset()
        for v in (0.003, 0.02, 0.7, 9.0, 42.0):
            registry.observe("cook_test_hist", v, {"pool": "p"})
        for v in (2.0, 400.0):
            registry.observe("cook_test_wait", v, {"pool": "p"},
                             buckets=LATENCY_BUCKETS)
        samples = parse_prometheus(registry.expose())
        by_name = {}
        for n, lbl, v in samples:
            by_name.setdefault(n, []).append((lbl, v))
        for base, total in (("cook_test_hist", 5), ("cook_test_wait", 2)):
            buckets = by_name[base + "_bucket"]
            # exposition order preserves the bound ladder; counts must be
            # non-decreasing and the +Inf bucket must equal _count
            counts = [v for _lbl, v in buckets]
            assert counts == sorted(counts)
            inf = [v for lbl, v in buckets if lbl["le"] == "+Inf"]
            assert inf == [total]
            (_, count), = by_name[base + "_count"]
            assert count == total
            # le label values parse as floats (except +Inf)
            for lbl, _v in buckets:
                if lbl["le"] != "+Inf":
                    float(lbl["le"])


# ---------------------------------------------------------------------------
# Device telemetry: recompiles tagged to cycle + /metrics
# ---------------------------------------------------------------------------

class TestRecompileTelemetry:
    def test_shape_change_recompile_counted_and_tagged(self):
        import jax.numpy as jnp

        from cook_tpu.ops import MatchInputs, greedy_match_kernel
        _reset()

        def inputs(j, h):
            return MatchInputs(
                job_res=jnp.ones((j, 4)),
                constraint_mask=jnp.ones((j, h), bool),
                avail=jnp.full((h, 4), 100.0),
                capacity=jnp.full((h, 4), 100.0),
                valid=jnp.ones(j, bool))

        with recorder.cycle(kind="fused") as rec:
            greedy_match_kernel(inputs(9, 4))
            before = rec.recompiles.get("match.greedy", 0)
            # shape change forces a fresh trace+compile
            greedy_match_kernel(inputs(17, 6))
            assert rec.recompiles["match.greedy"] == before + 1
        # the owning cycle's record is tagged...
        doc = recorder.recent(limit=1)[0]
        assert doc["recompiles"]["match.greedy"] >= 1
        # ...and /metrics carries the per-kernel counter
        samples = parse_prometheus(registry.expose())
        hits = [v for n, lbl, v in samples
                if n == "cook_jit_compile_total"
                and lbl.get("kernel") == "match.greedy"]
        assert hits and hits[0] >= 1

    def test_transfer_and_sync_wait_flow_to_record(self):
        from cook_tpu.ops import telemetry
        _reset()
        with recorder.cycle(kind="fused") as rec:
            telemetry.count_transfer("h2d", 1000)
            telemetry.count_transfer("d2h", 500)
            with telemetry.sync_wait("fused.fetch"):
                pass
        assert rec.h2d_bytes == 1000 and rec.d2h_bytes == 500
        assert rec.sync_wait_ms >= 0.0
        samples = parse_prometheus(registry.expose())
        directions = {lbl["direction"]: v for n, lbl, v in samples
                      if n == "cook_device_transfer_bytes_total"}
        assert directions == {"h2d": 1000.0, "d2h": 500.0}

    def test_nested_cycle_joins_enclosing_record(self):
        _reset()
        with recorder.cycle(kind="fused") as outer:
            with recorder.cycle(kind="match") as inner:
                assert inner is outer
        assert [r["kind"] for r in recorder.recent()] == ["fused"]


# ---------------------------------------------------------------------------
# Tracer: contextvars propagation + recent() filter
# ---------------------------------------------------------------------------

class TestTracerContext:
    def test_copied_context_keeps_cycle_trace(self):
        import contextvars
        import threading
        _reset()
        seen = {}

        def worker():
            with span("cluster.launch-tasks", cluster="c"):
                seen["trace"] = tracer.current().trace_id

        with span("cycle", kind="fused") as root:
            t = threading.Thread(target=contextvars.copy_context().run,
                                 args=(worker,))
            t.start()
            t.join()
        assert seen["trace"] == root.trace_id
        docs = tracer.traces(root.trace_id)
        assert {d["span"] for d in docs} == {"cycle",
                                             "cluster.launch-tasks"}

    def test_recent_name_filter_honors_limit(self):
        _reset()
        for i in range(20):
            with span("rank.pool", pool=f"p{i}"):
                pass
            with span("rank.cycle"):
                pass
        docs = tracer.recent(limit=3, name="rank.pool")
        assert [d["pool"] for d in docs] == ["p17", "p18", "p19"]
        assert all(d["span"] == "rank.pool" for d in docs)


# ---------------------------------------------------------------------------
# SLO layer
# ---------------------------------------------------------------------------

class TestSloLayer:
    def test_queue_latency_burn_rate(self):
        from cook_tpu.config import Config
        from cook_tpu.sched.monitor import Monitor
        from cook_tpu.state import Job, Pool, Resources, Store, new_uuid
        _reset()

        store = Store()
        store.put_pool(Pool(name="default"))
        now = store.clock()
        cfg = Config()
        cfg.slo.queue_latency_objective_s = 60.0
        cfg.slo.error_budget = 0.1
        # two pending jobs: one fresh, one 10 minutes old
        store.create_jobs([
            Job(uuid=new_uuid(), user="u", command="x",
                resources=Resources(cpus=1, mem=10),
                submit_time_ms=now - 600_000),
            Job(uuid=new_uuid(), user="u", command="x",
                resources=Resources(cpus=1, mem=10),
                submit_time_ms=now),
        ])
        Monitor(store, config=cfg).sweep()
        samples = parse_prometheus(registry.expose())
        gauges = {(n, lbl.get("slo"), lbl.get("pool")): v
                  for n, lbl, v in samples}
        assert gauges[("cook_slo_objective_seconds", "queue-latency",
                       "default")] == 60.0
        assert gauges[("cook_slo_breach_ratio", "queue-latency",
                       "default")] == 0.5
        assert gauges[("cook_slo_burn_rate", "queue-latency",
                       "default")] == pytest.approx(5.0)
        # the sampled age histogram exists with latency-scale buckets
        ages = [lbl["le"] for n, lbl, _v in samples
                if n == "cook_queue_age_seconds_bucket"]
        assert "600.0" in ages

    def test_cycle_duration_burn_rate_from_flight_recorder(self):
        import time as _time

        from cook_tpu.config import Config
        from cook_tpu.sched.monitor import Monitor
        from cook_tpu.state import Store
        _reset()

        cfg = Config()
        cfg.slo.cycle_duration_objective_s = 0.005
        cfg.slo.error_budget = 0.5
        with recorder.cycle(kind="fused"):
            _time.sleep(0.02)       # breaches the 5ms objective
        with recorder.cycle(kind="fused"):
            pass                    # within objective
        Monitor(Store(), config=cfg).sweep()
        samples = parse_prometheus(registry.expose())
        burn = [v for n, lbl, v in samples
                if n == "cook_slo_burn_rate"
                and lbl.get("slo") == "cycle-duration"]
        assert burn == [pytest.approx(1.0)]


# ---------------------------------------------------------------------------
# Docs-registry completeness: spans / metrics / CycleRecord fields /
# fault points.  ONE static extractor (cook_tpu/analysis/registry.py) is
# shared by these checks, the `cs lint` registry pass, and
# tests/test_analysis.py's self-lint golden — the harvesting rules can't
# drift between the test and the CLI (docs/ANALYSIS.md).
# ---------------------------------------------------------------------------

def _registry_diffs():
    from cook_tpu.analysis import registry as _registry
    return _registry.diff_registries(REPO / "cook_tpu", REPO / "docs")


def test_span_catalog_documented():
    from cook_tpu.analysis import registry as _registry
    names = _registry.harvest_spans(REPO / "cook_tpu")
    assert names, "no spans found — did the span helper get renamed?"
    missing = _registry_diffs()["span"]
    assert not missing, (
        f"spans missing from docs/OBSERVABILITY.md: {sorted(missing)}")


def test_metric_catalog_documented():
    """Every metric NAME emitted anywhere in cook_tpu/ must be registered
    in docs/OBSERVABILITY.md — the check fails on unregistered names, not
    just on missing known ones, so a new metric cannot ship
    undocumented."""
    from cook_tpu.analysis import registry as _registry
    names = _registry.harvest_metrics(REPO / "cook_tpu")
    assert len(names) > 20, f"metric scan looks broken: {sorted(names)}"
    missing = _registry_diffs()["metric"]
    assert not missing, (
        f"metrics missing from docs/OBSERVABILITY.md: {sorted(missing)}")


def test_cycle_record_fields_documented():
    """Every exported CycleRecord field (the /debug/cycles schema) must
    be registered in docs/OBSERVABILITY.md."""
    from cook_tpu.analysis import registry as _registry
    assert len(_registry.cycle_record_fields()) >= 15
    missing = _registry_diffs()["cycle-field"]
    assert not missing, (
        f"CycleRecord fields missing from docs/OBSERVABILITY.md: "
        f"{sorted(missing)}")


def test_fault_point_catalog_documented():
    """Every fault point consulted/armed in cook_tpu/ must be registered
    in docs/ROBUSTNESS.md's failure-mode matrix (this is the check that
    surfaced the undocumented `delta.extract`/`delta.apply` points)."""
    from cook_tpu.analysis import registry as _registry
    names = _registry.harvest_fault_points(REPO / "cook_tpu")
    assert len(names) >= 10, f"fault scan looks broken: {sorted(names)}"
    missing = _registry_diffs()["fault-point"]
    assert not missing, (
        f"fault points missing from docs/ROBUSTNESS.md: "
        f"{sorted(missing)}")
