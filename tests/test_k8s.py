"""Kubernetes-style backend tests: controller state machine, offer synthesis,
autoscaling, full scheduler integration (reference test tier:
scheduler/test/cook/test/kubernetes/*)."""

import pytest

from cook_tpu.cluster.k8s import (
    CookExpected,
    FakeKubernetesApi,
    FakeNode,
    FakePod,
    KubernetesCluster,
)
from cook_tpu.config import Config
from cook_tpu.sched import Scheduler
from cook_tpu.state import (
    InstanceStatus,
    Job,
    JobState,
    Reasons,
    Resources,
    Store,
    new_uuid,
)


def make_job(user="alice", cpus=1.0, mem=100.0, **kw):
    return Job(uuid=new_uuid(), user=user, command="x",
               resources=Resources(cpus=cpus, mem=mem), **kw)


def k8s_system(n_nodes=2, cpus=8.0, mem=8192.0):
    api = FakeKubernetesApi()
    for i in range(n_nodes):
        api.add_node(FakeNode(name=f"node{i}", cpus=cpus, mem=mem))
    store = Store()
    cluster = KubernetesCluster("k8s-1", api=api, store=store)
    cfg = Config()
    cfg.default_matcher.backend = "cpu"
    sched = Scheduler(store, cfg, [cluster], rank_backend="cpu")
    return api, store, cluster, sched


class TestOfferSynthesis:
    def test_capacity_minus_consumption(self):
        api, store, cluster, _ = k8s_system()
        api.create_pod(FakePod(name="existing", node_name="node0",
                               phase="Running", cpus=2.0, mem=1024.0))
        offers = {o.hostname: o for o in cluster.pending_offers("default")}
        assert offers["node0"].available.cpus == 6.0
        assert offers["node0"].available.mem == 7168.0
        assert offers["node1"].available.cpus == 8.0
        assert offers["node0"].task_count == 1

    def test_unschedulable_node_excluded(self):
        api, _s, cluster, _ = k8s_system()
        api.add_node(FakeNode(name="cordoned", cpus=8, mem=8192,
                              unschedulable=True))
        api.add_node(FakeNode(name="tainted", cpus=8, mem=8192,
                              taints=["maintenance"]))
        names = {o.hostname for o in cluster.pending_offers("default")}
        assert "cordoned" not in names and "tainted" not in names


class TestControllerLifecycle:
    def test_full_pod_lifecycle(self):
        api, store, cluster, sched = k8s_system()
        [uuid] = store.create_jobs([make_job()])
        sched.step_rank()
        res = sched.step_match()["default"]
        [tid] = res.launched_task_ids
        # pod exists, pending on its assigned node
        pod = api.pod(tid)
        assert pod is not None and pod.node_name is not None
        assert store.job(uuid).state is JobState.RUNNING
        api.step()  # pod starts running
        assert store.instance(tid).status is InstanceStatus.RUNNING
        assert store.instance(tid).hostname == pod.node_name
        api.finish_pod(tid, exit_code=0)
        assert store.instance(tid).status is InstanceStatus.SUCCESS
        assert store.job(uuid).state is JobState.COMPLETED
        # terminal pod is deleted from kubernetes and forgotten
        assert api.pod(tid) is None
        assert tid not in cluster.controller.expected

    def test_pod_failure_marks_instance_failed(self):
        api, store, cluster, sched = k8s_system()
        [uuid] = store.create_jobs([make_job(max_retries=2)])
        sched.step_rank()
        [tid] = sched.step_match()["default"].launched_task_ids
        api.step()
        api.finish_pod(tid, exit_code=3)
        inst = store.instance(tid)
        assert inst.status is InstanceStatus.FAILED
        assert inst.exit_code == 3
        assert store.job(uuid).state is JobState.WAITING  # retry

    def test_node_lost_is_mea_culpa(self):
        api, store, cluster, sched = k8s_system()
        [uuid] = store.create_jobs([make_job(max_retries=1)])
        sched.step_rank()
        [tid] = sched.step_match()["default"].launched_task_ids
        api.step()
        api.lose_node(store.instance(tid).hostname or "node0")
        inst = store.instance(tid)
        assert inst.status is InstanceStatus.FAILED
        assert inst.reason_code == Reasons.NODE_LOST.code
        # mea culpa: no retry consumed
        assert store.job(uuid).state is JobState.WAITING

    def test_user_kill_deletes_pod(self):
        api, store, cluster, sched = k8s_system()
        [uuid] = store.create_jobs([make_job()])
        sched.step_rank()
        [tid] = sched.step_match()["default"].launched_task_ids
        api.step()
        store.kill_job(uuid)
        assert store.job(uuid).state is JobState.COMPLETED
        assert api.pod(tid) is None

    def test_kill_before_pod_materializes(self):
        # the (killed, missing) race: kill lands before the pod is visible
        api, store, cluster, sched = k8s_system()
        cluster.controller.set_expected("ghost-task", CookExpected.KILLED)
        cluster.controller.process("ghost-task")
        assert "ghost-task" not in cluster.controller.expected

    def test_untracked_live_cook_pod_killed(self):
        # a cook-labeled pod with no expected state (e.g. from a dead
        # leader's unrecorded launch) is reaped...
        api, store, cluster, sched = k8s_system()
        api.create_pod(FakePod(name="stray", node_name="node0",
                               phase="Running", cpus=1, mem=64,
                               labels={"cook/job": "ghost"}))
        assert api.pod("stray") is None  # watch event triggers the kill

    def test_foreign_pod_left_alone(self):
        # ...but a foreign workload sharing the node is never touched
        api, store, cluster, sched = k8s_system()
        api.create_pod(FakePod(name="daemonset-thing", node_name="node0",
                               phase="Running", cpus=1, mem=64))
        cluster.controller.scan_all()
        assert api.pod("daemonset-thing") is not None


class TestStartupReconciliation:
    def test_leader_restart_adopts_running_pods(self):
        api, store, cluster, sched = k8s_system()
        [uuid] = store.create_jobs([make_job()])
        sched.step_rank()
        [tid] = sched.step_match()["default"].launched_task_ids
        api.step()
        assert store.instance(tid).status is InstanceStatus.RUNNING
        # new leader: restore the store, fresh cluster object over same api;
        # the old leader detaches first
        blob = store.snapshot()
        cluster.shutdown()
        store2 = Store.restore(blob)
        cluster2 = KubernetesCluster("k8s-1", api=api, store=store2)
        cfg = Config()
        cfg.default_matcher.backend = "cpu"
        sched2 = Scheduler(store2, cfg, [cluster2], rank_backend="cpu")
        # adopted: completing the pod now completes the job in the new store
        api.finish_pod(tid, exit_code=0)
        assert store2.instance(tid).status is InstanceStatus.SUCCESS
        assert store2.job(uuid).state is JobState.COMPLETED


class TestAutoscaling:
    def test_synthetic_pods_created_for_unmatched(self):
        api, store, cluster, sched = k8s_system(n_nodes=1, cpus=2.0)
        jobs = [make_job(cpus=2.0) for _ in range(3)]
        store.create_jobs(jobs)
        sched.step_rank()
        res = sched.step_match()["default"]
        assert len(res.unmatched) == 2
        created = cluster.autoscale("default", res.unmatched)
        assert created == 2
        synthetic = [p for p in api.pods() if p.synthetic]
        assert len(synthetic) == 2
        # synthetic pods sized like the jobs they stand in for
        assert all(p.cpus == 2.0 for p in synthetic)
        # idempotent
        assert cluster.autoscale("default", res.unmatched) == 0
        # once jobs launch, placeholders are reaped
        reaped = cluster.reap_synthetic_pods([j.uuid for j in jobs])
        assert reaped == 2

    def test_synthetic_pods_excluded_from_offers_accounting(self):
        api, store, cluster, sched = k8s_system(n_nodes=1, cpus=8.0)
        # synthetic pods consume fake-scheduler capacity once scheduled, but
        # are not tracked by the controller
        cluster.autoscale("default", [make_job(cpus=4.0)])
        [pod] = [p for p in api.pods() if p.synthetic]
        assert pod.name not in cluster.controller.expected
        cluster.controller.scan_all()
        assert api.pod(pod.name) is not None  # scan leaves synthetics alone


class TestSchedulerAutoscaleIntegration:
    def test_match_cycle_triggers_autoscaling(self):
        api = FakeKubernetesApi()
        api.add_node(FakeNode(name="node0", cpus=2.0, mem=8192.0))
        store = Store()
        cluster = KubernetesCluster("k8s-1", api=api, store=store)
        cfg = Config(autoscaling_enabled=True)
        cfg.default_matcher.backend = "cpu"
        sched = Scheduler(store, cfg, [cluster], rank_backend="cpu")
        store.create_jobs([make_job(cpus=2.0) for _ in range(3)])
        sched.step_rank()
        sched.step_match()
        synthetic = [p for p in api.pods() if p.synthetic]
        assert len(synthetic) == 2  # one matched, two surfaced as demand
        # capacity arrives (autoscaler added a node); jobs match for real and
        # their placeholders are reaped
        api.add_node(FakeNode(name="node1", cpus=8.0, mem=16384.0))
        sched.step_rank()
        res = sched.step_match()["default"]
        assert len(res.launched_task_ids) == 2
        assert [p for p in api.pods() if p.synthetic] == []


class TestDirectModeBackpressure:
    def test_max_launchable_headroom(self):
        api, store, cluster, _ = k8s_system(n_nodes=2)
        cluster.max_pods_per_node = 3
        assert cluster.max_launchable("default") == 6
        api.create_pod(FakePod(name="p1", node_name="node0", phase="Running",
                               cpus=1, mem=10))
        assert cluster.max_launchable("default") == 5
        cluster.max_total_pods = 2
        assert cluster.max_launchable("default") == 1


class TestPodSpecArtifacts:
    def test_ports_and_uris_compiled_into_pod(self):
        """job.ports -> containerPorts; job.uris -> cook-fetch init
        container sharing the workdir (the mesos fetcher's k8s analog)."""
        from cook_tpu.cluster.k8s.pod_spec import build_pod_spec
        from cook_tpu.state import Job, Resources, new_uuid

        job = Job(uuid=new_uuid(), user="alice", command="serve",
                  ports=2,
                  uris=[{"value": "/data/a.bin"},
                        {"value": "https://x/b.tgz", "extract": True}],
                  resources=Resources(cpus=1.0, mem=64.0))
        spec = build_pod_spec(job, "default")
        main = spec["containers"][0]
        assert spec["port_count"] == 2
        assert {"name": "COOK_PORT_COUNT", "value": "2"} in main["env"]
        fetch = [c for c in spec["init_containers"]
                 if c["name"] == "cook-fetch"]
        assert len(fetch) == 1
        assert "/data/a.bin" in fetch[0]["env"][0]["value"]
        assert "https://x/b.tgz" in fetch[0]["env"][0]["value"]
        # fetch lands in the same workdir volume the job mounts
        assert fetch[0]["volume_mounts"][0]["name"] == "cook-workdir"


class TestNodeBlocklist:
    def test_blocklisted_label_excludes_node_from_offers(self):
        """node-blocklist-labels (reference: node-schedulable?
        kubernetes/api.clj:782): a node carrying a blocklisted label key
        contributes no offers even when otherwise schedulable."""
        from cook_tpu.cluster.k8s.compute_cluster import KubernetesCluster
        from cook_tpu.cluster.k8s.fake_api import FakeKubernetesApi, FakeNode

        api = FakeKubernetesApi()
        api.add_node(FakeNode(name="good", cpus=8, mem=8192))
        api.add_node(FakeNode(name="cordoned", cpus=8, mem=8192,
                              labels={"maintenance": "true"}))
        cluster = KubernetesCluster(
            "k1", api=api, node_blocklist_labels=["maintenance"])
        cluster.initialize(lambda *a, **k: None)
        hosts = {o.hostname for o in cluster.pending_offers("default")}
        assert hosts == {"good"}


class TestDisallowedVolumesAndVars:
    """Operator-owned container paths and env var names are DROPPED at
    pod compile, not rejected (reference: make-volumes
    kubernetes/api.clj:990-1003 + make-filtered-env-vars :1117-1126;
    integration test_kubernetes_disallowed_volumes /
    _disallowed_var_names)."""

    def test_filtered_out_of_pod_spec(self):
        from cook_tpu.cluster.k8s.pod_spec import build_pod_spec
        from cook_tpu.state import Job, Resources
        job = Job(uuid="u-1", user="alice", command="x",
                  resources=Resources(cpus=1.0, mem=64.0),
                  env={"OK_VAR": "1", "INJECTED": "nope"},
                  container={"image": "img", "volumes": [
                      {"host-path": "/data", "container-path": "/data"},
                      {"host-path": "/tmp", "container-path": "/managed"},
                      "/scratch:/scratch"]})
        spec = build_pod_spec(
            job, "default", sidecar=False,
            disallowed_container_paths={"/managed", "/scratch"},
            disallowed_var_names={"INJECTED"})
        [c] = spec["containers"]
        mounts = {m["mount_path"] for m in c["volume_mounts"]}
        assert "/data" in mounts
        assert "/managed" not in mounts and "/scratch" not in mounts
        names = {e["name"] for e in c["env"]}
        assert "OK_VAR" in names and "INJECTED" not in names

    def test_cluster_threads_config_and_settings_reports_it(self):
        from cook_tpu.cluster.k8s.compute_cluster import KubernetesCluster
        from cook_tpu.rest import CookApi
        from cook_tpu.sched import Scheduler
        from cook_tpu.config import Config
        from cook_tpu.state import Store
        store = Store()
        cluster = KubernetesCluster(
            "k8s", store=store,
            disallowed_container_paths=["/managed"],
            disallowed_var_names=["INJECTED"])
        cfg = Config()
        cfg.default_matcher.backend = "cpu"
        sched = Scheduler(store, cfg, [cluster], rank_backend="cpu")
        api = CookApi(store, scheduler=sched, config=cfg)
        s = api.settings()
        assert s["kubernetes"]["disallowed-container-paths"] == ["/managed"]
        assert s["kubernetes"]["disallowed-var-names"] == ["INJECTED"]

    def test_env_parameter_cannot_bypass_filters(self):
        from cook_tpu.cluster.k8s.pod_spec import build_pod_spec
        from cook_tpu.state import Job, Resources
        job = Job(uuid="u-2", user="alice", command="x",
                  resources=Resources(cpus=1.0, mem=64.0),
                  container={"image": "img", "parameters": [
                      {"key": "env", "value": "INJECTED=evil"},
                      {"key": "env", "value": "COOK_JOB_UUID=forged"},
                      {"key": "env", "value": "FINE=yes"}]})
        spec = build_pod_spec(job, "default", sidecar=False,
                              disallowed_var_names={"INJECTED"})
        [c] = spec["containers"]
        env = {e["name"]: e.get("value") for e in c["env"]}
        assert env["FINE"] == "yes"
        assert "INJECTED" not in env           # operator-owned name
        assert env["COOK_JOB_UUID"] == "u-2"   # identity var unforgeable

    def test_api_only_node_reports_kubernetes_settings_from_config(self):
        from cook_tpu.rest import CookApi
        from cook_tpu.config import Config
        from cook_tpu.state import Store
        cfg = Config()
        cfg.kubernetes_disallowed_container_paths = ["/managed"]
        cfg.kubernetes_disallowed_var_names = ["INJECTED"]
        api = CookApi(Store(), scheduler=None, config=cfg)  # api-only
        s = api.settings()
        assert s["kubernetes"]["disallowed-container-paths"] == ["/managed"]
        assert s["kubernetes"]["disallowed-var-names"] == ["INJECTED"]

    def test_scheduler_config_threads_into_built_clusters(self):
        from cook_tpu.daemon import build_clusters, build_scheduler_config
        cfg = build_scheduler_config({
            "kubernetes": {"disallowed_container_paths": ["/managed"],
                           "disallowed_var_names": ["INJECTED"]}})
        from cook_tpu.state import Store
        [cluster] = build_clusters(
            [{"factory": "cook_tpu.cluster.k8s.compute_cluster.factory",
              "kwargs": {"name": "k8s-a"}}], Store(), config=cfg)
        assert cluster.disallowed_container_paths == {"/managed"}
        assert cluster.disallowed_var_names == {"INJECTED"}
        # the config policy is a GLOBAL FLOOR: explicit kwargs ADD to it
        # (so /settings' union reports exactly what is enforced)
        [cluster2] = build_clusters(
            [{"factory": "cook_tpu.cluster.k8s.compute_cluster.factory",
              "kwargs": {"name": "k8s-b",
                         "disallowed_var_names": ["OTHER"]}}],
            Store(), config=cfg)
        assert cluster2.disallowed_var_names == {"OTHER", "INJECTED"}
        assert cluster2.disallowed_container_paths == {"/managed"}

    def test_workdir_overlap_volume_dropped(self):
        # reference: test_workdir_volume_overlap — a user volume at the
        # sandbox path would be a duplicate mountPath; the job still runs
        from cook_tpu.cluster.k8s.pod_spec import (COOK_WORKDIR,
                                                   build_pod_spec)
        from cook_tpu.state import Job, Resources
        job = Job(uuid="u-3", user="alice", command="x",
                  resources=Resources(cpus=1.0, mem=64.0),
                  container={"image": "img", "volumes": [
                      {"host-path": "/x", "container-path": COOK_WORKDIR},
                      {"host-path": "/y", "container-path": "/y"}]})
        spec = build_pod_spec(job, "default", sidecar=False)
        [c] = spec["containers"]
        paths = [m["mount_path"] for m in c["volume_mounts"]]
        assert paths.count(COOK_WORKDIR) == 1  # only the sandbox mount
        assert "/y" in paths

    def test_user_volume_colliding_with_system_mounts_dropped(self):
        from cook_tpu.cluster.k8s.pod_spec import build_pod_spec
        from cook_tpu.state import Job, Resources
        job = Job(uuid="u-4", user="alice", command="x",
                  resources=Resources(cpus=1.0, mem=64.0),
                  labels={"shm-size-mb": "64"},
                  container={"image": "img", "volumes": [
                      {"host-path": "/a", "container-path": "/dev/shm"},
                      {"host-path": "/b", "container-path": "/data"},
                      {"host-path": "/c", "container-path": "/data"}]})
        spec = build_pod_spec(job, "default", sidecar=False)
        [c] = spec["containers"]
        paths = [m["mount_path"] for m in c["volume_mounts"]]
        assert paths.count("/dev/shm") == 1  # system shm wins
        assert paths.count("/data") == 1     # first user volume wins
        shm = [m for m in c["volume_mounts"]
               if m["mount_path"] == "/dev/shm"][0]
        assert shm["name"] == "shm"
        # dropped uservols take their volume entries with them
        assert len([v for v in spec["volumes"]
                    if v["name"].startswith("uservol-")]) == 1
