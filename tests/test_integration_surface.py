"""Integration surface tier: REST/CLI conformance scenarios against a real
``python -m cook_tpu`` daemon process (reference: the scenario families of
integration/tests/cook/test_basic.py + test_multi_user.py run against a
live cluster — scheduler info, submit field round-trips, priority, listing
filters, retry conflicts, group kill, max-runtime enforcement, CORS,
windowed stats, usage breakdown, unscheduled reasons, partial queries).

One module-scoped daemon serves every scenario (the reference tier does
the same against one cluster); each test uses its own jobs/uuids so they
compose.  Exec-dependent scenarios (task env, sandbox files) live in
test_remote_cluster.py against a real agent; these run the FakeCluster
backend with auto-advance so terminal states arrive without manual ticks.
"""

import json
import time
import urllib.error
import urllib.request

import pytest

from test_integration_scenarios import (req, spawn, wait_leader,
                                        wait_serving, wait_state)


@pytest.fixture(scope="module")
def daemon(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("surface")
    conf = {
        "host": "127.0.0.1", "port": 0,
        "data_dir": str(tmp / "data"),
        "election_dir": str(tmp),
        "admins": ["admin"],
        "impersonators": ["poser"],
        "cors_origins": ["http://cors\\.example\\.com"],
        "clusters": [{"factory": "cook_tpu.cluster.fake.factory",
                      "kwargs": {"name": "alpha", "n_hosts": 3,
                                 "cpus": 4.0, "mem": 4096.0,
                                 "default_task_duration_ms": 400,
                                 "auto_advance": True}}],
        "scheduler": {"rank_backend": "cpu", "cycle_mode": "split",
                      "match_interval_seconds": 0.1,
                      "rank_interval_seconds": 0.1,
                      "lingering_task_interval_seconds": 0.3},
    }
    procs = []
    p = spawn(conf, tmp, "surface")
    procs.append(p)
    url = wait_serving(p)
    assert wait_leader(url)
    yield url
    for pr in procs:
        if pr.poll() is None:
            pr.kill()
        pr.wait(timeout=10)


def submit(url, specs, user="alice", **kw):
    payload = {"jobs": specs, **kw}
    r = urllib.request.Request(
        f"{url}/jobs", data=json.dumps(payload).encode(), method="POST",
        headers={"X-Cook-User": user, "Content-Type": "application/json"})
    with urllib.request.urlopen(r, timeout=10) as resp:
        return json.load(resp)["jobs"]


def get(url, path):
    # req() issues every request as the admin user
    with req("GET", f"{url}{path}") as r:
        return json.load(r)


class TestSchedulerInfo:
    def test_info_fields(self, daemon):
        info = get(daemon, "/info")
        assert info["leader"] is True
        assert "version" in info and "authentication-scheme" in info


class TestSubmitFields:
    def test_defaults_and_round_trip(self, daemon):
        [u] = submit(daemon, [{
            "command": "true", "cpus": 1, "mem": 64,
            "labels": {"team": "infra"}, "priority": 75,
            "expected_runtime": 1234,
            "application": {"name": "cli", "version": "9",
                            "workload-class": "batch"}}])
        job = get(daemon, f"/jobs/{u}")
        assert job["name"] == "cookjob"          # reference default name
        assert job["labels"] == {"team": "infra"}
        assert job["priority"] == 75
        assert job["application"]["name"] == "cli"
        assert job["application"]["version"] == "9"

    def test_priority_out_of_range_rejected(self, daemon):
        with pytest.raises(urllib.error.HTTPError) as ei:
            submit(daemon, [{"command": "x", "priority": 101}])
        assert ei.value.code == 400

    def test_priority_orders_same_user_queue(self, daemon):
        # saturate the cluster so fresh submissions stay queued, then
        # assert the ranked /queue puts the high-priority job first
        hogs = submit(daemon, [{"command": "sleep 999", "cpus": 4,
                                "mem": 64,
                                "env": {"COOK_FAKE_DURATION_MS": "999999"}}
                               for _ in range(3)],
                      user="hog")
        try:
            for h in hogs:
                wait_state(daemon, h, "running")
            lo, hi = submit(daemon, [
                {"command": "true", "cpus": 1, "mem": 64, "priority": 10},
                {"command": "true", "cpus": 1, "mem": 64, "priority": 90}],
                user="prio-user")
            deadline = time.time() + 10
            order = None
            while time.time() < deadline:
                q = get(daemon, "/queue").get("default", [])
                order = [j["uuid"] for j in q if j["uuid"] in (lo, hi)]
                if len(order) == 2:
                    break
                time.sleep(0.1)
            assert order == [hi, lo], order
        finally:
            # a failure must not leave the module-scoped cluster saturated
            for h in hogs:
                insts = get(daemon, f"/jobs/{h}")["instances"]
                if insts:
                    req("DELETE",
                        f"{daemon}/instances?uuid={insts[-1]['task_id']}")


class TestMaxRuntime:
    def test_max_runtime_exceeded_fails_with_reason(self, daemon):
        """reference: test_max_runtime_exceeded — a job over its
        max_runtime is killed with the max-runtime-exceeded reason."""
        [u] = submit(daemon, [{"command": "sleep 999", "cpus": 1,
                               "mem": 64, "max_runtime": 500,
                               "max_retries": 1,
                               "env": {"COOK_FAKE_DURATION_MS":
                                       "999999"}}])
        job = wait_state(daemon, u, "failed", timeout=30)
        inst = job["instances"][-1]
        assert inst["reason_string"] == "max-runtime-exceeded", inst


class TestListing:
    def test_list_filters(self, daemon):
        tag = "lst"
        a, b = submit(daemon, [
            {"command": "true", "cpus": 1, "mem": 64, "name": f"{tag}-one"},
            {"command": "exit 1", "cpus": 1, "mem": 64, "max_retries": 1,
             "name": f"{tag}-two",
             "env": {"COOK_FAKE_EXIT_CODE": "1"}}], user="lister")
        wait_state(daemon, a, "success", timeout=30)
        wait_state(daemon, b, "failed", timeout=30)
        by_name = get(daemon, f"/list?user=lister&name={tag}-*"
                              "&state=completed")
        assert {j["uuid"] for j in by_name} == {a, b}
        failed = get(daemon, "/list?user=lister&state=failed")
        assert {j["uuid"] for j in failed} == {b}
        with pytest.raises(urllib.error.HTTPError) as ei:
            get(daemon, "/list?user=lister&name=bad%20name!")
        assert ei.value.code == 400
        # time window below every submit matches nothing
        assert get(daemon, "/list?user=lister&state=completed"
                           "&end-ms=1000") == []

    def test_partial_jobs_query(self, daemon):
        [u] = submit(daemon, [{"command": "true", "cpus": 1, "mem": 64}])
        bogus = "00000000-0000-0000-0000-000000000000"
        with pytest.raises(urllib.error.HTTPError) as ei:
            get(daemon, f"/jobs?uuid={u}&uuid={bogus}")
        assert ei.value.code == 404
        found = get(daemon, f"/jobs?uuid={u}&uuid={bogus}&partial=true")
        assert [j["uuid"] for j in found] == [u]


class TestRetrySemantics:
    def test_decrease_below_attempts_conflict(self, daemon):
        [u] = submit(daemon, [{"command": "exit 1", "cpus": 1, "mem": 64,
                               "max_retries": 2,
                               "env": {"COOK_FAKE_EXIT_CODE": "1"}}])
        wait_state(daemon, u, "failed", timeout=30)
        assert len(get(daemon, f"/jobs/{u}")["instances"]) == 2
        body = json.dumps({"job": u, "retries": 1}).encode()
        r = urllib.request.Request(
            f"{daemon}/retry", data=body, method="POST",
            headers={"X-Cook-User": "alice",
                     "Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(r, timeout=10)
        assert ei.value.code in (400, 409)

    def test_retry_resurrects_failed_job(self, daemon):
        [u] = submit(daemon, [{"command": "exit 1", "cpus": 1, "mem": 64,
                               "max_retries": 1,
                               "env": {"COOK_FAKE_EXIT_CODE": "1"}}])
        wait_state(daemon, u, "failed", timeout=30)
        with req("POST", f"{daemon}/retry",
                 {"job": u, "retries": 3}) as r:
            assert r.status == 200
        job = get(daemon, f"/jobs/{u}")
        assert job["state"] in ("waiting", "running", "failed")
        assert job["max_retries"] == 3


class TestGroups:
    def test_group_kill_via_rest(self, daemon):
        g = "99999999-1111-2222-3333-444444444444"
        uuids = submit(daemon, [{"command": "sleep 999", "cpus": 1,
                                 "mem": 64, "group": g,
                                 "env": {"COOK_FAKE_DURATION_MS": "999999"}}
                                for _ in range(2)],
                       groups=[{"uuid": g, "name": "killme"}])
        for u in uuids:
            wait_state(daemon, u, "running")
        with req("DELETE", f"{daemon}/group?uuid={g}") as r:
            assert r.status == 200
        for u in uuids:
            job = wait_state(daemon, u, "failed", timeout=20)
            assert job["state"] == "failed"

    def test_group_query_without_uuid_400(self, daemon):
        with pytest.raises(urllib.error.HTTPError) as ei:
            get(daemon, "/group")
        assert ei.value.code == 400


class TestCors:
    def test_preflight_allowed_and_denied(self, daemon):
        r = urllib.request.Request(f"{daemon}/jobs", method="OPTIONS",
                                   headers={"Origin":
                                            "http://cors.example.com"})
        with urllib.request.urlopen(r, timeout=5) as resp:
            assert resp.status == 200
            assert resp.headers["Access-Control-Allow-Origin"] == \
                "http://cors.example.com"
        r = urllib.request.Request(f"{daemon}/jobs", method="OPTIONS",
                                   headers={"Origin": "http://evil.com"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(r, timeout=5)
        assert ei.value.code == 403

    def test_cors_request_carries_allow_origin(self, daemon):
        r = urllib.request.Request(
            f"{daemon}/info",
            headers={"Origin": "http://cors.example.com",
                     "X-Cook-User": "alice"})
        with urllib.request.urlopen(r, timeout=5) as resp:
            assert resp.headers["Access-Control-Allow-Origin"] == \
                "http://cors.example.com"


class TestWindowedStats:
    def test_stats_through_daemon(self, daemon):
        [u] = submit(daemon, [{"command": "true", "cpus": 1, "mem": 64,
                               "name": "statjob"}], user="statuser")
        wait_state(daemon, u, "success", timeout=30)
        now_ms = int(time.time() * 1000)
        out = get(daemon, "/stats/instances?status=success"
                          f"&start={now_ms - 3_600_000}"
                          f"&end={now_ms + 3_600_000}&name=statjob")
        assert out["overall"]["count"] >= 1
        assert "statuser" in out["by-user-and-reason"]
        with pytest.raises(urllib.error.HTTPError) as ei:
            get(daemon, "/stats/instances?status=nope"
                        f"&start={now_ms - 1000}&end={now_ms}")
        assert ei.value.code == 400


class TestUsageAndUnscheduled:
    def test_usage_group_breakdown(self, daemon):
        g = "99999999-aaaa-bbbb-cccc-dddddddddddd"
        grouped = submit(daemon, [{"command": "sleep 999", "cpus": 1,
                                   "mem": 64, "group": g,
                                   "env": {"COOK_FAKE_DURATION_MS":
                                           "999999"}}],
                         user="usage-user",
                         groups=[{"uuid": g, "name": "grp"}])
        loose = submit(daemon, [{"command": "sleep 999", "cpus": 1,
                                 "mem": 64,
                                 "env": {"COOK_FAKE_DURATION_MS":
                                         "999999"}}], user="usage-user")
        try:
            for u in grouped + loose:
                wait_state(daemon, u, "running")
            out = get(daemon, "/usage?user=usage-user&group_breakdown=true")
            assert out["total_usage"]["jobs"] == 2
            [entry] = out["grouped"]
            assert entry["group"]["uuid"] == g
            assert out["ungrouped"]["running_jobs"] == loose
        finally:
            for u in grouped + loose:
                insts = get(daemon, f"/jobs/{u}")["instances"]
                if insts:
                    req("DELETE",
                        f"{daemon}/instances?uuid={insts[-1]['task_id']}")

    def test_unscheduled_reasons_for_too_big_job(self, daemon):
        [u] = submit(daemon, [{"command": "x", "cpus": 64, "mem": 64}])
        # two-step workflow: the first query marks the job under
        # investigation; a later match cycle records the placement verdict
        deadline = time.time() + 15
        reasons = []
        while time.time() < deadline:
            out = get(daemon, f"/unscheduled_jobs?job={u}")
            reasons = [r["reason"] for r in out[0]["reasons"]]
            if any("placed" in r or "match" in r or "hosts" in r
                   for r in reasons):
                break
            time.sleep(0.2)
        assert any("placed" in r or "match" in r or "hosts" in r
                   for r in reasons), reasons
        req("DELETE", f"{daemon}/jobs?uuid={u}")


class TestQueueAccess:
    def test_queue_admin_gated(self, daemon):
        r = urllib.request.Request(f"{daemon}/queue",
                                   headers={"X-Cook-User": "alice"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(r, timeout=5)
        assert ei.value.code == 403
        assert isinstance(get(daemon, "/queue"), dict)


def req_as(method, url, user, payload=None, impersonate=None, timeout=5):
    headers = {"X-Cook-User": user, "Content-Type": "application/json"}
    if impersonate:
        headers["X-Cook-Impersonate"] = impersonate
    data = json.dumps(payload).encode() if payload is not None else None
    r = urllib.request.Request(url, data=data, method=method,
                               headers=headers)
    return urllib.request.urlopen(r, timeout=timeout)


class TestImpersonation:
    """reference: integration test_impersonation.py — only configured
    impersonators may impersonate (admins get nothing implicitly),
    authorization is evaluated as the impersonated user, impersonated
    identities may not reach admin endpoints, and self-impersonation is
    a plain request."""

    def _owned_job(self, daemon, owner="vic"):
        [u] = submit(daemon, [{"command": "sleep 999", "cpus": 1,
                               "mem": 64,
                               "env": {"COOK_FAKE_DURATION_MS": "999999"}}],
                     user=owner)
        wait_state(daemon, u, "running")
        return u

    def test_impersonated_job_delete(self, daemon):
        u = self._owned_job(daemon)
        # the impersonator as themselves: not the owner -> 403
        with pytest.raises(urllib.error.HTTPError) as ei:
            req_as("DELETE", f"{daemon}/jobs?uuid={u}", "poser")
        assert ei.value.code == 403
        # impersonating the WRONG user: still 403
        with pytest.raises(urllib.error.HTTPError) as ei:
            req_as("DELETE", f"{daemon}/jobs?uuid={u}", "poser",
                   impersonate="mallory")
        assert ei.value.code == 403
        # a non-impersonator impersonating the owner: 403
        with pytest.raises(urllib.error.HTTPError) as ei:
            req_as("DELETE", f"{daemon}/jobs?uuid={u}", "mallory",
                   impersonate="vic")
        assert ei.value.code == 403
        # the impersonator impersonating the owner: allowed
        with req_as("DELETE", f"{daemon}/jobs?uuid={u}", "poser",
                    impersonate="vic") as r:
            assert r.status == 200

    def test_admin_cannot_impersonate(self, daemon):
        u = self._owned_job(daemon, owner="vic2")
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                req_as("DELETE", f"{daemon}/jobs?uuid={u}", "admin",
                       impersonate="vic2")
            assert ei.value.code == 403
        finally:  # admin kills it directly (no impersonation)
            req_as("DELETE", f"{daemon}/jobs?uuid={u}", "admin")

    def test_cannot_impersonate_admin_endpoints(self, daemon):
        # impersonating an ADMIN must not unlock admin endpoints
        with pytest.raises(urllib.error.HTTPError) as ei:
            req_as("GET", f"{daemon}/queue", "poser", impersonate="admin")
        assert ei.value.code == 403
        with pytest.raises(urllib.error.HTTPError) as ei:
            req_as("POST", f"{daemon}/quota", "poser",
                   payload={"user": "x", "pools": {}}, impersonate="admin")
        assert ei.value.code == 403

    def test_self_impersonation_is_plain_request(self, daemon):
        # admin self-impersonating keeps admin rights
        with req_as("GET", f"{daemon}/queue", "admin",
                    impersonate="admin") as r:
            assert r.status == 200
        # a normal user self-impersonating can submit
        with req_as("POST", f"{daemon}/jobs", "selfy",
                    payload={"jobs": [{"command": "true", "cpus": 1,
                                       "mem": 64}]},
                    impersonate="selfy") as resp:
            assert resp.status == 200
