"""Native sharded watch queue tests: build, per-key ordering, parallelism,
Python-fallback equivalence, scheduler integration."""

import threading
import time

import pytest

from cook_tpu.native import (
    PyWatchQueue,
    make_watch_queue,
    native_available,
)


@pytest.fixture(params=["native", "python"])
def queue_factory(request):
    if request.param == "native":
        if not native_available():
            pytest.skip("no C++ toolchain")
        from cook_tpu.native import ShardedWatchQueue
        return ShardedWatchQueue
    return PyWatchQueue


class TestWatchQueue:
    def test_per_key_ordering(self, queue_factory):
        seen = {}
        lock = threading.Lock()

        def handler(key, payload):
            with lock:
                seen.setdefault(key, []).append(payload)

        q = queue_factory(handler, shards=4)
        try:
            for i in range(200):
                for key in ("a", "b", "c", "d", "e"):
                    q.submit(key, i)
            q.flush()
            assert q.pending == 0
            for key in ("a", "b", "c", "d", "e"):
                assert seen[key] == list(range(200)), f"key {key} reordered"
        finally:
            q.close()

    def test_parallelism_across_shards(self, queue_factory):
        # a slow key must not block other shards for the full serial time
        barrier_hits = []
        lock = threading.Lock()

        def handler(key, payload):
            if key == "slow":
                time.sleep(0.05)
            with lock:
                barrier_hits.append(key)

        q = queue_factory(handler, shards=8)
        try:
            t0 = time.time()
            for _ in range(10):
                q.submit("slow")
            for i in range(50):
                q.submit(f"fast-{i}")
            q.flush()
            elapsed = time.time() - t0
            # serial would be >= 0.5s for the slow key alone; the fast keys
            # ran on other shards meanwhile — total stays near slow-key time
            assert elapsed < 2.0
            assert len(barrier_hits) == 60
        finally:
            q.close()

    def test_handler_error_isolated(self, queue_factory):
        def handler(key, payload):
            if payload == "boom":
                raise ValueError("boom")

        q = queue_factory(handler, shards=2)
        try:
            q.submit("k", "boom")
            q.submit("k", "fine")
            q.flush()
            assert q.processed == 2
            assert len(q.errors()) == 1
        finally:
            q.close()

    def test_processed_counters(self, queue_factory):
        q = queue_factory(lambda k, p: None, shards=2)
        try:
            for i in range(25):
                q.submit(f"k{i}")
            q.flush()
            assert q.processed == 25
        finally:
            q.close()


class TestNativeBuild:
    def test_native_library_builds_here(self):
        # this environment ships g++; the native path must actually build
        assert native_available(), "native watch queue failed to build"


class TestSchedulerIntegration:
    def test_status_updates_via_sharded_queue(self):
        from cook_tpu.cluster import FakeCluster, FakeHost
        from cook_tpu.config import Config
        from cook_tpu.sched import Scheduler
        from cook_tpu.state import (InstanceStatus, Job, JobState, Resources,
                                    Store, new_uuid)

        store = Store()
        cluster = FakeCluster(
            "c", [FakeHost(f"h{i}", Resources(cpus=8, mem=8192))
                  for i in range(4)])
        cfg = Config()
        cfg.default_matcher.backend = "cpu"
        sched = Scheduler(store, cfg, [cluster], rank_backend="cpu",
                          status_queue_shards=7)
        jobs = [Job(uuid=new_uuid(), user=f"u{i % 3}", command="x",
                    resources=Resources(cpus=1, mem=100)) for i in range(12)]
        store.create_jobs(jobs)
        sched.step_rank()
        res = sched.step_match()["default"]
        sched.flush_status_updates()
        assert len(res.launched_task_ids) == 12
        for tid in res.launched_task_ids:
            assert store.instance(tid).status is InstanceStatus.RUNNING
        for tid in res.launched_task_ids:
            cluster.complete_task(tid)
        sched.flush_status_updates()
        for job in jobs:
            assert store.job(job.uuid).state is JobState.COMPLETED
