"""RealKubernetesApi over real sockets against the in-repo mock apiserver.

Every method of the stdlib-HTTP client adapter executes here: CRUD +
field translation, chunked watch streams with resourceVersion resume
after a dropped connection, the 410 Gone relist path, lease CAS, and the
full k8s backend (KubernetesCluster + PodController) driven end-to-end
through HTTP (VERDICT r3 missing #1; reference behaviors:
scheduler/src/cook/kubernetes/api.clj:372-734).
"""

import threading
import time

import pytest

from cook_tpu.cluster.k8s.fake_api import (FakeKubernetesApi, FakeNode,
                                           FakePod)
from cook_tpu.cluster.k8s.mock_apiserver import MockApiServer
from cook_tpu.cluster.k8s.real_api import RealKubernetesApi, parse_qty


@pytest.fixture()
def mock():
    srv = MockApiServer().start()
    yield srv
    srv.close()


@pytest.fixture()
def api(mock):
    a = RealKubernetesApi(base_url=mock.base_url, namespace="cook",
                          watch_timeout_s=5.0)
    yield a
    a._stop.set()


def wait_for(pred, timeout=5.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


class TestQuantities:
    def test_parse_qty_forms(self):
        assert parse_qty("2") == 2.0
        assert parse_qty("1500m") == 1.5
        assert parse_qty("512Mi") == 512.0
        assert parse_qty("1Gi") == 1024.0
        assert parse_qty("524288Ki") == 512.0
        assert parse_qty(None, 7.0) == 7.0
        assert parse_qty("garbage", 3.0) == 3.0


class TestCrudTranslation:
    def test_nodes_roundtrip(self, mock, api):
        mock.fake.add_node(FakeNode(
            name="n1", cpus=16.0, mem=32768.0, gpus=2.0, pool="gpu",
            labels={"zone": "z1"}, taints=["dedicated"],
            unschedulable=False, gpu_model="a100"))
        [n] = api.nodes()
        assert (n.name, n.cpus, n.mem, n.gpus) == ("n1", 16.0, 32768.0, 2.0)
        assert n.pool == "gpu" and n.labels["zone"] == "z1"
        assert n.taints == ["dedicated"] and n.gpu_model == "a100"

    def test_pod_crud_and_field_mapping(self, mock, api):
        api.create_pod(FakePod(
            name="p1", cpus=2.0, mem=1024.0,
            labels={"cook/job": "j1"}, annotations={"a": "b"},
            spec={"containers": [{
                "name": "cook-job", "image": "img:1",
                "command": ["/bin/sh", "-c", "true"],
                "env": [{"name": "FOO", "value": "bar"}]}]}))
        # wire body captured by the mock carries the compiled spec
        [body] = mock.last_created_bodies
        c = body["spec"]["containers"][0]
        assert c["image"] == "img:1" and c["command"][0] == "/bin/sh"
        assert {"name": "FOO", "value": "bar"} in c["env"]
        # read-side translation
        p = api.pod("p1")
        assert p is not None and p.cpus == 2.0 and p.mem == 1024.0
        assert p.labels["cook/job"] == "j1" and p.annotations["a"] == "b"
        assert api.pod("nope") is None
        with pytest.raises(ValueError):
            api.create_pod(FakePod(name="p1"))  # 409 -> ValueError
        # terminated container state maps to exit_code/reason
        mock.fake.step()  # schedule needs a node
        mock.fake.add_node(FakeNode(name="n1", cpus=8.0, mem=8192.0))
        mock.fake.step()
        mock.fake.step()
        mock.fake.finish_pod("p1", exit_code=3)
        p = api.pod("p1")
        assert p.exit_code == 3 and p.phase == "Failed"
        # delete: tolerated when missing, grace period forwarded
        api.delete_pod("p1", grace_period_s=0)
        api.delete_pod("p1")  # now 404: swallowed
        assert api.pod("p1") is None

    def test_unschedulable_condition_mapping(self, mock, api):
        api.create_pod(FakePod(name="p2", cpus=1.0, mem=64.0))
        mock.fake.mark_unschedulable("p2", "0/3 nodes: taint mismatch")
        p = api.pod("p2")
        assert "taint mismatch" in p.unschedulable_reason


class TestWatches:
    def test_watch_stream_delivers_events(self, mock, api):
        seen = []
        api.watch(seen.append)
        mock.fake.add_node(FakeNode(name="n1", cpus=4.0, mem=4096.0))
        api.create_pod(FakePod(name="w1", cpus=1.0, mem=128.0))
        wait_for(lambda: any(e.kind == "pod" and e.type == "ADDED"
                             and e.obj.name == "w1" for e in seen),
                 msg="pod ADDED event")
        wait_for(lambda: any(e.kind == "node" and e.obj.name == "n1"
                             for e in seen), msg="node ADDED event")
        mock.fake.step()  # schedule -> MODIFIED
        wait_for(lambda: any(e.kind == "pod" and e.type == "MODIFIED"
                             and e.obj.node_name == "n1" for e in seen),
                 msg="pod MODIFIED with node")
        assert api.resource_version > 0

    def test_reconnect_resumes_from_last_rv(self, mock, api):
        seen = []
        api.watch(seen.append)
        api.create_pod(FakePod(name="r1", cpus=1.0, mem=64.0))
        wait_for(lambda: any(e.obj.name == "r1" for e in seen
                             if e.kind == "pod"), msg="first event")
        n_before = len([e for e in seen if e.kind == "pod"])
        mock.drop_watch_streams()   # hard-drop: client must reconnect
        time.sleep(0.2)
        api.create_pod(FakePod(name="r2", cpus=1.0, mem=64.0))
        wait_for(lambda: any(e.obj.name == "r2" for e in seen
                             if e.kind == "pod"), msg="post-drop event")
        # resume (not replay): r1's ADDED is not delivered twice
        r1_adds = [e for e in seen
                   if e.kind == "pod" and e.type == "ADDED"
                   and e.obj.name == "r1"]
        assert len(r1_adds) == 1
        assert api.watch_reconnects >= 1

    def test_watch_gap_410_relists(self, mock, api):
        # history exists before the client ever watches
        for i in range(5):
            mock.fake.create_pod(FakePod(name=f"old{i}", cpus=1.0,
                                         mem=64.0))
        mock.compact()  # horizon = now: rv>0 watches below it get 410
        seen = []
        api.watch(seen.append, resource_version=1)  # too old -> 410
        wait_for(lambda: len({e.obj.name for e in seen
                              if e.kind == "pod"}) == 5,
                 msg="relist delivered current state")
        assert api.watch_gap_relists >= 1
        # and the watch is live again after the relist
        api.create_pod(FakePod(name="fresh", cpus=1.0, mem=64.0))
        wait_for(lambda: any(e.obj.name == "fresh" for e in seen
                             if e.kind == "pod"), msg="live after gap")

    def test_gap_synthesizes_deletes_for_vanished_pods(self, mock, api):
        """A pod garbage-collected while the watch is down must surface as
        DELETED after the 410 relist, or its instance stays RUNNING in
        the store forever."""
        seen = []
        api.watch(seen.append)
        api.create_pod(FakePod(name="gone", cpus=1.0, mem=64.0))
        api.create_pod(FakePod(name="stays", cpus=1.0, mem=64.0))
        wait_for(lambda: {"gone", "stays"} <= {
            e.obj.name for e in seen if e.kind == "pod"}, msg="both seen")
        mock.drop_watch_streams()
        # behind the dropped watch: the pod vanishes AND history compacts,
        # so resume gets 410 and must reconcile by relisting
        mock.fake.delete_pod("gone", grace_period_s=0)
        mock.compact()
        wait_for(lambda: any(e.kind == "pod" and e.type == "DELETED"
                             and e.obj.name == "gone" for e in seen),
                 timeout=10.0, msg="synthesized DELETED after gap")
        assert mock.fake.pod("stays") is not None


class TestLeases:
    def test_acquire_renew_and_cas_conflict(self, mock):
        a = RealKubernetesApi(base_url=mock.base_url)
        b = RealKubernetesApi(base_url=mock.base_url)
        now = time.time()
        lease = a.try_acquire_lease("lead", "node-a", now, duration_s=10.0,
                                    holder_url="http://a")
        assert lease is not None and lease.transitions == 1
        # competitor loses while the hold is live
        assert b.try_acquire_lease("lead", "node-b", now + 1) is None
        # holder renews
        lease = a.try_acquire_lease("lead", "node-a", now + 2,
                                    duration_s=10.0)
        assert lease is not None and lease.transitions == 1
        # expiry: competitor takes over, transitions bumps (fencing)
        lease = b.try_acquire_lease("lead", "node-b", now + 20)
        assert lease is not None and lease.transitions == 2
        got = a.get_lease("lead")
        assert got.holder == "node-b"
        # release: a non-holder release is a no-op...
        a.release_lease("lead", "node-a")
        assert a.get_lease("lead").holder == "node-b"
        # ...the holder's release clears the hold immediately
        b.release_lease("lead", "node-b")
        assert a.get_lease("lead").holder == ""
        assert a.get_lease("missing") is None

    def test_concurrent_contenders_single_winner(self, mock):
        apis = [RealKubernetesApi(base_url=mock.base_url) for _ in range(4)]
        now = time.time()
        wins = []
        barrier = threading.Barrier(4)

        def contend(i):
            barrier.wait()
            if apis[i].try_acquire_lease("c", f"n{i}", now) is not None:
                wins.append(i)

        ts = [threading.Thread(target=contend, args=(i,)) for i in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert len(wins) == 1  # apiserver CAS admits exactly one


class TestFullBackendOverHttp:
    """KubernetesCluster + PodController driven through RealKubernetesApi
    over HTTP: offers from watched nodes, launch -> pod created via POST,
    phase transitions -> status updates, completion observed."""

    def test_launch_run_complete(self, mock):
        from cook_tpu.cluster.base import LaunchSpec
        from cook_tpu.cluster.k8s.compute_cluster import KubernetesCluster
        from cook_tpu.state import (InstanceStatus, Job, Resources, Store)

        mock.fake.add_node(FakeNode(name="n1", cpus=8.0, mem=8192.0))
        api = RealKubernetesApi(base_url=mock.base_url,
                                watch_timeout_s=5.0)
        updates = []
        store = Store()
        store.create_jobs([Job(uuid="j1", user="alice", command="echo hi",
                               resources=Resources(cpus=1.0, mem=256.0))])
        cluster = KubernetesCluster("k8s-real", api, store=store)
        cluster.initialize(lambda tid, status, reason, **kw:
                           updates.append((tid, status)))
        wait_for(lambda: len(cluster.pending_offers("default")) == 1,
                 msg="offer from watched node")
        offer = cluster.pending_offers("default")[0]
        assert offer.available.cpus == 8.0
        cluster.launch_tasks("default", [LaunchSpec(
            task_id="t1", job_uuid="j1", hostname="", slave_id="",
            resources=Resources(cpus=1.0, mem=256.0),
            env={"COOK_COMMAND": "echo hi"})])
        wait_for(lambda: mock.fake.pod("t1") is not None,
                 msg="pod created over HTTP")
        # the compiled pod (job + sidecar file server) crossed the wire in
        # k8s form: camelCase probe, containerPort, per-container resources
        body = [b for b in mock.last_created_bodies
                if b["metadata"]["name"] == "t1"][-1]
        names = [c["name"] for c in body["spec"]["containers"]]
        assert names == ["cook-job", "cook-sidecar"]
        side = body["spec"]["containers"][1]
        assert side["readinessProbe"]["httpGet"]["path"] \
            == "/readiness-probe"
        assert side["ports"][0]["containerPort"] == \
            side["readinessProbe"]["httpGet"]["port"]
        # internal resource dicts were translated to k8s names/quantities
        # (a real apiserver rejects e.g. "memory_mb")
        assert side["resources"]["requests"] == {"cpu": "0.1",
                                                 "memory": "32Mi"}
        assert side["resources"]["limits"] == {"memory": "32Mi"}
        # and the probe endpoint is actually served by our sidecar server
        import urllib.request as _ur
        from cook_tpu.agent.file_server import SandboxFileServer
        import tempfile
        fs = SandboxFileServer(tempfile.mkdtemp())
        fs.start()
        with _ur.urlopen(f"{fs.url}/readiness-probe", timeout=5) as r:
            assert r.status == 200
        fs.stop()
        mock.fake.step()   # schedule
        mock.fake.step()   # run
        wait_for(lambda: any(s is InstanceStatus.RUNNING
                             for _, s in updates), msg="RUNNING update")
        mock.fake.finish_pod("t1", exit_code=0)
        wait_for(lambda: any(s is InstanceStatus.SUCCESS
                             for _, s in updates), msg="SUCCESS update")
        cluster.shutdown()


class TestTokenRefresh:
    """Bound service-account tokens rotate; the client re-reads the
    projected file so long-lived schedulers keep authenticating
    (reference: TokenRefreshingAuthenticator.java + the refresh thread,
    kubernetes/compute_cluster.clj:756-792)."""

    def test_token_file_rotation_picked_up(self, mock, tmp_path):
        from cook_tpu.cluster.k8s.real_api import RealKubernetesApi
        token_file = tmp_path / "token"
        token_file.write_text("tok-1")
        api = RealKubernetesApi(base_url=mock.base_url, token="tok-1",
                                watch_timeout_s=5)
        api._token_path = str(token_file)
        api._token_checked = 0.0
        assert api._bearer() == "tok-1"
        # rotate the file; the refresh window must pick it up
        token_file.write_text("tok-2")
        api._token_checked = 0.0  # force the next check
        assert api._bearer() == "tok-2"
        # a vanished file keeps the last good token
        token_file.unlink()
        api._token_checked = 0.0
        assert api._bearer() == "tok-2"
        # inside the 60s window no re-read happens
        token_file.write_text("tok-3")
        assert api._bearer() == "tok-2"
